"""Command-line interface.

Subcommands cover the reference's executable entry points (SURVEY.md §3):

  demo     — fixed-input mesh export, reproducing the reference demo driver
             (/root/reference/mano_np.py:205-219)
  convert  — asset conversion, reproducing dump_model
             (/root/reference/dump_model.py:46-49) with .npz as the
             canonical output
  animate  — batch-evaluate a pose sequence ([T,16,3] .npy) and dump OBJ
             frames: the offline analogue of the reference's GL viewer loop
             (/root/reference/data_explore.py:8-18)
  render   — rasterize a pose (or pose sequence) to PNG frames / an
             animated GIF with the built-in JAX renderer, replacing the
             reference's external OpenGL viewer dependency
  fit      — recover pose/shape from target vertices or sparse 3D joint
             keypoints (.npy) by Adam or Levenberg-Marquardt; writes a
             .npz checkpoint
  serve-bench — drive the bucketed micro-batching engine (serving/)
             with a synthetic ragged request stream; one JSON line of
             serving metrics (engine-vs-direct ratio, recompiles,
             padding waste, per-bucket latency)
  info     — print an asset's schema summary

Run as ``python -m mano_hand_tpu.cli <subcommand>``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import numpy as np

# The reference demo's hardcoded inputs (mano_np.py:209-216): data constants,
# reproduced so `demo` output is comparable against the reference's hand.obj.
DEMO_POSE_PCA = np.array([
    -0.32322194, 0.740878, -1.182191, 1.51246975, -1.89044963,
    0.68187004, -0.33078079, 0.23475931, -1.43845225,
])
DEMO_SHAPE = np.array([
    -0.33191198, 0.88129797, -1.9995425, -0.79066971, -1.41297644,
    -1.63064562, -1.25495915, -0.61775709, -0.4129301, 0.15526694,
])
DEMO_GLOBAL_ROT = np.array([1.0, 0.0, 0.0])


def _load_params(spec: str, side: str | None = None):
    from mano_hand_tpu.assets import load_model, synthetic_params

    if spec == "synthetic":
        return synthetic_params(seed=0, side=side or "right")
    return load_model(spec, side=side)


def cmd_demo(args) -> int:
    from mano_hand_tpu.models.layer import MANOModel

    params = _load_params(args.asset, args.side)
    model = MANOModel(params, backend=args.backend)
    model.set_params(
        pose_pca=DEMO_POSE_PCA, shape=DEMO_SHAPE, global_rot=DEMO_GLOBAL_ROT
    )
    if str(args.out).lower().endswith(".ply"):
        model.export_ply(args.out)
        print(f"wrote {args.out} (binary PLY), backend={args.backend}")
    else:
        model.export_obj(args.out)
        print(f"wrote {args.out} (+ restpose twin), backend={args.backend}")
    return 0


def cmd_convert(args) -> int:
    from mano_hand_tpu.assets import (
        load_model, save_dumped_pickle, save_npz,
    )

    try:
        params = load_model(args.src, side=args.side)
    except Exception as e:
        print(f"cannot load asset {args.src}: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    note = ""
    dst = Path(args.dst)
    if args.mirror:
        from mano_hand_tpu.assets import mirror_params

        params = mirror_params(params)
        note = f" (mirrored -> {params.side})"
        if (dst.suffix == ".pkl"
                and params.side not in dst.name.lower()):
            # The nine-key dumped-pickle format has no side field; the
            # loader re-infers side from the FILENAME. A mirrored pickle
            # without the side in its name would silently round-trip
            # with the wrong-hand metadata.
            print(f"--mirror to .pkl needs the side in the filename "
                  f"(dumped pickles carry no side field): name it "
                  f"*{params.side}*.pkl or write .npz", file=sys.stderr)
            return 2
    if dst.suffix == ".npz":
        save_npz(params, dst)
    elif dst.suffix == ".pkl":
        save_dumped_pickle(params, dst)
    else:
        print(f"unsupported output format: {dst.suffix}", file=sys.stderr)
        return 2
    print(f"converted {args.src} -> {dst}{note}")
    return 0


def _load_pose_sequence(path: str | None, params) -> np.ndarray:
    """Pose bank -> [T, n_joints, 3]. Accepts [T,16,3], [T,15,3] (zero
    global-rot row prepended, data_explore.py:13 behavior), or a single
    [16,3]/[15,3] pose; None gives one rest-pose frame."""
    if path is None:
        return np.zeros((1, params.n_joints, 3))
    poses = np.load(path)
    if poses.ndim == 2:
        poses = poses[None]
    if poses.shape[-2] == params.n_joints - 1:
        poses = np.concatenate(
            [np.zeros((*poses.shape[:-2], 1, 3)), poses], axis=-2
        )
    return poses


def cmd_animate(args) -> int:
    import jax.numpy as jnp

    from mano_hand_tpu.io.obj import export_obj_sequence
    from mano_hand_tpu.models import core

    params = _load_params(args.asset, args.side).astype(np.float32)
    poses = _load_pose_sequence(args.poses, params)
    if str(args.out).endswith(".glb") and args.skinned:
        # Engine-ready skeletal export: joint hierarchy + LBS weights
        # + the clip as quaternion rotation tracks. Drivable/
        # retargetable after export; plain LBS (pose correctives are
        # not encodable in a glTF skin — the morph path is exact).
        # Only the ONE rest-pose forward runs — the skin carries the
        # animation, so the per-frame batched forward below would be
        # thrown-away work at clip scale.
        from mano_hand_tpu.io.gltf import export_glb_skinned

        rest = core.forward(
            params, jnp.zeros((params.n_joints, 3), jnp.float32),
            jnp.zeros(params.n_shape, jnp.float32),
        )
        path = export_glb_skinned(
            np.asarray(rest.verts), np.asarray(params.faces),
            np.asarray(rest.joints), params.parents,
            np.asarray(params.lbs_weights), args.out,
            pose_frames=poses, fps=args.fps,
        )
        print(f"wrote {poses.shape[0]}-frame skinned GLB to {path}")
        return 0
    shapes = np.zeros((poses.shape[0], params.n_shape))
    out = core.jit_forward_batched(
        params, jnp.asarray(poses, jnp.float32), jnp.asarray(shapes, jnp.float32)
    )
    if str(args.out).endswith(".glb"):
        # One self-contained viewer-ready file: the clip as a morph-target
        # animation (drag into Blender / any glTF viewer and press play).
        from mano_hand_tpu.io.gltf import export_glb

        verts = np.asarray(out.verts)
        path = export_glb(
            verts[0], np.asarray(params.faces), args.out,
            morph_frames=list(verts), fps=args.fps,
        )
        print(f"wrote {poses.shape[0]}-frame animated GLB to {path}")
        return 0
    paths = export_obj_sequence(
        np.asarray(out.verts), np.asarray(params.faces), args.out
    )
    print(f"wrote {len(paths)} frames to {args.out}/")
    return 0


def cmd_render(args) -> int:
    import jax.numpy as jnp

    from mano_hand_tpu.models import core
    from mano_hand_tpu import viz

    params = _load_params(args.asset, args.side).astype(np.float32)
    poses = _load_pose_sequence(args.poses, params)
    shapes = np.zeros((poses.shape[0], params.n_shape))
    out = core.jit_forward_batched(
        params, jnp.asarray(poses, jnp.float32),
        jnp.asarray(shapes, jnp.float32),
    )
    frames = viz.render_sequence(
        np.asarray(out.verts), np.asarray(params.faces),
        height=args.size, width=args.size,
    )
    dst = Path(args.out)
    if dst.suffix == ".avi":
        # The reference's animation demo output format
        # (/root/reference/data_explore.py:17).
        viz.write_avi(frames, dst, fps=args.fps)
        print(f"wrote {dst} ({len(frames)} frames)")
    elif dst.suffix == ".gif":
        viz.write_gif(frames, dst, fps=args.fps)
        print(f"wrote {dst} ({len(frames)} frames)")
    else:
        dst.mkdir(parents=True, exist_ok=True)
        for t, frame in enumerate(frames):
            viz.write_png(frame, dst / f"frame_{t:05d}.png")
        print(f"wrote {len(frames)} PNGs to {dst}/")
    return 0


def _load_init(path, want_trans=False):
    """Warm-start checkpoint -> (init dict, None) or (None, error).

    The dict holds 'pose'/'shape', plus 'trans' when the checkpoint has
    one (a --fit-trans run) AND the new fit wants it — otherwise the
    stale estimate is dropped with a note (the solvers reject unknown
    init keys). One loader for both solvers; leaf shapes (incl. batch
    agreement) are validated by the library entry points.
    """
    from mano_hand_tpu.io.checkpoints import load_arrays

    ck = load_arrays(path)
    missing = {"pose", "shape"} - set(ck)
    if missing:
        return None, (f"--init checkpoint lacks {sorted(missing)} "
                      f"(has {sorted(ck)})")
    init = {"pose": ck["pose"], "shape": ck["shape"]}
    if "trans" in ck:
        if want_trans:
            init["trans"] = ck["trans"]
        else:
            print("note: --init has a trans estimate but --fit-trans "
                  "is off; ignoring it", file=sys.stderr)
    return init, None


def cmd_fit(args) -> int:
    import jax

    from mano_hand_tpu import fitting
    from mano_hand_tpu.io.checkpoints import save_fit_result

    params = _load_params(args.asset, args.side).astype(np.float32)
    tgt_lower = str(args.targets).lower()
    if tgt_lower.endswith((".ply", ".obj")):
        if args.data_term in ("silhouette", "depth"):
            # A mesh/point cloud is not an image; without this the value
            # guard below would emit a nonsense error for vertex
            # coordinates.
            fmt = (".npy/.png" if args.data_term == "silhouette"
                   else ".npy")   # PNG cannot carry meters
            print(f"a .ply/.obj is geometry, not an image: use a {fmt} "
                  f"[H, W] image with --data-term {args.data_term}",
                  file=sys.stderr)
            return 2
        # Scanner/DCC output directly: the vertex cloud (any faces are
        # irrelevant to the ICP data terms, which resample anyway; for
        # --data-term verts an OBJ written by this package or the
        # reference is in vertex correspondence already).
        if tgt_lower.endswith(".obj"):
            from mano_hand_tpu.io.obj import read_obj

            targets = read_obj(args.targets).verts
        else:
            from mano_hand_tpu.io.ply import read_ply

            targets = read_ply(args.targets).verts
    elif str(args.targets).lower().endswith(".png"):
        if args.data_term != "silhouette":
            print("a .png target is a segmentation mask: use "
                  "--data-term silhouette", file=sys.stderr)
            return 2
        try:
            from PIL import Image
        except ImportError:
            print("reading .png masks needs Pillow; save the mask as a "
                  ".npy [H, W] float array in [0, 1] instead",
                  file=sys.stderr)
            return 2
        # Grayscale, normalized to [0, 1] — the range the soft-IoU loss
        # is defined on (the library rejects raw 0/255 by value).
        targets = (
            np.asarray(Image.open(args.targets).convert("L"), np.float32)
            / 255.0
        )
    else:
        targets = np.load(args.targets)  # [V|J, 3|2] or [B, V|J, 3|2]
        if args.data_term == "silhouette":
            targets = np.asarray(targets, np.float32)
            if targets.size and (targets.min() < 0 or targets.max() > 1):
                # Mirror the library's value guard with a CLI-shaped
                # error instead of a traceback.
                print("mask values must be in [0, 1] (got "
                      f"[{targets.min():g}, {targets.max():g}]); divide "
                      "a 0/255 mask by 255", file=sys.stderr)
                return 2
        elif args.data_term == "depth":
            targets = np.asarray(targets, np.float32)
            if targets.size and targets.ndim >= 2 and not (
                (targets > 0).any(axis=(-2, -1)).all()
            ):
                # Per image: one dropped-out frame in a batch would fit
                # to nothing and report its init as converged.
                print("depth target has image(s) with no valid "
                      "(positive) pixels — depth is view-space meters, "
                      "<= 0 or NaN = no reading", file=sys.stderr)
                return 2
    if args.data_term not in ("joints", "keypoints2d"):
        # Name the real conflict for BOTH keypoint flags here — sending
        # the user to --tips from the openpose check would ping-pong them
        # straight into this error.
        if args.tips:
            print("--tips only applies to --data-term joints/keypoints2d",
                  file=sys.stderr)
            return 2
        if args.keypoint_order != "mano":
            print("--keypoint-order only applies to --data-term "
                  "joints/keypoints2d", file=sys.stderr)
            return 2
    try:
        from mano_hand_tpu.models.core import resolve_tip_ids

        tips = resolve_tip_ids(args.tips or None, params.n_verts)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    n_kp = params.n_joints + (len(tips) if tips else 0)
    if args.keypoint_order == "openpose" and n_kp != 21:
        print("--keypoint-order openpose is the 21-point convention; "
              "pass --tips smplx|manopth", file=sys.stderr)
        return 2
    kp_kw = {}
    if args.data_term in ("joints", "keypoints2d"):
        kp_kw = dict(tip_vertex_ids=tips, keypoint_order=args.keypoint_order)
    if args.data_term in ("silhouette", "depth"):
        # Masks/depth maps are [H, W] / [B, H, W] images, not
        # [rows, coords] arrays. A zero-size image has a constant loss —
        # zero gradients, and the INIT would be saved as a "successful"
        # fit (same class the point-term empty check keeps out).
        if targets.ndim not in (2, 3) or 0 in targets.shape:
            print(f"image targets must be non-empty [H, W] or [B, H, W] "
                  f"for --data-term {args.data_term}, got "
                  f"{targets.shape}", file=sys.stderr)
            return 2
    else:
        if args.data_term == "keypoints2d":
            want = (n_kp, 2)
        elif args.data_term == "joints":
            want = (n_kp, 3)
        elif args.data_term in ("points", "point_to_plane"):
            want = (None, 3)  # any number of scan points, 3D
        else:
            want = (params.n_verts, 3)
        rows_ok = (
            targets.ndim >= 2
            and (targets.shape[-2] == want[0] if want[0] is not None
                 else targets.shape[-2] > 0)  # empty scan would fit to NaN
        )
        if (targets.ndim not in (2, 3) or targets.shape[-1] != want[1]
                or not rows_ok):
            rows = "N" if want[0] is None else str(want[0])
            print(
                f"targets must be [{rows}, {want[1]}] or "
                f"[B, {rows}, {want[1]}] for --data-term {args.data_term}, "
                f"got {targets.shape}",
                file=sys.stderr,
            )
            return 2
    if args.heatmap and (args.data_term != "verts" or targets.ndim != 2):
        # The heatmap colors per-vertex errors against the target, which
        # needs known correspondence and ONE problem.
        print("--heatmap requires --data-term verts with a single "
              "[V, 3] target", file=sys.stderr)
        return 2
    if not 0.0 <= args.trim < 1.0:
        print(f"--trim must be in [0, 1), got {args.trim}", file=sys.stderr)
        return 2
    if args.trim and args.data_term not in ("points", "point_to_plane"):
        # Checked BEFORE any solver resolution: naming only the solver
        # here would ping-pong the user into the opposite error.
        print("--trim only applies to --data-term points/point_to_plane",
              file=sys.stderr)
        return 2
    if (args.robust_weights != "none"
            and args.data_term not in ("points", "point_to_plane")):
        print("--robust-weights only applies to --data-term "
              "points/point_to_plane", file=sys.stderr)
        return 2
    # Pose spaces LM cannot optimize need the Adam solver — ONE
    # definition, shared with the explicit-LM guard below, so a future
    # pose space fails safe instead of silently routing to LM. LM
    # handles "aa" (its native parameterization) and "pca" (GN in the
    # truncated space, fit_lm pose_space="pca"); an UNSET solver still
    # resolves pca to adam (priors/6d interplay live there) — pca-LM is
    # an explicit `--solver lm` choice.
    needs_adam = args.pose_space not in (None, "aa", "pca")
    explicit_pca_lm = args.pose_space == "pca" and args.solver == "lm"
    if args.solver is None:
        if needs_adam or args.pose_space == "pca":
            args.solver = "adam"
        else:
            args.solver = ("lm" if args.data_term
                           in ("verts", "point_to_plane") else "adam")
    steps = (
        args.steps if args.steps is not None
        else (25 if args.solver == "lm" else 200)
    )
    if args.conf is not None and args.data_term != "keypoints2d":
        # Mirror the library-level guard (solvers reject conf/camera
        # outside keypoints2d) instead of silently dropping the file.
        print("--conf only applies to --data-term keypoints2d",
              file=sys.stderr)
        return 2
    if args.data_term not in ("silhouette", "depth"):
        # Refuse rather than silently drop (the --tips/--trim pattern):
        # these flags change the fit ONLY through the rasterized paths.
        for flag, val in (("--camera-scale", args.camera_scale),
                          ("--camera-rot", args.camera_rot),
                          ("--sil-sigma", args.sil_sigma)):
            if val is not None:
                print(f"{flag} only applies to --data-term "
                      "silhouette/depth", file=sys.stderr)
                return 2
    else:
        if args.data_term == "depth" and (
            args.camera_scale is not None or args.camera_rot
        ):
            # Weak perspective has no meaningful depth axis — a depth
            # image only makes sense under a real (pinhole) projection.
            print("--camera-scale/--camera-rot are the weak-perspective "
                  "silhouette flags; --data-term depth uses the default "
                  "pinhole camera or --camera-k", file=sys.stderr)
            return 2
        # Degenerate-value guards (same class as the empty-mask check):
        # scale 0 projects everything to one point (constant image, zero
        # gradients, the init saved as a "fit"); sigma 0 divides by zero
        # in the rasterizer and negative sigma inverts inside/outside.
        if args.camera_scale is not None and args.camera_scale <= 0:
            print(f"--camera-scale must be > 0, got {args.camera_scale}",
                  file=sys.stderr)
            return 2
        if args.sil_sigma is not None and args.sil_sigma <= 0:
            print(f"--sil-sigma must be > 0, got {args.sil_sigma}",
                  file=sys.stderr)
            return 2
    intr_cam = None
    if args.camera_k:
        # Dataset calibration: K entries + image size. Takes precedence
        # over the synthetic-camera flags; keypoint targets are then
        # PIXEL coordinates (the annotation convention) and are
        # converted once via pixels_to_ndc. Validated BEFORE solver
        # resolution so e.g. a verts fit (LM default) still refuses it.
        if args.data_term not in ("keypoints2d", "silhouette", "depth"):
            print("--camera-k only applies to --data-term "
                  "keypoints2d/silhouette/depth", file=sys.stderr)
            return 2
        try:
            fx, fy, cx, cy = (float(x) for x in args.camera_k.split(","))
            w_str, _, h_str = (args.camera_size or "").partition("x")
            cam_w, cam_h = int(w_str), int(h_str)
        except ValueError as e:
            print("--camera-k must be 'fx,fy,cx,cy' with "
                  f"--camera-size 'WxH': {e}", file=sys.stderr)
            return 2
        from mano_hand_tpu.viz.camera import from_intrinsics

        try:
            intr_cam = from_intrinsics(
                [[fx, 0, cx], [0, fy, cy], [0, 0, 1]], cam_w, cam_h,
            )
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
    elif args.camera_size is not None:
        print("--camera-size only applies with --camera-k",
              file=sys.stderr)
        return 2
    if args.solver == "lm" and (args.pose_prior != "l2"
                                or args.pose_prior_weight is not None):
        # Either prior flag under LM is a contradiction, not a preference
        # — silently dropping a requested regularization weight would
        # return a different fit than the user asked for.
        print("--pose-prior/--pose-prior-weight require --solver adam "
              "(LM regularizes via its Tikhonov shape rows)",
              file=sys.stderr)
        return 2
    if args.solver == "lm" and args.joint_limits is not None:
        print("--joint-limits requires --solver adam (the hinge prior "
              "is a first-order energy term)", file=sys.stderr)
        return 2
    if args.joint_limit_weight is not None and args.joint_limits is None:
        print("--joint-limit-weight without --joint-limits does nothing; "
              "pass the bounds file", file=sys.stderr)
        return 2
    if args.restarts:
        if args.init:
            print("--restarts owns the initialization (zero + Kabsch + "
                  "sampled seeds); drop --init", file=sys.stderr)
            return 2
        if args.restarts < 1:
            print(f"--restarts must be >= 1, got {args.restarts}",
                  file=sys.stderr)
            return 2
    if args.solver == "lm":
        if args.lr is not None:
            print("note: --lr only applies to --solver adam; ignored",
                  file=sys.stderr)
        if args.data_term in ("keypoints2d", "silhouette", "depth"):
            print(f"--data-term {args.data_term} requires --solver adam",
                  file=sys.stderr)
            return 2
        if args.robust != "none":
            # Materially changes the result — refuse rather than note:
            # the GN residual has no robustifier.
            print("--robust requires --solver adam", file=sys.stderr)
            return 2
        lm_kw = {}
        if args.data_term in ("joints", "points", "point_to_plane"):
            # LM's Tikhonov rows stand in for the Adam path's shape prior
            # (16 joints — or a partial scan — underdetermine shape).
            lm_kw = dict(
                data_term=args.data_term,
                shape_weight=(0.1 if args.shape_prior is None
                              else args.shape_prior),
            )
        elif args.shape_prior is not None:
            print("note: --shape-prior only applies to --solver adam or "
                  "--data-term joints/points/point_to_plane; ignored",
                  file=sys.stderr)
        if args.fit_trans:
            lm_kw["fit_trans"] = True
        if args.init:
            init, err = _load_init(args.init, want_trans=args.fit_trans)
            if err:
                print(err, file=sys.stderr)
                return 2
            lm_kw["init"] = init
        if args.trim:
            lm_kw["trim_fraction"] = args.trim
        if args.robust_weights != "none":
            lm_kw["robust_weights"] = args.robust_weights
        if needs_adam:
            # Only reachable with an EXPLICIT --solver lm (an unset solver
            # resolves to adam for these spaces): a contradiction, not a
            # preference — refuse rather than silently drop it. 'aa' is
            # LM's native parameterization and 'pca' its GN-in-the-
            # truncated-space mode; both pass through.
            print(f"--pose-space {args.pose_space} requires --solver adam "
                  "(LM optimizes axis-angle or PCA coefficients)",
                  file=sys.stderr)
            return 2
        if explicit_pca_lm:
            if args.restarts:
                # fit_restarts samples axis-angle inits (restarts.py
                # rejects pca): name the conflict here with the fix.
                print("--restarts with --solver lm samples axis-angle "
                      "inits; drop --pose-space pca or drop --restarts",
                      file=sys.stderr)
                return 2
            if lm_kw.get("init"):
                # JSON inits ship pose/shape arrays; the pca
                # parameterization expects {global_rot, pca, shape}.
                ik = set(lm_kw["init"])
                if not ik <= {"global_rot", "pca", "shape"}:
                    print("--init for --pose-space pca LM must hold "
                          "global_rot/pca/shape keys, got "
                          f"{sorted(ik)}", file=sys.stderr)
                    return 2
            lm_kw["pose_space"] = "pca"  # library-default n_pca (full)
        if args.restarts:
            try:
                res, _losses = fitting.fit_restarts(
                    params, targets, n_restarts=args.restarts,
                    solver="lm", n_steps=steps, **lm_kw, **kp_kw)
            except ValueError as e:   # e.g. batched targets
                print(f"--restarts: {e}", file=sys.stderr)
                return 2
        else:
            res = fitting.fit_lm(params, targets, n_steps=steps, **lm_kw,
                                 **kp_kw)
    else:
        if args.trim:
            print("--trim requires --solver lm (the Adam chamfer path "
                  "uses --robust huber instead)", file=sys.stderr)
            return 2
        if args.robust_weights != "none":
            print("--robust-weights requires --solver lm (the Adam "
                  "chamfer path uses --robust huber instead)",
                  file=sys.stderr)
            return 2
        if args.data_term == "point_to_plane":
            # The Adam path has no normal-distance residual; the GN
            # solver owns this polish stage. Name the FULL conflict when
            # a pose space forced the adam resolution — "use --solver lm"
            # alone would send the user into the opposite error.
            if needs_adam:
                print("--data-term point_to_plane is LM-only and LM "
                      "optimizes axis-angle or PCA coefficients: it "
                      f"cannot combine with --pose-space {args.pose_space}"
                      "; drop the pose space or use --data-term points",
                      file=sys.stderr)
            elif args.pose_space == "pca":
                # Unset solver resolved pca->adam; the combination IS
                # available, but only as an explicit LM choice.
                print("--data-term point_to_plane requires --solver lm; "
                      "pass --solver lm explicitly to combine it with "
                      "--pose-space pca", file=sys.stderr)
            else:
                print("--data-term point_to_plane requires --solver lm",
                      file=sys.stderr)
            return 2
        # Shape is weakly observable from 16 joints; regularize it
        # (unless the user set an explicit weight). A mask observes shape
        # only through the outline area — hold it near zero by default.
        shape_prior = (
            args.shape_prior if args.shape_prior is not None
            else (0.0 if args.data_term == "verts"
                  else 1.0 if args.data_term in ("silhouette", "depth")
                  else 1e-3)
        )
        kp2d = {}
        default_lr = 0.05
        if args.data_term == "silhouette":
            if args.robust != "none":
                print("--robust does not apply to --data-term silhouette "
                      "(the IoU is already bounded per image)",
                      file=sys.stderr)
                return 2
            if args.camera_eye is not None or args.focal is not None:
                # Refuse rather than silently drop (same contract as the
                # depth branch): these pinhole flags LOOK applicable but
                # the silhouette camera is weak-perspective
                # (--camera-scale/--camera-rot) or --camera-k only.
                print("--camera-eye/--focal apply to keypoints2d; "
                      "--data-term silhouette uses a weak-perspective "
                      "camera (--camera-scale/--camera-rot) or --camera-k",
                      file=sys.stderr)
                return 2
            if intr_cam is not None:
                if args.camera_scale is not None or args.camera_rot:
                    print("--camera-scale/--camera-rot conflict with "
                          "--camera-k (the calibration IS the camera)",
                          file=sys.stderr)
                    return 2
                if targets.shape[-2:] != (intr_cam.height,
                                          intr_cam.width):
                    # Both sides HxW so a transposed mask reads as the
                    # mismatch it is.
                    print(f"mask resolution {targets.shape[-2]}x"
                          f"{targets.shape[-1]} (HxW) must match "
                          f"--camera-size {intr_cam.height}x"
                          f"{intr_cam.width} (HxW)",
                          file=sys.stderr)
                    return 2
                sil_camera = intr_cam
            else:
                from mano_hand_tpu.viz.camera import (
                    WeakPerspectiveCamera, view_rotation,
                )

                try:
                    rot = [float(x)
                           for x in (args.camera_rot or "0,0,0").split(",")]
                    if len(rot) != 3:
                        raise ValueError(
                            f"need 3 components, got {len(rot)}"
                        )
                except ValueError as e:
                    print(f"--camera-rot must be 'x,y,z' axis-angle: {e}",
                          file=sys.stderr)
                    return 2
                # Weak perspective by design: under a pinhole camera a
                # mask fit inflates the hand toward the lens (measured,
                # see docs/api.md); the scaled-orthographic model removes
                # that axis. (A REAL calibration via --camera-k is the
                # exception: its depth is meaningful, trust it.)
                sil_camera = WeakPerspectiveCamera(
                    rot=view_rotation(rot),
                    scale=(3.0 if args.camera_scale is None
                           else args.camera_scale),
                )
            # Translation is the one thing an outline observes strongly
            # — always fit it.
            default_lr = 0.01
            kp2d = dict(
                camera=sil_camera,
                fit_trans=True,
                sil_sigma=(1.0 if args.sil_sigma is None
                           else args.sil_sigma),
            )
        if args.data_term == "depth":
            # Depth needs a REAL projection (weak perspective has no
            # depth axis): the dataset calibration when given, else the
            # default pinhole framing. One depth image observes full 3D
            # translation — always fit it.
            if args.camera_eye is not None or args.focal is not None:
                # Refuse rather than silently drop: these pinhole flags
                # LOOK applicable here but the depth camera is the
                # default framing or --camera-k only.
                print("--camera-eye/--focal apply to keypoints2d; "
                      "--data-term depth uses the default pinhole "
                      "camera or --camera-k", file=sys.stderr)
                return 2
            if intr_cam is not None:
                depth_camera = intr_cam
                if targets.shape[-2:] != (intr_cam.height,
                                          intr_cam.width):
                    print(f"depth resolution {targets.shape[-2]}x"
                          f"{targets.shape[-1]} (HxW) must match "
                          f"--camera-size {intr_cam.height}x"
                          f"{intr_cam.width} (HxW)", file=sys.stderr)
                    return 2
            else:
                from mano_hand_tpu.viz.camera import default_hand_camera

                depth_camera = default_hand_camera()
            default_lr = 0.01
            kp2d = dict(
                camera=depth_camera,
                fit_trans=True,
                sil_sigma=(1.0 if args.sil_sigma is None
                           else args.sil_sigma),
            )
        if args.data_term == "keypoints2d":
            conf = None
            if args.conf:
                conf = np.load(args.conf).astype(np.float32)
                want_conf = targets.shape[:-1]
                if conf.shape not in (want_conf, want_conf[-1:]):
                    print(f"--conf must be {list(want_conf)} (or "
                          f"[{want_conf[-1]}] shared) to match targets "
                          f"{targets.shape}, got {conf.shape}",
                          file=sys.stderr)
                    return 2
            if intr_cam is not None:
                if args.camera_eye is not None or args.focal is not None:
                    # Refuse rather than silently drop (the file-wide
                    # pattern): the calibration IS the camera.
                    print("--camera-eye/--focal conflict with --camera-k",
                          file=sys.stderr)
                    return 2
                # Dataset convention: the .npy targets are PIXEL
                # coordinates on the calibrated image; convert once.
                targets = np.asarray(intr_cam.pixels_to_ndc(
                    targets.astype(np.float32)
                ))
                kp_camera = intr_cam
            else:
                from mano_hand_tpu.viz.camera import look_at

                try:
                    eye = [float(x) for x in
                           (args.camera_eye or "0,0,-0.75").split(",")]
                    if len(eye) != 3:
                        raise ValueError(
                            f"need 3 components, got {len(eye)}"
                        )
                except ValueError as e:
                    print(f"--camera-eye must be 'x,y,z': {e}",
                          file=sys.stderr)
                    return 2
                kp_camera = look_at(
                    eye=eye,
                    focal=2.2 if args.focal is None else args.focal,
                )
            # 2D data is depth-blind: fit a global translation, use the
            # better-conditioned PCA pose space, a mild pose prior, and a
            # gentler step (the defaults the library-level tests validate).
            default_lr = 0.02
            kp2d = dict(
                camera=kp_camera,
                target_conf=conf,
                fit_trans=True,
                n_pca=15,
            )
        # One decision point for the effective pose space: the user's
        # explicit choice, else pca for depth-blind 2D keypoints, else aa
        # (incl. silhouette — the mask defaults are validated in aa).
        pose_space = args.pose_space or (
            "pca" if args.data_term == "keypoints2d" else "aa"
        )
        if args.pose_prior == "mahalanobis" and pose_space == "6d":
            print("--pose-prior mahalanobis needs axis-angle statistics: "
                  "use --pose-space aa or pca", file=sys.stderr)
            return 2
        joint_limits = None
        if args.joint_limits is not None:
            if pose_space == "6d":
                print("--joint-limits are axis-angle bounds: use "
                      "--pose-space aa or pca", file=sys.stderr)
                return 2
            try:
                with np.load(args.joint_limits) as lim:
                    if "lo" not in lim or "hi" not in lim:
                        raise ValueError(
                            f"needs keys lo/hi, has {sorted(lim.files)}")
                    lo, hi = lim["lo"], lim["hi"]
            except Exception as e:  # unreadable/malformed file
                print(f"--joint-limits {args.joint_limits}: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
                return 2
            n_dof = (params.n_joints - 1) * 3
            if lo.shape != (n_dof,) or hi.shape != (n_dof,):
                print(f"--joint-limits lo/hi must be [{n_dof}]; got "
                      f"{lo.shape}/{hi.shape}", file=sys.stderr)
                return 2
            if not (np.asarray(lo) <= np.asarray(hi)).all():
                print("--joint-limits has lo > hi entries — swapped "
                      "bounds would wall off the whole axis",
                      file=sys.stderr)
                return 2
            joint_limits = (lo, hi)
        # Default pose-prior weight: the 2D term is depth-blind and always
        # needs one; elsewhere the data-driven prior defaults on gently
        # when selected, and the isotropic prior stays off.
        pose_prior_weight = args.pose_prior_weight
        if pose_prior_weight is None:
            if args.data_term == "keypoints2d":
                pose_prior_weight = 1e-4
            elif args.data_term in ("silhouette", "depth"):
                # A single image cannot pin articulation: hold the pose
                # hard and let translation do the observable work (the
                # weight the image-recovery tests validate). Lower it
                # when combining with more views or keypoints.
                pose_prior_weight = 1.0
            elif args.pose_prior == "mahalanobis":
                pose_prior_weight = 1e-3
            else:
                pose_prior_weight = 0.0
        init = None
        if args.init:
            if pose_space != "aa":
                # fit() warm-starts in the ACTIVE parameterization, and
                # checkpoints store axis-angle pose.
                print("--init requires the axis-angle pose space "
                      f"(active: {pose_space})", file=sys.stderr)
                return 2
            init, err = _load_init(
                args.init,
                want_trans=args.fit_trans or kp2d.get("fit_trans", False))
            if err:
                print(err, file=sys.stderr)
                return 2
        adam_kw = dict(
            n_steps=steps,
            lr=default_lr if args.lr is None else args.lr,
            data_term=args.data_term,
            shape_prior_weight=shape_prior,
            pose_space=pose_space,
            pose_prior=args.pose_prior,
            pose_prior_weight=pose_prior_weight,
            joint_limits=joint_limits,
            joint_limit_weight=(1.0 if args.joint_limit_weight is None
                                else args.joint_limit_weight),
            robust=args.robust, robust_scale=args.robust_scale,
            **kp2d,
            **kp_kw,
        )
        # The 2D/image paths force translation on via their own dicts
        # (kp2d/silhouette/depth); setdefault keeps that while --fit-trans
        # turns it on for the 3D terms.
        adam_kw.setdefault("fit_trans", args.fit_trans)
        if args.restarts:
            if pose_space != "aa":
                # fit_restarts samples axis-angle seeds by design.
                print(f"--restarts requires the axis-angle pose space "
                      f"(active: {pose_space})", file=sys.stderr)
                return 2
            try:
                res, _losses = fitting.fit_restarts(
                    params, targets, n_restarts=args.restarts,
                    solver="adam", **adam_kw)
            except ValueError as e:   # e.g. batched targets
                print(f"--restarts: {e}", file=sys.stderr)
                return 2
        else:
            res = fitting.fit(params, targets, init=init, **adam_kw)
    jax.block_until_ready(res.pose)
    path = save_fit_result(res, args.out)
    final = float(np.max(np.asarray(res.final_loss)))
    print(f"fit ({args.solver}, {steps} steps) -> {path} "
          f"(worst final loss {final:.3e})")
    if args.heatmap:
        from mano_hand_tpu.models import core
        from mano_hand_tpu.viz import error_colormap, render_mesh
        from mano_hand_tpu.viz.png import write_png

        import jax.numpy as jnp

        fitted = core.forward(
            params, jnp.asarray(res.pose), jnp.asarray(res.shape)
        ).verts
        if getattr(res, "trans", None) is not None:
            fitted = fitted + jnp.asarray(res.trans)
        errs = jnp.linalg.norm(
            fitted - jnp.asarray(targets, jnp.float32), axis=-1
        )
        colors = error_colormap(errs)
        if str(args.heatmap).lower().endswith(".glb"):
            # A 3D-inspectable heatmap: the fitted mesh with COLOR_0
            # vertex colors, orbitable in any glTF viewer.
            from mano_hand_tpu.io.gltf import export_glb

            export_glb(np.asarray(fitted), np.asarray(params.faces),
                       args.heatmap, vertex_colors=np.asarray(colors))
        else:
            img = render_mesh(fitted, params.faces, vertex_colors=colors)
            write_png(np.asarray(img), args.heatmap)
        print(f"error heatmap (max {float(errs.max()) * 1e3:.2f} mm) -> "
              f"{args.heatmap}")
    return 0


def cmd_export_aot(args) -> int:
    """Serialize the compiled forward as a self-contained serving artifact."""
    from mano_hand_tpu.io.export_aot import save_forward

    params = _load_params(args.asset, args.side)
    params = params.astype(np.float32)
    path = save_forward(
        params, args.out,
        batch=args.batch if args.batch else "b",
        tip_vertex_ids=args.tips or None,
        keypoint_order=args.keypoint_order,
        platforms=tuple(args.platforms.split(",")) if args.platforms
        else None,
    )
    import os

    print(f"exported AOT forward -> {path} ({os.path.getsize(path)} bytes; "
          "params baked in; consumer needs only jax + "
          "mano_hand_tpu.io.export_aot.load_forward)")
    return 0


def cmd_serve_bench(args) -> int:
    """Drive the serving engine with a synthetic ragged request stream and
    print ONE JSON line of serving metrics (engine vs direct-jit
    throughput, recompiles, padding waste, per-bucket latency). The
    protocol itself lives in ``serving.measure.serve_bench_run`` —
    shared with bench.py's config7 leg so the two cannot diverge.
    ``--chaos`` injects a deterministic fault plan under supervised
    dispatch (``runtime/``), or runs the full recovery drill with
    ``--chaos drill``; ``--subjects N`` switches to the mixed-subject
    coalescing protocol (bench.py config9's
    ``serving.measure.coalesce_bench_run``); ``--overload`` runs the
    overload/saturation drill (bench.py config10's
    ``serving.measure.overload_drill_run``); ``--cold-start`` runs the
    restart drill against a persistent ``--aot-dir`` (bench.py
    config11's ``serving.measure.cold_start_drill_run``); ``--trace
    DIR`` (PR 8) spans every request through an ``obs.Tracer`` and
    exports the Chrome-trace timeline + final flight record into DIR
    for `mano trace-report`; ``--metrics DIR`` (PR 9) registers the
    engine's telemetry on an ``obs.MetricsRegistry`` and persists the
    final scrape (metrics.json + Prometheus text) for `mano status
    --metrics-dir` — stdout stays EXACTLY one JSON line (progress and
    incidents ride stderr / the trace dir)."""
    import os

    import jax

    from mano_hand_tpu.obs import log as obs_log
    from mano_hand_tpu.serving.measure import serve_bench_run

    # Progress rides the leveled stderr logger (PR 8): pinned to
    # "info" here — an interactive bench wants its phases visible —
    # while stdout remains the one-JSON-line artifact channel.
    log = obs_log.get_logger("serve-bench", level="info").info

    if args.chaos != "drill":
        # The drill fixes its own protocol sizes; these knobs shape the
        # serve_bench_run stream only.
        if args.requests < 1:
            print(f"--requests must be >= 1, got {args.requests}",
                  file=sys.stderr)
            return 2
        if args.min_rows < 1 or args.max_rows < args.min_rows:
            print(f"need 1 <= --min-rows <= --max-rows, got "
                  f"({args.min_rows}, {args.max_rows})", file=sys.stderr)
            return 2
        if args.max_rows > args.max_bucket:
            print(f"--max-rows {args.max_rows} exceeds --max-bucket "
                  f"{args.max_bucket}", file=sys.stderr)
            return 2
    params = _load_params(args.asset, args.side).astype(np.float32)

    # Deadline watchdog for device backends (CLAUDE.md): a tunnel drop
    # mid-dispatch hangs the engine's dispatcher inside a C-level PJRT
    # RPC where neither signals nor thread joins can reach it — SIGTERM
    # is insufficient because Python handlers run only on the MAIN
    # thread between bytecodes, which a thread wedged in a C call never
    # reaches; only a hard exit from a still-running daemon THREAD
    # lands (the unified runtime.supervise.Watchdog, shared with
    # bench.py). Armed BEFORE any jax backend call: resolving the
    # backend itself initializes PJRT in-process and hangs on a wedged
    # tunnel, so an auto default (--emit-by unset) arms provisionally at
    # 900 s and is DISARMED below once the backend resolves to cpu. The
    # JSON line stays valid either way (null + error on the kill path).
    from mano_hand_tpu.runtime.supervise import Watchdog

    tracer = None
    if args.trace:
        # One tracer spans the whole invocation (PR 8); the protocols
        # below pass it into their engines, and the timeline + final
        # flight record are exported into --trace DIR before the JSON
        # line prints.
        from mano_hand_tpu.obs import Tracer

        tracer = Tracer()

    metrics_reg = None
    if args.metrics:
        # The metrics registry (PR 9) exports the LIVE engine's
        # telemetry — ServingCounters/load()/tracer as pull collectors
        # — so it composes with the default protocol (optionally under
        # a --chaos plan), whose engine registers itself. The drill
        # protocols fix their own engines internally; refuse rather
        # than silently export an empty registry (the flag-guard
        # convention).
        if (args.overload or args.cold_start or args.subjects > 0
                or args.streams > 0 or args.chaos == "drill"):
            print("--metrics composes only with the default protocol "
                  "(optionally under a --chaos plan); the drill "
                  "protocols (--overload/--cold-start/--subjects/"
                  "--streams/--chaos drill) fix their own engines and "
                  "export nothing into a caller registry",
                  file=sys.stderr)
            return 2
        from mano_hand_tpu.obs import MetricsRegistry

        metrics_reg = MetricsRegistry()

    emit_by = 900.0 if args.emit_by < 0 else args.emit_by

    def _hard_exit(cause: str) -> None:
        # The one-JSON-line artifact prints FIRST: --emit-by exists so
        # the driver finds stdout populated AT the deadline, and
        # nothing — not even the flight-recorder dump — may delay it.
        print(json.dumps({
            "engine_evals_per_sec": None,
            "error": f"serve-bench {cause} — hung device RPC (tunnel "
                     "drop mid-dispatch?)",
        }), flush=True)
        if tracer is not None:
            # The flight recorder's reason to exist: the timeline up to
            # the wedge lands on disk before the process dies (the
            # watchdog already stamped the kill incident onto it). But
            # the dump must never cost the kill itself: the same
            # incident that wedged the dispatcher can wedge I/O too
            # (try/except catches exceptions, not hangs), so the write
            # runs on a disposable daemon thread with a BOUNDED join —
            # the call_with_deadline reasoning — and os._exit lands
            # regardless.
            def dump():
                try:
                    from mano_hand_tpu.obs import write_trace_dir

                    write_trace_dir(tracer, args.trace,
                                    reason="watchdog_kill")
                except Exception:  # noqa: BLE001 — best-effort dump
                    pass

            import threading

            t = threading.Thread(target=dump, name="trace-dump",
                                 daemon=True)
            t.start()
            t.join(10.0)
        os._exit(3)

    wd = Watchdog(_hard_exit, deadline_s=emit_by or None,
                  name="serve-bench-watchdog", tracer=tracer).start()
    if args.emit_by < 0 and jax.default_backend() == "cpu":
        wd.disarm()  # auto mode: no tunnel to guard against on cpu

    def export_trace(out: dict) -> None:
        """Drop the Chrome-trace timeline + final flight record into
        --trace DIR and note the paths in the artifact. A full or
        read-only trace dir must not discard a COMPLETED run: the
        failure is recorded in the artifact and the one JSON line
        still prints (the FlightRecorder disk-failure rule)."""
        if tracer is None:
            return
        try:
            from mano_hand_tpu.obs import write_trace_dir

            out["trace_export"] = write_trace_dir(tracer, args.trace,
                                                  reason="run_complete")
        except OSError as e:
            out["trace_export"] = {
                "error": f"{type(e).__name__}: {e} (trace dir "
                         f"{args.trace!r} unwritable; the run's "
                         "metrics above are unaffected)"}

    if args.cold_start:
        # The cold-start/restart drill (the same protocol as bench.py
        # config11: serving/measure.py:cold_start_drill_run — lattice
        # bake, mid-traffic kill, zero-compile restore, damage
        # injections, hang-composed boot), one JSON line of drill
        # metrics, judged by scripts/bench_report.py.
        if (args.chaos or args.subjects > 0 or args.overload
                or args.streams > 0 or args.deadline_s is not None):
            # The flag-guard convention (PR 4/5): the drill fixes its
            # own protocol — its own chaos hang leg, its own engines,
            # its own deadlines — refuse rather than silently not run
            # what the caller asked for.
            print("--cold-start fixes its own protocol and does not "
                  "compose with --chaos, --subjects, --overload, "
                  "--streams, or --deadline-s", file=sys.stderr)
            return 2
        if not args.aot_dir:
            # Refuse the aot-dir-less invocation by name: the drill's
            # whole subject is the persistent artifact directory a
            # restart reuses — defaulting to a temp dir would measure
            # a lattice no real restart could ever hit.
            print("--cold-start requires --aot-dir (the executable "
                  "lattice and SubjectTable checkpoint live there; "
                  "without it there is nothing for a restart to "
                  "restore from)", file=sys.stderr)
            return 2
        from mano_hand_tpu.serving.measure import cold_start_drill_run

        out = cold_start_drill_run(
            params, aot_dir=args.aot_dir, seed=args.seed,
            tracer=tracer, log=log)
        out["backend"] = jax.default_backend()
        export_trace(out)
        print(json.dumps(out))
        return 0

    if args.streams > 0:
        # The streaming-session drill (the same protocol as bench.py
        # config15: serving/measure.py:stream_drill_run — N concurrent
        # per-user tracking sessions, warm-started frozen-shape fits,
        # gathered tier-0 dispatch, a mid-drill chaos plan), one JSON
        # line of drill metrics, judged by scripts/bench_report.py.
        if (args.chaos or args.subjects > 0 or args.overload
                or args.cold_start or args.aot_dir
                or args.deadline_s is not None):
            # The flag-guard convention: the drill fixes its own
            # protocol (its own chaos schedule, supervised policy, and
            # per-frame deadlines) — refuse rather than silently not
            # run what the caller asked for.
            print("--streams fixes its own protocol and does not "
                  "compose with --chaos, --subjects, --overload, "
                  "--cold-start, --aot-dir, or --deadline-s",
                  file=sys.stderr)
            return 2
        from mano_hand_tpu.serving.measure import stream_drill_run

        out = stream_drill_run(
            params, streams=args.streams, seed=args.seed,
            tracer=tracer, log=log)
        out["backend"] = jax.default_backend()
        export_trace(out)
        print(json.dumps(out))
        return 0

    if args.overload:
        # The overload/saturation drill (the same protocol as bench.py
        # config10: serving/measure.py:overload_drill_run — bounded
        # admission + per-request deadlines + priority shedding at N x
        # the measured service rate), one JSON line of drill metrics,
        # judged by scripts/bench_report.py.
        if (args.chaos or args.subjects > 0 or args.aot_dir
                or args.deadline_s is not None):
            # Same policy as the other composition guards: the drill
            # fixes its own protocol (its own chaos saturation plan,
            # its own bounded engine, its own request TTL) — refuse
            # rather than silently not run what the caller asked for
            # (--deadline-s is the --chaos per-batch knob; the drill's
            # request TTL is a protocol constant).
            print("--overload fixes its own protocol and does not "
                  "compose with --chaos, --subjects, --aot-dir, or "
                  "--deadline-s", file=sys.stderr)
            return 2
        from mano_hand_tpu.serving.measure import overload_drill_run

        out = overload_drill_run(
            params, saturation=args.overload_saturation, seed=args.seed,
            tracer=tracer, log=log)
        out["backend"] = jax.default_backend()
        export_trace(out)
        print(json.dumps(out))
        return 0

    if args.chaos == "drill":
        # The full fault-recovery drill (the same protocol as bench.py
        # config7_recovery): every fault class + recovery, one JSON
        # line of drill metrics, judged by scripts/bench_report.py.
        if args.subjects > 0:
            # Same policy as the --aot-dir guard below: refuse rather
            # than silently not run the protocol the caller asked for.
            print("--subjects does not compose with --chaos drill (the "
                  "drill fixes its own protocol, which already drives "
                  "mixed-subject pose-only streams); use --subjects "
                  "with a custom --chaos plan instead", file=sys.stderr)
            return 2
        from mano_hand_tpu.serving.measure import recovery_drill_run

        # The drill fixes its own protocol sizes (its request stream
        # needs a largest bucket >= 8); only the deadline is tunable.
        kw = ({} if args.deadline_s is None
              else {"deadline_s": args.deadline_s})
        out = recovery_drill_run(
            params, max_bucket=8, seed=args.seed,
            tracer=tracer, log=log, **kw)
        out["backend"] = jax.default_backend()
        export_trace(out)
        print(json.dumps(out))
        return 0
    policy = None
    if args.chaos:
        # A custom fault schedule under supervised dispatch: the plan
        # wraps the PRIMARY executables; the breaker's probe always
        # answers True (the fault is simulated, there is no real
        # outage to wait out) so the run measures the engine's reaction
        # to the schedule, not probe policy.
        from mano_hand_tpu.runtime import (
            ChaosPlan, CircuitBreaker, DispatchPolicy,
        )

        try:
            plan = ChaosPlan(args.chaos)
        except ValueError as e:
            # Same contract as every other argument guard here: a
            # message + rc 2, not a traceback.
            print(f"--chaos {args.chaos!r}: {e}", file=sys.stderr)
            return 2
        policy = DispatchPolicy(
            deadline_s=30.0 if args.deadline_s is None else args.deadline_s,
            retries=2,
            breaker=CircuitBreaker(
                failure_threshold=3, probe=lambda: True,
                probe_interval_s=1.0, respect_priority_claim=False),
            chaos=plan,
        )
    if args.subjects > 0:
        # The PR-4 mixed-subject coalescing protocol (the same code
        # path as bench.py config9, judged by scripts/bench_report.py);
        # composes with --chaos: the plan wraps the gathered primary
        # executables under the supervised policy built above.
        if args.aot_dir:
            # The gathered pose-only programs take the subject table as
            # a runtime argument, so a persistent AOT artifact would
            # bake nothing — refuse rather than silently not measure
            # the tier the caller asked for.
            print("--aot-dir does not apply to --subjects (the gathered "
                  "programs have no AOT tier; table and index are "
                  "runtime arguments)", file=sys.stderr)
            return 2
        from mano_hand_tpu.serving.measure import coalesce_bench_run

        out = coalesce_bench_run(
            params,
            subjects=args.subjects,
            requests=args.requests,
            min_rows=args.min_rows,
            max_rows=args.max_rows,
            max_bucket=args.max_bucket,
            max_delay_s=args.max_delay_ms * 1e-3,
            seed=args.seed,
            policy=policy,
            tracer=tracer,
            log=log,
        )
        out["backend"] = jax.default_backend()
        if args.chaos:
            out["chaos"] = args.chaos
        export_trace(out)
        print(json.dumps(out))
        return 0
    out = serve_bench_run(
        params,
        requests=args.requests,
        min_rows=args.min_rows,
        max_rows=args.max_rows,
        max_bucket=args.max_bucket,
        max_delay_s=args.max_delay_ms * 1e-3,
        aot_dir=args.aot_dir or None,
        seed=args.seed,
        policy=policy,
        tracer=tracer,
        metrics=metrics_reg,
    )
    out["backend"] = jax.default_backend()
    if args.chaos:
        out["chaos"] = args.chaos
    export_trace(out)
    if metrics_reg is not None:
        # The registry export (--metrics DIR): the final scrape as
        # metrics.json + Prometheus text, readable later by `mano
        # status --metrics-dir DIR`. An unwritable dir must not
        # discard a COMPLETED run (the --trace export rule): the
        # failure is recorded in the artifact, the JSON line prints.
        try:
            from mano_hand_tpu.obs.metrics import export_metrics_dir

            out["metrics_export"] = export_metrics_dir(
                metrics_reg.snapshot(), args.metrics)
        except OSError as e:
            out["metrics_export"] = {
                "error": f"{type(e).__name__}: {e} (metrics dir "
                         f"{args.metrics!r} unwritable; the run's "
                         "metrics above are unaffected)"}
    print(json.dumps(out))
    return 0


def cmd_serve(args) -> int:
    """`mano serve` — the network edge (PR 15): one worker process
    exposing a ``ServingEngine`` over the edge wire protocol
    (edge/server.py): ``POST /v1/forward`` (+ ``/v1/specialize``) with
    QoS headers, the PR-12 stream upgrade, 429 + Retry-After
    backpressure, ``/metrics`` (PR-9 Prometheus text) and ``/healthz``,
    flight-record-bearing 5xx bodies, and graceful drain on
    SIGTERM/SIGINT via the engine's ``stop(timeout_s=)`` sweep.

    Multi-worker coexistence: by default (``--device-lock auto``) a
    worker on a device backend takes the SHARED device lock
    (``utils.devicelock.DeviceLock(role="server")``) — N workers
    coexist, a driver bench's priority claim makes new workers stand
    down (rc 2), and a CPU-pinned worker takes no lock at all (the
    bench-interpret precedent: never preempt a real builder pipeline
    from a harness that cannot touch the chip).

    stdout carries exactly two JSON lines: a ready line at bind time
    (host/port/pid — the SIGTERM drill and orchestrators parse it)
    and a final drain report at exit; logs go to stderr.
    """
    import contextlib
    import os
    import signal
    import threading

    from mano_hand_tpu.edge import EdgeServer
    from mano_hand_tpu.obs import Tracer
    from mano_hand_tpu.obs.metrics import engine_registry
    from mano_hand_tpu.obs.recorder import FlightRecorder
    from mano_hand_tpu.serving.engine import ServingEngine
    from mano_hand_tpu.utils.devicelock import DeviceBusy, DeviceLock

    params = _load_params(args.asset, args.side).astype(np.float32)
    tracer = Tracer()
    tier_quotas = ({1: args.tier1_quota}
                   if args.max_queued and args.tier1_quota else None)
    # --store-warm-capacity N (PR 18) opts the worker into the PR-16
    # tiered store with a host-RAM warm tier of N rows (sharded when
    # the worker runs lanes — the shards ARE the per-lane tables).
    store = None
    if getattr(args, "store_warm_capacity", 0):
        from mano_hand_tpu.serving.subject_store import (
            SubjectStore,
            SubjectStoreConfig,
        )

        store = SubjectStore(SubjectStoreConfig(
            warm_capacity=int(args.store_warm_capacity),
            sharded=bool(args.lanes)))
    eng = ServingEngine(
        params,
        max_bucket=args.max_bucket,
        max_delay_s=args.max_delay_ms / 1e3,
        aot_dir=args.aot_dir or None,
        max_queued=args.max_queued or None,
        tier_quotas=tier_quotas,
        lanes=args.lanes or None,
        posed_kernel=args.posed_kernel,
        tracer=tracer,
        subject_store=store,
        max_subjects=args.max_subjects,
    )
    recorder = FlightRecorder(tracer, eng.counters,
                              out_dir=args.flight_dir or None)
    registry = engine_registry(eng, tracer=tracer)
    # --control (PR 19): attach the closed-loop controller; its
    # retry_after_for also becomes the edge's 429 Retry-After source.
    ctl = None
    if getattr(args, "control", False):
        from mano_hand_tpu.serving.control import Controller

        ctl = Controller(eng, log=lambda m: print(
            f"control: {m}", file=sys.stderr))

    lock_mode = args.device_lock
    if lock_mode == "auto":
        lock_mode = "off" if args.platform == "cpu" else "server"
    lock_ctx = (DeviceLock("server", log=lambda m: print(
        m, file=sys.stderr)) if lock_mode == "server"
        else contextlib.nullcontext())

    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        print(f"signal {signum}: draining", file=sys.stderr)
        stop_evt.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    try:
        with lock_ctx:
            eng.start()
            if not args.no_warmup:
                eng.warmup()
            # --warm-streams (PR 19, the PR-18 scale-up remainder):
            # exercise ONE synthetic stream — specialize, fit a frame,
            # close — BEFORE the ready line, so a scale-up worker's
            # first real stream frame pays zero compiles. The
            # fit-stage programs are deliberately NOT in the AOT
            # lattice (per-stream LM, shapes frozen at open — the
            # PR-18 dead-end), so a live warm pass is the only way to
            # pre-pay them. Best-effort: a failure logs and boots the
            # worker cold rather than not at all.
            warmed = False
            if getattr(args, "warm_streams", False):
                try:
                    sess = eng.open_stream(
                        np.zeros((params.n_shape,), np.float32))
                    try:
                        sess.submit_frame(
                            np.zeros((params.n_joints, 3), np.float32)
                        ).result(timeout=300)
                    finally:
                        sess.close()
                    warmed = True
                    print("warm-streams: stream-fit family warm",
                          file=sys.stderr)
                except Exception as e:  # noqa: BLE001 — cold > dead
                    print(f"warm-streams failed (worker boots cold): "
                          f"{type(e).__name__}: {e}", file=sys.stderr)
            if ctl is not None:
                ctl.start()
            srv = EdgeServer(
                eng, host=args.host, port=args.port, registry=registry,
                drain_timeout_s=args.drain_timeout_s,
                retry_after_source=(None if ctl is None
                                    else ctl.retry_after_for),
                # The healthz warm fact (PR 20): a definitive bool — a
                # worker that skipped (or failed) the warm pass says
                # False, and the proxy keeps NEW stream opens off it
                # while a warm sibling is routable.
                warm_streams=warmed,
                log=lambda m: print(m, file=sys.stderr)).start()
            print(json.dumps({
                "edge": {"host": srv.host, "port": srv.port,
                         "pid": os.getpid(),
                         "device_lock": lock_mode}}), flush=True)
            # Interruptible wait: the signal handler runs on this main
            # thread between wait windows (a bare Event.wait can sit
            # in one C-level acquire).
            while not stop_evt.wait(0.5):
                pass
            if ctl is not None:
                ctl.stop()
            report = srv.drain(timeout_s=args.drain_timeout_s)
            report["incident_captures"] = len(recorder.captures)
            # Cross-process telemetry (PR 18): the fleet drill judges
            # span-once and zero-steady-recompiles ACROSS workers, so
            # each worker's exit line carries its own tracer accounting
            # and compile counters for the supervisor to aggregate.
            report["accounting"] = tracer.accounting()
            snap = eng.counters.snapshot()
            report["counters"] = {
                k: snap[k] for k in
                ("compiles", "aot_loads", "aot_load_failures")}
            print(json.dumps({"edge_exit": report}), flush=True)
    except DeviceBusy as e:
        print(f"device busy: {e}", file=sys.stderr)
        return 2
    return 0


def cmd_proxy(args) -> int:
    """`mano proxy` — ONE member of the active/standby proxy pair
    (PR 20): the proxy tier's single point of failure, made killable.

    Arbitration is the DeviceLock pattern at the socket level: both
    members run this command against the same ``--lock`` file; exactly
    one wins the EXCLUSIVE flock, binds the service ``--port``, and
    serves. The loser parks in a bounded-step, SIGTERM-interruptible
    ``LOCK_NB`` poll (never a C-level ``LOCK_EX`` wait — signal
    handlers need the main thread, the CLAUDE.md rule). When the
    active dies — SIGKILL included — the kernel releases its flock and
    the standby takes over: it reads+increments the takeover
    generation stored IN the lock file (under the flock), waits
    (bounded) for the corpse's port to free, rebuilds per-backend
    routing state from the workers' own ``/healthz``
    (``EdgeProxy.resync_backends``), and serves. Live streams are not
    lost: clients reconnect through ``edge.client.ResilientStream``,
    which re-opens with ``resume_pose`` (the PR-18 last-confirmed-pose
    protocol) against the new active.

    stdout contract (edge/fleet.py's ``ProxyPair`` parses it):
    a ready line at spawn ``{"proxy": {pid, port, role: "standby"}}``
    BEFORE the (possibly unbounded) park; on activation
    ``{"proxy_event": {event: "active", takeovers: N, port}}``; on
    SIGTERM a final ``{"proxy_exit": {...}}``. Logs go to stderr.
    """
    import errno
    import fcntl
    import os
    import signal
    import socket
    import threading
    import time as _time

    from mano_hand_tpu.edge.proxy import Backend, EdgeProxy

    backends = []
    for spec in args.backend:
        name, _, hp = spec.partition("=")
        host, _, port = hp.rpartition(":")
        if not name or not host or not port.isdigit():
            print(f"--backend wants NAME=HOST:PORT, got {spec!r}",
                  file=sys.stderr)
            return 2
        backends.append(Backend(name, host, int(port)))
    if not backends:
        print("proxy needs at least one --backend NAME=HOST:PORT",
              file=sys.stderr)
        return 2

    stop_evt = threading.Event()

    def _on_signal(signum, frame):
        print(f"signal {signum}: proxy stopping", file=sys.stderr)
        stop_evt.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    # Ready line FIRST: the pair supervisor needs the pid before the
    # park, which lasts as long as the active lives.
    print(json.dumps({"proxy": {"pid": os.getpid(),
                                "port": int(args.port),
                                "role": "standby"}}), flush=True)

    fd = open(args.lock, "a+")
    try:
        while not stop_evt.is_set():
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError as e:
                if e.errno not in (errno.EAGAIN, errno.EACCES):
                    raise
                stop_evt.wait(0.05)
        if stop_evt.is_set():
            print(json.dumps({"proxy_exit": {
                "role": "standby", "served": False}}), flush=True)
            return 0

        # The takeover generation lives IN the lock file, mutated only
        # under the flock we now hold: generation 0 is the first-boot
        # active, N the Nth takeover winner.
        fd.seek(0)
        try:
            gen = int(json.loads(fd.read() or "{}").get(
                "takeovers", -1)) + 1
        except ValueError:
            gen = 0
        fd.seek(0)
        fd.truncate(0)
        fd.write(json.dumps({"takeovers": gen, "pid": os.getpid()}))
        fd.flush()

        # A SIGKILLed predecessor's listener closes with its process
        # (the same teardown that released the flock), but give the
        # kernel a bounded beat rather than crash-looping on EADDRINUSE.
        bind_deadline = _time.monotonic() + 10.0
        while not stop_evt.is_set():
            probe = socket.socket()
            probe.setsockopt(socket.SOL_SOCKET,
                             socket.SO_REUSEADDR, 1)
            try:
                probe.bind((args.host, int(args.port)))
                break
            except OSError as e:
                if _time.monotonic() > bind_deadline:
                    print(f"service port {args.port} never freed: {e}",
                          file=sys.stderr)
                    print(json.dumps({"proxy_exit": {
                        "role": "active", "takeovers": gen,
                        "error": f"bind: {e}"}}), flush=True)
                    return 1
                stop_evt.wait(0.05)
            finally:
                probe.close()
        if stop_evt.is_set():
            print(json.dumps({"proxy_exit": {
                "role": "standby", "served": False}}), flush=True)
            return 0

        proxy = EdgeProxy(
            backends, host=args.host, port=int(args.port),
            drain_timeout_s=args.drain_timeout_s,
            upstream_timeout_s=args.upstream_timeout_s,
            role="active", takeovers=gen,
            log=lambda m: print(m, file=sys.stderr))
        # Routing rebuild BEFORE the first proxied byte: a takeover
        # winner must not start with an empty breaker ledger aimed at
        # a dead worker. Bounded (concurrent, per-backend timeout).
        resynced = proxy.resync_backends(timeout_s=5.0)
        proxy.start()
        print(json.dumps({"proxy_event": {
            "event": "active", "takeovers": gen, "port": proxy.port,
            "backends_up": sum(1 for ok in resynced.values() if ok),
            "backends": len(resynced)}}), flush=True)
        while not stop_evt.wait(0.2):
            pass
        report = proxy.drain(timeout_s=args.drain_timeout_s)
        print(json.dumps({"proxy_exit": {
            "role": "active", "takeovers": gen, "drain": report,
            "counters": proxy._counter_dict()}}), flush=True)
        return 0
    finally:
        fd.close()                      # releases the flock if held


def cmd_trace_report(args) -> int:
    """`mano trace-report` — the CLI spelling of
    scripts/trace_report.py (PR 8): one merged host+device timeline
    report over an XLA ``--profile`` capture and/or an engine span
    export. The script stays a standalone stdlib-only tool (it must
    run where this package isn't importable — e.g. over an archived
    artifact dir on a bare box), so the CLI loads it by path instead
    of duplicating the logic."""
    import importlib.util
    from pathlib import Path

    script = (Path(__file__).resolve().parents[1] / "scripts"
              / "trace_report.py")
    spec = importlib.util.spec_from_file_location(
        "mano_trace_report", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    argv = [str(args.path), "--top", str(args.top)]
    if args.json:
        argv.append("--json")
    if args.all_tracks:
        argv.append("--all-tracks")
    return mod.main(argv)


def cmd_status(args) -> int:
    """`mano status` — the operator's one-look health surface (PR 9):
    host facts, tunnel/device health, the committed numerics goldens,
    and (``--metrics-dir``) the last persisted metrics scrape of a
    `serve-bench --metrics` run, as one JSON document on stdout.

    Device health is probed ONLY in a killable subprocess
    (runtime.supervise.run_python — the CLAUDE.md rule: an in-process
    ``jax.devices()`` HANGS for hours when the tunnel is down, and no
    signal can clear it). A failed or hung probe degrades the report
    to host-only facts (``degraded: true``) instead of hanging the
    command; rc stays 0 — status is a report, not a gate.

    ``--prom`` re-renders the persisted metrics snapshot as Prometheus
    text (a scrape endpoint must not pay a 20 s tunnel probe, so
    probes are skipped in that mode)."""
    from mano_hand_tpu.obs.metrics import METRICS_JSON, prometheus_text
    from mano_hand_tpu.obs.sentinel import (
        default_goldens_path, load_goldens,
    )

    metrics_snap = None
    metrics_info = None
    if args.metrics_dir:
        from pathlib import Path

        path = Path(args.metrics_dir) / METRICS_JSON
        try:
            metrics_snap = json.loads(path.read_text())
            metrics_info = {
                "path": str(path),
                "schema": metrics_snap.get("schema"),
                "metrics": len(metrics_snap.get("metrics") or {}),
                "wall_time_utc": metrics_snap.get("wall_time_utc"),
            }
        except (OSError, ValueError) as e:
            metrics_info = {"path": str(path),
                            "error": f"{type(e).__name__}: {e}"}
    if args.prom:
        if metrics_snap is None:
            print("--prom needs a readable --metrics-dir (the "
                  "persisted scrape of a `serve-bench --metrics DIR` "
                  "run)" + (f": {metrics_info['error']}"
                            if metrics_info else ""), file=sys.stderr)
            return 2
        print(prometheus_text(metrics_snap), end="")
        return 0

    host = {"python": sys.version.split()[0], "platform": sys.platform}
    from importlib import metadata

    for pkg in ("jax", "jaxlib", "numpy"):
        try:
            host[pkg] = metadata.version(pkg)
        except Exception:  # noqa: BLE001 — a missing dist is a fact
            host[pkg] = None

    from mano_hand_tpu.runtime import supervise

    probes = {}
    degraded = False
    for plat in [p.strip() for p in args.platforms.split(",")
                 if p.strip()]:
        code = ["import jax"]
        if plat != "default":
            # The site-hook rule: only the config API pins a platform.
            code.append(
                f"jax.config.update('jax_platforms', {plat!r})")
        # The jax.devices() below runs in the KILLABLE subprocess —
        # a tunnel-down hang is killed at the timeout, never waited
        # out in this process.
        code.append("ds = jax.devices()")
        code.append("print(len(ds), ds[0].platform, "
                    "getattr(ds[0], 'device_kind', '?'))")
        res = supervise.run_python("\n".join(code),
                                   timeout_s=args.probe_timeout)
        entry = {"ok": bool(res.ok)}
        if res.ok:
            parts = (res.out or "").split(None, 2)
            if len(parts) == 3:
                entry.update(devices=int(parts[0]), platform=parts[1],
                             device_kind=parts[2])
        else:
            entry["error"] = res.err
            entry["killed"] = bool(getattr(res, "killed", False))
            degraded = True
        probes[plat] = entry

    server_block = None
    if args.server:
        # PR 15: probe a live edge worker. Bounded (EdgeClient's
        # socket timeout covers connect and every read) and degrading
        # (any failure is a fact in the report, not a crash): status
        # is a report, not a gate — rc stays 0 either way.
        from urllib.parse import urlparse

        from mano_hand_tpu.edge import EdgeClient

        spec = (args.server if "//" in args.server
                else f"http://{args.server}")
        u = urlparse(spec)
        server_block = {"url": args.server, "ok": False}
        cli = EdgeClient(u.hostname or "127.0.0.1", u.port or 8077,
                         timeout_s=args.server_timeout)
        try:
            h = cli.healthz()
            server_block["ok"] = bool(h.get("ok"))
            server_block["healthz"] = {
                k: h.get(k) for k in ("status", "degraded",
                                      "uptime_s", "breaker", "lanes")}
            server_block["engine"] = h.get("engine")
            server_block["streams"] = h.get("streams")
            if h.get("role") == "proxy":
                # PR 18: the probed server is a fleet front tier. Its
                # /healthz already did the bounded per-backend fan-out
                # (a wedged worker is a per-entry error after its own
                # probe deadline, never a hang), so the aggregate is
                # one more dict to surface, per-worker health/breaker
                # state included.
                server_block["role"] = "proxy"
                # PR 20: active/standby pair facts. A mid-takeover
                # probe (nobody bound to the service port yet) lands
                # in the except arm below as an error fact — the
                # command still never hangs (socket timeout) and rc
                # stays 0; the next scrape sees the new active's
                # incremented takeover generation.
                server_block["proxy_role"] = h.get("proxy_role")
                server_block["takeovers"] = h.get("takeovers")
                server_block["backends"] = {
                    name: {k: b.get(k) for k in
                           ("ok", "status", "degraded", "breaker",
                            "draining_via_proxy", "outstanding",
                            "streams", "stream_warm", "error")}
                    for name, b in (h.get("backends") or {}).items()}
                server_block["counters"] = h.get("counters")
            try:
                text = cli.metrics_text()
                server_block["metrics"] = {
                    "lines": len(text.splitlines()),
                    "has_serving": "mano_serving_" in text,
                }
            except Exception as e:  # noqa: BLE001 — degrade per leg
                server_block["metrics"] = {
                    "error": f"{type(e).__name__}: {e}"}
        except Exception as e:  # noqa: BLE001 — down/hung server
            server_block["error"] = f"{type(e).__name__}: {e}"
        finally:
            cli.close()

    gpath = default_goldens_path()
    goldens = load_goldens(gpath)
    report = {
        "schema": 1,
        "host": host,
        "probes": probes,
        "degraded": degraded,
        "goldens": {
            "path": str(gpath),
            "present": goldens is not None,
            "entries": sorted((goldens or {}).get("entries", {})),
        },
    }
    if degraded:
        report["note"] = (
            "device probe failed/hung — host-only report (the tunnel "
            "is probably down; serving degrades to the CPU tier, see "
            "runtime/health.py)")
    if server_block is not None:
        report["server"] = server_block
    if metrics_info is not None:
        report["metrics"] = metrics_info
    if metrics_snap is not None:
        # Streaming sessions (PR 12): the persisted scrape carries the
        # engine's one-lock-hold streams block (load_samples maps
        # ServingEngine.load()["streams"] to load_streams_* gauges);
        # surface active-stream count + per-stream backlog age here so
        # the operator's one look answers "how many live users, and is
        # any stream's oldest frame stuck" without re-parsing metrics.
        m = metrics_snap.get("metrics") or {}
        streams_block = {}
        for short in ("active", "frames_in_flight", "backlog_age_s",
                      "opened", "frames_submitted", "frames_resolved"):
            entry = m.get(f"load_streams_{short}")
            samples = (entry or {}).get("samples") or []
            if samples:
                streams_block[short] = samples[0][1]
        if streams_block:
            report["streams"] = streams_block
    print(json.dumps(report, indent=2))
    return 0


def cmd_analyze(args) -> int:
    """Project-invariant static analysis (analysis/, PR 7): the policy
    linter, lock-discipline checker, lockstep-drift detector, and (on
    CPU, no chip touched) the jaxpr program auditor. Exit 0 iff clean;
    each failure names the rule, file:line, and its escape hatch."""
    import os

    import jax

    if not args.platform:
        # The site-hook rule the linter itself enforces: env selection
        # is overridden at interpreter startup; only the config API
        # reliably pins the host backend — the auditor must trace on
        # CPU even when the TPU tunnel is configured (and down).
        jax.config.update("jax_platforms", "cpu")
    cache = os.environ.get("MANO_TEST_CACHE_DIR")
    if cache:
        # The compile-cache rule (CLAUDE.md): `make analyze` may run
        # beside a live pytest process, and two processes must never
        # share one cache dir — the Makefile points this at its own.
        jax.config.update("jax_compilation_cache_dir", cache)
    from mano_hand_tpu.analysis.run import run_analysis

    return run_analysis(update_baseline=args.update_baseline,
                        skip_jaxpr=args.skip_jaxpr, as_json=args.json)


def cmd_info(args) -> int:
    params = _load_params(args.asset, args.side)
    info = {
        "side": params.side,
        "n_verts": params.n_verts,
        "n_joints": params.n_joints,
        "n_faces": int(params.faces.shape[0]),
        "n_shape": params.n_shape,
        "parents": list(params.parents),
        "dtype": str(np.asarray(params.v_template).dtype),
    }
    print(json.dumps(info, indent=2))
    return 0


def cmd_verify(args) -> int:
    # Truth anchor for user-supplied (license-gated) official pickles:
    # the loaders can only be tested on synthetic replicas in-repo, so
    # the decoded asset is audited at the user's machine instead —
    # structural gates, numeric invariants, and canonical digests
    # (assets/verify.py has the full contract).
    from mano_hand_tpu.assets.verify import (
        format_report, report_json, verify_asset,
    )

    try:
        report = verify_asset(args.asset, side=args.side,
                              golden=args.golden)
    except Exception as e:  # noqa: BLE001 — decode failures ARE the verdict
        print(f"verify: {args.asset} failed to decode as a MANO asset: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(report_json(report, expect=args.expect))
        ok = report.gates_ok and (
            args.expect is None
            or report.digests["combined"] == args.expect)
        return 0 if ok else 1
    text, rc = format_report(report, args.asset, expect=args.expect)
    print(text)
    return rc


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="mano_hand_tpu", description=__doc__)
    p.add_argument(
        "--platform", default="",
        help="force a JAX platform (e.g. 'cpu'). Needed when the default "
             "accelerator tunnel is down: a site hook overrides "
             "JAX_PLATFORMS, so only the config API reliably selects cpu.",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    d = sub.add_parser("demo", help="export the reference demo mesh")
    d.add_argument("--asset", default="synthetic",
                   help="asset path (.npz/.pkl) or 'synthetic'")
    d.add_argument("--side", default=None, choices=[None, "left", "right", "neutral"])
    d.add_argument("--backend", default="jax", choices=["np", "jax"])
    d.add_argument("--out", default="hand.obj",
                   help="output mesh; a .ply suffix writes binary PLY "
                        "with normals instead of the OBJ pair")
    d.set_defaults(fn=cmd_demo)

    c = sub.add_parser("convert", help="convert assets between formats")
    c.add_argument("src")
    c.add_argument("dst", help="output path (.npz or .pkl)")
    c.add_argument("--side", default=None, choices=[None, "left", "right", "neutral"])
    c.add_argument("--mirror", action="store_true",
                   help="write the OPPOSITE side: reflect the asset "
                        "across x=0 (template/bases re-signed, winding "
                        "reversed, PCA stats mirrored — "
                        "assets.mirror_params); for when only one "
                        "side's file is at hand")
    c.set_defaults(fn=cmd_convert)

    a = sub.add_parser("animate", help="batch-evaluate a pose sequence")
    a.add_argument("poses", help=".npy of [T,16,3] or [T,15,3] axis-angles")
    a.add_argument("--asset", default="synthetic")
    a.add_argument("--side", default=None, choices=[None, "left", "right", "neutral"])
    a.add_argument("--out", default="frames",
                   help="output dir for OBJ frames, or a .glb path for "
                        "ONE viewer-ready animated file (morph targets)")
    a.add_argument("--fps", type=float, default=30.0,
                   help="playback rate for --out .glb")
    a.add_argument("--skinned", action="store_true",
                   help="with --out .glb: export a skeletal skin "
                        "(joint nodes + LBS weights + quaternion "
                        "rotation tracks — drivable in any engine) "
                        "instead of baked morph targets (exact but "
                        "frame-count-sized)")
    a.set_defaults(fn=cmd_animate)

    r = sub.add_parser("render", help="rasterize poses to PNG/GIF")
    r.add_argument("--poses", default=None,
                   help=".npy of [T,16,3]/[T,15,3]/[16,3]; default rest pose")
    r.add_argument("--asset", default="synthetic")
    r.add_argument("--side", default=None, choices=[None, "left", "right", "neutral"])
    r.add_argument("--out", default="render",
                   help="output dir for PNGs, or a .gif path")
    r.add_argument("--size", type=int, default=256)
    r.add_argument("--fps", type=int, default=20)
    r.set_defaults(fn=cmd_render)

    f = sub.add_parser(
        "fit",
        help="recover pose/shape from target verts, 3D joints, 2D "
             "keypoints, scan points, or segmentation masks",
    )
    f.add_argument("targets",
                   help=".npy of [V,3]/[B,V,3] verts; [16,3]/[B,16,3] "
                        "joints with --data-term joints; [16,2]/[B,16,2] "
                        "image points with --data-term keypoints2d; "
                        "[N,3]/[B,N,3] scan points with --data-term "
                        "points or point_to_plane (a .ply or .obj file "
                        "loads its vertex cloud directly); an "
                        "[H,W]/[B,H,W] .npy mask in [0,1] or a .png "
                        "with --data-term silhouette")
    f.add_argument("--pose-space", default=None,
                   choices=["aa", "pca", "6d"],
                   help="pose parameterization: axis-angle (both solvers' "
                        "native space — leaves the solver default alone), "
                        "PCA coefficients, or the 6D continuous rotation "
                        "representation (wrap-free; results decode back "
                        "to axis-angle). pca/6d imply the Adam solver; "
                        "keypoints2d defaults to pca when unset")
    f.add_argument("--data-term", default="verts",
                   choices=["verts", "joints", "keypoints2d", "points",
                            "point_to_plane", "silhouette", "depth"],
                   help="fit to a full target mesh, sparse 3D keypoints "
                        "(detector/mocap output), 2D keypoints projected "
                        "through a pinhole camera, a correspondence-"
                        "free point cloud (partial depth-sensor scans): "
                        "'points' = chamfer/point-to-point ICP, "
                        "'point_to_plane' = LM-only normal-distance "
                        "polish after a points fit, or a segmentation "
                        "mask ('silhouette': soft-IoU through the "
                        "differentiable rasterizer, weak-perspective "
                        "camera; multi-view fitting is a library/example "
                        "feature — see examples/12), or a sensor depth "
                        "image ('depth': [H,W] .npy in view-space "
                        "meters, <=0/NaN = no reading — the one "
                        "single-view image term that observes full 3D "
                        "translation; pinhole/--camera-k only)")
    f.add_argument("--init", default=None,
                   help="warm-start from a previous fit checkpoint (.npz "
                        "with pose/shape, e.g. a coarse --data-term joints "
                        "fit before --data-term points refinement: "
                        "chamfer/ICP plateau from a cold start). Works "
                        "with both solvers (Adam needs --pose-space aa)")
    f.add_argument("--robust", default="none", choices=["none", "huber"],
                   help="Huber-robust data term (bounded pull from "
                        "outlier points). Adam only")
    f.add_argument("--robust-scale", type=float, default=0.01,
                   help="Huber scale in data units (meters for 3D terms)")
    f.add_argument("--tips", default="",
                   help="extend joints/keypoints2d targets with fingertip "
                        "vertex picks: 'smplx' | 'manopth' (the standard "
                        "21-keypoint set); default: 16 joints only")
    f.add_argument("--keypoint-order", default="mano",
                   choices=["mano", "openpose"],
                   help="row ordering of 21-keypoint targets "
                        "(openpose = OpenPose/FreiHAND convention)")
    f.add_argument("--robust-weights", default="none",
                   choices=["none", "tukey", "geman"],
                   help="soft IRLS reweighting of ICP points by their "
                        "per-step distances (LM solver, points/"
                        "point_to_plane) — the graded-noise counterpart "
                        "of --trim's hard cut; they compose")
    f.add_argument("--trim", type=float, default=0.0,
                   help="trimmed-ICP fraction in [0, 1): reject this "
                        "fraction of the worst-matching scan points each "
                        "step (outlier defense; --solver lm with "
                        "--data-term points/point_to_plane only)")
    f.add_argument("--fit-trans", action="store_true",
                   help="fit a global translation too (uncentered "
                        "targets/scans; both solvers — the 2D keypoint "
                        "terms always fit it). Checkpoint gains a "
                        "'trans' array; --init may carry one")
    f.add_argument("--conf", default=None,
                   help=".npy of [16]/[B,16] keypoint confidences "
                        "(keypoints2d only)")
    f.add_argument("--camera-eye", default=None,
                   help="camera position 'x,y,z' looking at the origin "
                        "(keypoints2d only; default 0,0,-0.75; "
                        "conflicts with --camera-k)")
    f.add_argument("--focal", type=float, default=None,
                   help="pinhole focal in NDC units (keypoints2d only; "
                        "default 2.2; conflicts with --camera-k)")
    f.add_argument("--camera-k", default=None,
                   help="dataset calibration 'fx,fy,cx,cy' in pixels "
                        "(with --camera-size): keypoints2d targets are "
                        "then PIXEL coordinates; silhouette masks must "
                        "match the calibrated resolution")
    f.add_argument("--camera-size", default=None,
                   help="calibrated image size 'WxH' (with --camera-k)")
    f.add_argument("--camera-scale", type=float, default=None,
                   help="weak-perspective scale (silhouette only): NDC "
                        "units per meter (default 3.0)")
    f.add_argument("--camera-rot", default=None,
                   help="axis-angle view rotation 'x,y,z' of the "
                        "silhouette camera (silhouette only; "
                        "default 0,0,0)")
    f.add_argument("--sil-sigma", type=float, default=None,
                   help="rasterizer edge softness in pixels for the "
                        "silhouette/depth terms (default 1.0 — about "
                        "right; larger blurs the optimum itself, "
                        "measured in docs/roadmap.md)")
    f.add_argument("--pose-prior", default="l2",
                   choices=["l2", "mahalanobis"],
                   help="pose regularizer: isotropic L2 toward zero, or "
                        "the data-driven Mahalanobis energy toward the "
                        "asset's mean pose in PCA-whitened space "
                        "(adam solver, aa/pca pose spaces)")
    f.add_argument("--pose-prior-weight", type=float, default=None,
                   help="pose prior weight (default: 1e-4 for "
                        "keypoints2d, 1.0 for silhouette/depth — a "
                        "single image cannot pin articulation, 1e-3 for "
                        "--pose-prior mahalanobis, else 0)")
    f.add_argument("--joint-limits", default=None,
                   help=".npz with per-DOF axis-angle bounds (keys lo, "
                        "hi, each [45]; build with "
                        "objectives.pose_limits_from_corpus) — adds the "
                        "squared-hinge anatomical limit prior "
                        "(adam solver, aa/pca pose spaces)")
    f.add_argument("--joint-limit-weight", type=float, default=None,
                   help="weight of the joint-limit hinge (default 1.0; "
                        "only with --joint-limits)")
    f.add_argument("--restarts", type=int, default=0,
                   help="solve ONE problem from N inits (zero + the "
                        "closed-form Kabsch alignment on verts/joints "
                        "targets + anatomical samples) and keep the "
                        "best — for far-rotated or multi-modal targets; "
                        "single-problem targets only")
    f.add_argument("--shape-prior", type=float, default=None,
                   help="shape regularizer. adam: L2 prior weight (default "
                        "0 for verts, 1.0 for silhouette/depth, 1e-3 "
                        "for joints/keypoints2d). lm "
                        "with joints: Tikhonov residual-ROW weight, which "
                        "enters the least-squares loss SQUARED (default "
                        "0.1) — not numerically comparable to the adam "
                        "weight")
    f.add_argument("--asset", default="synthetic")
    f.add_argument("--side", default=None, choices=[None, "left", "right", "neutral"])
    f.add_argument("--solver", default=None, choices=["lm", "adam"],
                   help="default: lm for --data-term verts/point_to_plane, "
                        "adam for joints/keypoints2d/points/silhouette/"
                        "depth; lm also supports joints and points "
                        "(second-order ICP); keypoints2d/silhouette/depth "
                        "are adam-only, point_to_plane lm-only")
    f.add_argument("--steps", type=int, default=None,
                   help="default: 25 (lm) / 200 (adam)")
    f.add_argument("--lr", type=float, default=None,
                   help="adam learning rate (default 0.05; 0.02 for "
                        "keypoints2d, 0.01 for silhouette/depth; "
                        "adam only)")
    f.add_argument("--out", default="fit.npz")
    f.add_argument("--heatmap", default=None,
                   help="also export the fitted mesh with per-vertex "
                        "error colors (blue=0 -> red=max): a rendered "
                        "PNG, or with a .glb extension a 3D mesh with "
                        "COLOR_0 vertex colors any glTF viewer can orbit "
                        "(--data-term verts, single target)")
    f.set_defaults(fn=cmd_fit)

    e = sub.add_parser(
        "export-aot",
        help="serialize the compiled forward (jax.export) for serving",
    )
    e.add_argument("--asset", default="synthetic")
    e.add_argument("--side", default=None, choices=[None, "left", "right", "neutral"])
    e.add_argument("--out", default="mano_fwd.jaxexp")
    e.add_argument("--batch", type=int, default=0,
                   help="pin the batch size; default 0 = symbolic (any B)")
    e.add_argument("--tips", default="",
                   help="fingertip convention for baked-in keypoints "
                        "('smplx' | 'manopth'); default: 16 joints only")
    e.add_argument("--keypoint-order", default="mano",
                   choices=["mano", "openpose"])
    e.add_argument("--platforms", default="",
                   help="comma-separated lowering platforms; default cpu,tpu")
    e.set_defaults(fn=cmd_export_aot)

    sb = sub.add_parser(
        "serve-bench",
        help="measure the bucketed micro-batching engine on a synthetic "
             "ragged request stream (one JSON line of serving metrics)",
    )
    sb.add_argument("--asset", default="synthetic")
    sb.add_argument("--side", default=None,
                    choices=[None, "left", "right", "neutral"])
    sb.add_argument("--requests", type=int, default=256,
                    help="requests per measured pass")
    sb.add_argument("--min-rows", type=int, default=1)
    sb.add_argument("--max-rows", type=int, default=64,
                    help="request batch sizes are uniform in "
                         "[--min-rows, --max-rows]")
    sb.add_argument("--max-bucket", type=int, default=256)
    sb.add_argument("--max-delay-ms", type=float, default=2.0,
                    help="coalescing window once a request is pending")
    sb.add_argument("--aot-dir", default="",
                    help="persistent per-bucket AOT artifact cache "
                         "(serving/engine.py); empty = in-memory only")
    sb.add_argument("--chaos", default="",
                    help="inject a deterministic fault plan "
                         "(runtime/chaos.py spec, e.g. "
                         "'error@0-1,latency:0.2@4,hang@7') into the "
                         "engine's primary executables under supervised "
                         "dispatch, or 'drill' to run the full recovery "
                         "drill (every fault class + recovery; "
                         "serving/measure.py:recovery_drill_run) and "
                         "print its one-line artifact")
    sb.add_argument("--deadline-s", type=float, default=None,
                    help="per-batch supervised dispatch deadline used "
                         "with --chaos (hung batches are abandoned, "
                         "retried, then failed over to CPU). Default: "
                         "30 for a --chaos plan, the drill protocol's "
                         "own 2 s for --chaos drill — raise it on the "
                         "real tunnel, where a healthy dispatch can "
                         "take seconds")
    sb.add_argument("--emit-by", type=float, default=-1.0,
                    help="hard wall-clock deadline in seconds: emit a "
                         "null JSON line and hard-exit if the run hangs "
                         "(tunnel drops leave the dispatcher in an "
                         "unkillable device RPC). Default: 900 on "
                         "device backends, off on cpu; 0 disables")
    sb.add_argument("--subjects", type=int, default=0,
                    help="run the MIXED-SUBJECT coalescing protocol "
                         "instead (serving/measure.py:coalesce_bench_run,"
                         " shared with bench.py config9): this many "
                         "baked subjects submit an interleaved pose-only "
                         "stream through the gathered engine dispatch, "
                         "measured against the per-subject-split "
                         "baseline. 0 = the classic full-path protocol")
    sb.add_argument("--overload", action="store_true",
                    help="run the OVERLOAD/saturation drill instead "
                         "(serving/measure.py:overload_drill_run, "
                         "shared with bench.py config10): bounded "
                         "admission + per-request deadlines + priority "
                         "shedding under a burst submitter at "
                         "--overload-saturation x the measured service "
                         "rate, one JSON line judged by "
                         "scripts/bench_report.py. Saturation is "
                         "throttled in-process (chaos 'sat' plan) — no "
                         "chip required, none harmed")
    sb.add_argument("--cold-start", action="store_true",
                    help="run the cold-start/restart drill instead "
                         "(serving/measure.py:cold_start_drill_run, "
                         "the bench.py config11 protocol): bake the "
                         "executable lattice + SubjectTable checkpoint "
                         "into a drill-owned coldstart_drill/ subdir of "
                         "--aot-dir (required; a production lattice in "
                         "the dir itself is never touched), kill the "
                         "mid-traffic, cold-boot, and judge zero jit "
                         "compiles after restore, restored-subject "
                         "bit-identity, and counted degradation of "
                         "every damage injection; does not compose "
                         "with --chaos/--subjects/--overload/"
                         "--deadline-s")
    sb.add_argument("--overload-saturation", type=float, default=4.0,
                    help="offered-load multiple of the measured "
                         "service rate for --overload (criteria are "
                         "judged at >= 4x achieved)")
    sb.add_argument("--streams", type=int, default=0,
                    help="run the STREAMING-SESSION drill instead "
                         "(serving/measure.py:stream_drill_run, shared "
                         "with bench.py config15): this many "
                         "concurrent per-user tracking sessions — "
                         "warm-started frozen-shape per-frame fits, "
                         "gathered tier-0 dispatch, a mid-drill chaos "
                         "plan with bit-identical CPU failover — one "
                         "JSON line judged by scripts/bench_report.py. "
                         "0 = off")
    sb.add_argument("--trace", default="",
                    help="request-lifecycle tracing (PR 8): span every "
                         "request through an obs.Tracer and export the "
                         "Chrome-trace timeline + final flight record "
                         "into this directory (read it with `mano "
                         "trace-report DIR`). Composes with every "
                         "protocol; stdout stays one JSON line. A "
                         "watchdog kill dumps the timeline here before "
                         "exiting")
    sb.add_argument("--metrics", default="",
                    help="metrics registry export (PR 9): register the "
                         "run's engine telemetry (ServingCounters, "
                         "load(), tracer) on an obs.metrics registry "
                         "and persist the final scrape into this "
                         "directory as metrics.json + metrics.prom "
                         "(read them with `mano status --metrics-dir "
                         "DIR [--prom]`). Default protocol only "
                         "(optionally under a --chaos plan); the "
                         "drill modes fix their own engines")
    sb.add_argument("--seed", type=int, default=0)
    sb.set_defaults(fn=cmd_serve_bench)

    sv = sub.add_parser(
        "serve",
        help="serve the engine over the edge wire protocol (PR 15): "
             "forward/stream endpoints with QoS headers, 429 "
             "backpressure, /metrics + /healthz, SIGTERM drain")
    sv.add_argument("--host", default="127.0.0.1",
                    help="bind address (default loopback — fronting a "
                         "real network is the proxy's job)")
    sv.add_argument("--port", type=int, default=8077,
                    help="bind port (0 = ephemeral; the bound port is "
                         "in the stdout ready line)")
    sv.add_argument("--asset", default="synthetic")
    sv.add_argument("--side", default=None,
                    choices=[None, "left", "right", "neutral"])
    sv.add_argument("--max-bucket", type=int, default=64)
    sv.add_argument("--max-delay-ms", type=float, default=2.0,
                    help="coalesce window (the latency/throughput "
                         "knob)")
    sv.add_argument("--max-queued", type=int, default=256,
                    help="bounded admission (PR 5): outstanding cap; "
                         "0 = unbounded (429s never fire)")
    sv.add_argument("--max-subjects", type=int, default=4096,
                    help="specialized-subject table ceiling (PR 4); "
                         "under --lanes it also sizes the per-lane "
                         "shard tables (ceil(max-subjects / lanes))")
    sv.add_argument("--tier1-quota", type=int, default=0,
                    help="tier-1 admission quota (0 = the PR-5 "
                         "default: half of max-queued)")
    sv.add_argument("--lanes", type=int, default=0,
                    help="per-device dispatch lanes (PR 13; 0 = "
                         "single-device dispatch)")
    sv.add_argument("--posed-kernel", default="xla",
                    choices=["xla", "fused"],
                    help="gathered pose-only program family (PR 10)")
    sv.add_argument("--aot-dir", default="",
                    help="executable lattice dir (PR 6) for zero-"
                         "compile boot")
    sv.add_argument("--store-warm-capacity", type=int, default=0,
                    help="tiered subject store (PR 16): host-RAM warm "
                         "tier of N rows (sharded under --lanes); "
                         "0 = device-table only")
    sv.add_argument("--no-warmup", action="store_true",
                    help="skip the boot-time bucket warmup (compiles "
                         "then land in the first requests)")
    sv.add_argument("--warm-streams", action="store_true",
                    help="exercise one synthetic stream fit before "
                         "the ready line (PR 19): a scale-up worker's "
                         "first real frame pays zero compiles (the "
                         "fit-stage programs are not in the AOT "
                         "lattice)")
    sv.add_argument("--control", action="store_true",
                    help="attach the closed-loop controller (PR 19): "
                         "live quota/coalesce/Retry-After actuation "
                         "off burn rates; crash degrades to the "
                         "static flags above")
    sv.add_argument("--drain-timeout-s", type=float, default=15.0,
                    help="SIGTERM drain budget: in-flight requests "
                         "resolve, the engine stop() sweep runs, the "
                         "process exits inside this window")
    sv.add_argument("--flight-dir", default="",
                    help="persist flight-recorder incident captures "
                         "here (default: in-memory only)")
    sv.add_argument("--device-lock", default="auto",
                    choices=["auto", "server", "off"],
                    help="multi-worker coexistence: 'server' takes "
                         "the SHARED device lock (N workers coexist; "
                         "a driver bench claim -> rc 2); 'auto' = "
                         "server on device backends, off when "
                         "--platform cpu pins the host")
    sv.set_defaults(fn=cmd_serve)

    px = sub.add_parser(
        "proxy",
        help="one member of the active/standby fleet-proxy pair "
             "(PR 20): parks on an exclusive flock; the winner binds "
             "the service port, resyncs backend health from worker "
             "/healthz, and serves — a SIGKILLed active's kernel-"
             "released lock activates the standby with an incremented "
             "takeover generation")
    px.add_argument("--port", type=int, required=True,
                    help="the pair's stable service port (clients and "
                         "ResilientStream reconnect here across "
                         "takeovers)")
    px.add_argument("--host", default="127.0.0.1")
    px.add_argument("--lock", required=True,
                    help="flock arbitration file; also carries the "
                         "takeover generation (mutated only under the "
                         "flock)")
    px.add_argument("--backend", action="append", default=[],
                    metavar="NAME=HOST:PORT",
                    help="one worker address (repeatable)")
    px.add_argument("--drain-timeout-s", type=float, default=10.0)
    px.add_argument("--upstream-timeout-s", type=float, default=300.0)
    px.set_defaults(fn=cmd_proxy)

    tr = sub.add_parser(
        "trace-report",
        help="summarize an XLA --profile capture and/or an engine span "
             "export (serve-bench --trace DIR) into one merged "
             "host+device report: top device ops + per-bucket/tier "
             "queue/dispatch/device/readback stage breakdown",
    )
    tr.add_argument("path", help="trace dir or one *.trace.json[.gz]")
    tr.add_argument("--top", type=int, default=15)
    tr.add_argument("--json", action="store_true",
                    help="machine-readable JSON instead of the tables")
    tr.add_argument("--all-tracks", action="store_true",
                    help="include host tracks even when a device track "
                         "exists")
    tr.set_defaults(fn=cmd_trace_report)

    st = sub.add_parser(
        "status",
        help="host + device health report (killable-subprocess tunnel "
             "probe — never an in-process jax.devices()), committed "
             "numerics goldens, and the last persisted metrics scrape",
    )
    st.add_argument("--platforms", default="cpu,default",
                    help="comma-separated platforms to probe; "
                         "'default' probes whatever the site hook "
                         "configured (the tunnel on this box) — a "
                         "down tunnel degrades the report, never "
                         "hangs it")
    st.add_argument("--probe-timeout", type=float, default=20.0,
                    help="per-platform probe deadline in seconds; a "
                         "hung probe is SIGKILLed at the deadline")
    st.add_argument("--server", default="",
                    help="probe a running edge worker (PR 15) or "
                         "fleet proxy (PR 18): hit its /healthz + "
                         "/metrics with a bounded timeout and fold "
                         "the answer into the report — a proxy "
                         "answers with the per-backend aggregate; a "
                         "down/hung server degrades the block (rc "
                         "stays 0, never hangs — the tunnel-probe "
                         "contract)")
    st.add_argument("--server-timeout", type=float, default=3.0,
                    help="per-read bound on the --server probe")
    st.add_argument("--metrics-dir", default="",
                    help="read the metrics.json a `serve-bench "
                         "--metrics DIR` run persisted and include it "
                         "in the report")
    st.add_argument("--prom", action="store_true",
                    help="print the persisted metrics snapshot as "
                         "Prometheus text instead of the JSON report "
                         "(requires --metrics-dir; skips the device "
                         "probes — a scrape endpoint must stay fast)")
    st.set_defaults(fn=cmd_status)

    an = sub.add_parser(
        "analyze",
        help="run the project-invariant static-analysis pass (policy "
             "linter, lock-discipline checker, jaxpr program auditor, "
             "lockstep-drift detector); exit 0 iff clean",
    )
    an.add_argument("--update-baseline", action="store_true",
                    help="recommit analysis/baseline.json (jaxpr "
                         "primitive counts + lockstep fingerprints) "
                         "after an INTENTIONAL program/scaffolding "
                         "change; justify the diff in the PR")
    an.add_argument("--skip-jaxpr", action="store_true",
                    help="skip the jaxpr program auditor (the one "
                         "checker that imports jax and traces; the "
                         "pure-AST checkers run in milliseconds)")
    an.add_argument("--json", action="store_true",
                    help="one machine-readable JSON line instead of "
                         "the report")
    an.set_defaults(fn=cmd_analyze)

    i = sub.add_parser("info", help="print asset summary")
    i.add_argument("--asset", default="synthetic")
    i.add_argument("--side", default=None, choices=[None, "left", "right", "neutral"])
    i.set_defaults(fn=cmd_info)

    v = sub.add_parser(
        "verify",
        help="audit a MANO asset (official .pkl/.npz) against the public "
             "structural facts + numeric invariants; print canonical "
             "digests")
    v.add_argument("asset", help="asset path (.pkl official/dumped, .npz)")
    v.add_argument("--side", default=None, choices=[None, "left", "right", "neutral"])
    v.add_argument("--golden", default=None,
                   help="second asset to diff numerically (e.g. the .npz "
                        "converted from a known-good pickle)")
    v.add_argument("--expect", default=None,
                   help="expected combined sha256 (pin a verified digest "
                        "in CI)")
    v.add_argument("--json", action="store_true",
                   help="machine-readable report")
    v.set_defaults(fn=cmd_verify)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
