"""mano_hand_tpu — a TPU-native (JAX/XLA) framework for the MANO hand model.

Built from scratch with the capability surface of reyuwei/MANO-Hand
(reference mounted at /root/reference), re-designed TPU-first: a pure,
jitted, vmapped, differentiable forward core; a float64 NumPy oracle; an
asset pipeline; gradient-based pose/shape fitting; and mesh-sharded
multi-chip execution via jax.sharding.
"""

from mano_hand_tpu import constants
from mano_hand_tpu.assets import (
    ManoParams,
    load_model,
    synthetic_pair,
    synthetic_params,
)
from mano_hand_tpu.models import (
    ManoOutput,
    decode_pca,
    forward,
    forward_batched,
    forward_chunked,
    forward_pca,
    keypoints,
)
from mano_hand_tpu.models.layer import MANOModel

__version__ = "0.1.0"
