"""Deterministic fault injection for device-call sites (PR 3 tentpole).

Every failure mode the axon tunnel has shown in production — hang
forever in a C-level RPC, transient gRPC-style error, persistent error
(an hours-long outage), latency spike, silent wrong output — becomes a
schedulable event that a ``ChaosPlan`` injects into any wrapped
callable (a compiled bucket executable, a device transfer, a probe).
The plan is driven by a per-plan CALL INDEX, not wall clock or
randomness, so the quick test lane reproduces each tunnel pathology
on CPU bit-for-bit, run after run (tests/test_runtime.py).

Plan spec grammar (``parse_plan``) — comma-separated events::

    KIND[:PARAM]@SEL[%LANE]

    KIND   hang      block until the plan's ``release`` event is set
                     (the unkillable-RPC stand-in; a supervised caller
                     deadline-kills it, an unsupervised one wedges —
                     exactly like the real tunnel)
           error     raise InjectedFault(transient=True) whose message
                     carries "UNAVAILABLE" (the gRPC marker class
                     supervise.classify_failure treats as retryable)
           fatal     raise InjectedFault(transient=False) ("INVALID_
                     ARGUMENT" marker — the compile-error class that
                     must NOT be retried)
           latency   sleep PARAM seconds, then run the call
           sat       saturation throttle: sleep PARAM seconds, then run
                     the call — mechanically a latency event, but named
                     for its role: an OPEN-ended sat plan ("sat:T@0-")
                     models the slow-device half of an overload (every
                     dispatch pays T, so device throughput is capped
                     and a sustained arrival rate above it grows the
                     backlog). The arrival-burst half lives in the
                     DRIVER (serving/measure.py:overload_drill_run's
                     burst submitter) — chaos wraps device calls, so it
                     can slow the service rate but cannot generate load
           wrong     run the call, return the result + PARAM (default
                     1.0): the silent-corruption mode that motivates
                     probing numerics in the shipped compilation
                     context (CLAUDE.md rule)
    SEL    N         exactly call index N (0-based)
           N-M       calls N..M inclusive (N <= M)
           N-        every call from N onward (a persistent outage)
           *         every call
           T1s-T2s   (PR 19) TIME window: every call whose arrival
                     falls in [T1, T2) seconds after ``schedule()``
                     (epoch = the monotonic clock at schedule time;
                     fractional seconds fine). Call-index selectors
                     describe the device's own dispatch sequence; a
                     time window describes the OUTSIDE world — "the
                     tunnel browns out 2 s into the drill, for 1 s" —
                     which is what an arrival-correlated fault burst
                     under a traffic trace (serving/traffic.py) needs:
                     the fault window lands at a trace offset no
                     matter how many dispatches the controller's
                     batching happened to produce first. Both ends
                     must carry the ``s`` suffix (mixed domains are a
                     typo), T1 < T2 strictly (an instant matches no
                     interval), and ``T1s-`` is the open-ended form.
    LANE   N         (PR 13) restrict the event to callables wrapped
                     with ``wrap(..., lane=N)`` — a per-device dispatch
                     lane (serving/lanes.py). A lane-tagged event is
                     indexed by that LANE'S OWN call counter, not the
                     plan-global one, so "kill exactly lane 2 from its
                     3rd dispatch on" stays deterministic however the
                     other lanes interleave. Untagged events keep the
                     historical plan-global index and hit every wrapped
                     callable, lane or not.

    "error@0-1"            two transient faults, then clean
    "hang@2"               call 2 wedges
    "error@0-"             persistent outage (never self-clears)
    "error@0-%1"           lane 1 alone goes down, siblings stay clean
    "latency:0.2@1-3"      200 ms spikes on calls 1-3
    "sat:0.02@0-"          every dispatch throttled 20 ms (saturation)
    "wrong:0.5@4"          call 4 silently returns verts + 0.5
    "error@2s-3s"          every call arriving 2-3 s into the plan
    "sat:0.05@1.5s-%0"     lane 0 throttled from 1.5 s onward

    Specs are VALIDATED at parse time: unknown kinds, malformed or
    misplaced ``:PARAM`` (hang/error/fatal take none; latency/sat
    require a non-negative one), non-integer or negative selector
    indices, inverted ranges (``N-M`` with N > M, which can match
    no call), and malformed ``%LANE`` tags all raise ``ValueError``
    with the offending token — a typo'd plan must fail the run, not
    silently inject nothing.

``schedule(spec)`` swaps the event list and resets the call index, so
one long-lived engine can be driven through a whole fault matrix
without rebuilding its executable caches (serving/measure.py's
recovery drill does exactly this).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np


class InjectedFault(RuntimeError):
    """A fault raised by a ChaosPlan. ``transient`` mirrors the real
    tunnel's split: retryable RPC blips vs deterministic failures."""

    def __init__(self, message: str, transient: bool = True):
        super().__init__(message)
        self.transient = transient


class FaultEvent:
    """One scheduled fault: ``kind`` over call indices [start, stop] —
    or, when ``t_start`` is set (PR 19), over the TIME window
    [t_start, t_stop) seconds after ``schedule()``. ``lane`` (PR 13)
    restricts it to one dispatch lane's callables; for index-domain
    events it also switches the index domain to that lane's own call
    counter (a time window is already interleave-independent, so the
    lane tag is purely a filter there)."""

    __slots__ = ("kind", "start", "stop", "param", "lane",
                 "t_start", "t_stop")

    def __init__(self, kind: str, start: int, stop: Optional[int],
                 param: float = 0.0, lane: Optional[int] = None,
                 t_start: Optional[float] = None,
                 t_stop: Optional[float] = None):
        self.kind = kind
        self.start = start
        self.stop = stop            # None = open-ended (persistent)
        self.param = param
        self.lane = lane            # None = every wrapped callable
        self.t_start = t_start      # None = call-index domain
        self.t_stop = t_stop        # None = open-ended window

    def matches(self, idx: int) -> bool:
        return idx >= self.start and (self.stop is None or idx <= self.stop)

    def matches_time(self, elapsed_s: float) -> bool:
        return (self.t_start is not None and elapsed_s >= self.t_start
                and (self.t_stop is None or elapsed_s < self.t_stop))

    def __repr__(self) -> str:  # test/log readability
        if self.t_start is not None:
            stop = "" if self.t_stop is None else f"{self.t_stop}s"
            sel = f"{self.t_start}s-{stop}"
        elif self.stop == self.start:
            sel = f"{self.start}"
        else:
            sel = f"{self.start}-{'' if self.stop is None else self.stop}"
        tag = "" if self.lane is None else f"%{self.lane}"
        return f"FaultEvent({self.kind}@{sel}{tag}, param={self.param})"


_KINDS = ("hang", "error", "fatal", "latency", "sat", "wrong")
# Which kinds take a ':PARAM' — and whether they REQUIRE one. A param on
# a kind that ignores it ("hang:2@0") is a typo'd latency/sat plan that
# would otherwise silently inject the wrong fault class.
_PARAM_REQUIRED = ("latency", "sat")
_PARAM_ALLOWED = ("latency", "sat", "wrong")


def _parse_index(text: str, token: str) -> int:
    try:
        idx = int(text)
    except ValueError:
        raise ValueError(
            f"chaos event {token!r}: selector index {text!r} is not an "
            "integer (expected N, N-M, N-, or *)") from None
    if idx < 0:
        raise ValueError(
            f"chaos event {token!r}: selector index {idx} is negative "
            "(call indices are 0-based)")
    return idx


def _parse_seconds(text: str, token: str) -> float:
    try:
        t = float(text)
    except ValueError:
        raise ValueError(
            f"chaos event {token!r}: time bound {text!r}s is not a "
            "number of seconds") from None
    if t < 0:
        raise ValueError(
            f"chaos event {token!r}: time bound {t}s is negative")
    return t


def _parse_event(token: str) -> FaultEvent:
    head, _, sel = token.partition("@")
    if not sel:
        raise ValueError(f"chaos event {token!r} lacks '@SELECTOR'")
    sel, pct, lane_s = sel.partition("%")
    if pct and not lane_s:
        raise ValueError(
            f"chaos event {token!r}: '%' lane tag needs a lane index "
            "(e.g. error@0-%1)")
    lane = _parse_index(lane_s, token) if pct else None
    if pct and not sel:
        raise ValueError(
            f"chaos event {token!r}: '%LANE' must follow a selector "
            "(e.g. error@0-%1)")
    kind, colon, param_s = head.partition(":")
    if kind not in _KINDS:
        raise ValueError(f"unknown chaos kind {kind!r} (one of {_KINDS})")
    if kind in _PARAM_REQUIRED and not param_s:
        raise ValueError(
            f"{kind} events need ':SECONDS' (e.g. {kind}:0.2)")
    if colon and kind not in _PARAM_ALLOWED:
        raise ValueError(
            f"chaos event {token!r}: {kind} takes no ':PARAM' "
            f"(only {_PARAM_ALLOWED} do)")
    if param_s:
        try:
            param = float(param_s)
        except ValueError:
            raise ValueError(
                f"chaos event {token!r}: param {param_s!r} is not a "
                "number") from None
        if kind in _PARAM_REQUIRED and param < 0:
            raise ValueError(
                f"chaos event {token!r}: {kind} seconds must be >= 0")
    else:
        param = 1.0 if kind == "wrong" else 0.0
    if sel == "*":
        return FaultEvent(kind, 0, None, param, lane)
    lo, dash, hi = sel.partition("-")
    # Time-window domain (PR 19): 's'-suffixed bounds. Both ends must
    # agree — "2s-5" (or "2-5s") is a typo that would otherwise parse
    # as a huge call index, silently injecting at the wrong place.
    time_lo, time_hi = lo.endswith("s"), hi.endswith("s")
    if time_lo or time_hi:
        if not dash:
            raise ValueError(
                f"chaos event {token!r}: a time selector needs a "
                "window, not an instant (T1s-T2s or T1s-; a bare "
                f"{sel!r} can match no call)")
        if not time_lo or (hi and not time_hi):
            raise ValueError(
                f"chaos event {token!r}: mixed selector domains — "
                "both window ends must carry the 's' suffix "
                "(e.g. 2s-3s), or neither (call indices)")
        t0 = _parse_seconds(lo[:-1], token)
        if not hi:
            return FaultEvent(kind, 0, None, param, lane,
                              t_start=t0, t_stop=None)
        t1 = _parse_seconds(hi[:-1], token)
        if t1 <= t0:
            raise ValueError(
                f"chaos event {token!r}: time window {t0}s-{t1}s is "
                "empty (need T1 < T2)")
        return FaultEvent(kind, 0, None, param, lane,
                          t_start=t0, t_stop=t1)
    start = _parse_index(lo, token)
    if not dash:
        return FaultEvent(kind, start, start, param, lane)
    if not hi:
        return FaultEvent(kind, start, None, param, lane)
    stop = _parse_index(hi, token)
    if stop < start:
        raise ValueError(
            f"chaos event {token!r}: range {start}-{stop} is inverted "
            "and would match no call")
    return FaultEvent(kind, start, stop, param, lane)


class ChaosPlan:
    """A deterministic, schedulable fault plan over wrapped callables.

    All callables wrapped by one plan share ONE call counter — faults
    land on the plan's dispatch timeline regardless of which bucket
    executable a given dispatch hits, matching how a tunnel outage hits
    whatever happens to be in flight.

    Thread-safe (the engine's dispatcher and a test driver both touch
    it). ``release`` frees any hung calls: test teardown / drill exit
    sets it so abandoned worker threads unwind instead of sleeping
    forever in the process.
    """

    def __init__(self, spec: str = ""):
        self._lock = threading.Lock()
        self._events: List[FaultEvent] = []
        self._calls = 0
        # Time-window epoch (PR 19): 's'-suffixed selectors measure
        # elapsed seconds from the most recent schedule() (monotonic —
        # never wall clock), so a plan scheduled at a trace's t=0
        # pins its fault windows to trace offsets.
        self._epoch = time.monotonic()
        # Per-lane call counters (PR 13): lane-tagged events index into
        # the tagged lane's own dispatch sequence, so one lane's fault
        # schedule is deterministic however its siblings interleave.
        self._lane_calls: dict = {}
        self.faults_injected = 0
        self.release = threading.Event()
        if spec:
            self.schedule(spec)

    # -------------------------------------------------------------- control
    def schedule(self, spec: str) -> "ChaosPlan":
        """Replace the event list and restart the call index at 0
        (``faults_injected`` keeps accumulating — it is the plan's
        lifetime audit trail, snapshotted per phase by callers)."""
        events = [_parse_event(t.strip())
                  for t in spec.split(",") if t.strip()]
        with self._lock:
            self._events = events
            self._calls = 0
            self._lane_calls = {}
            self._epoch = time.monotonic()
        return self

    def clear(self) -> None:
        """Drop every scheduled event (the fault 'clears' — recovery)."""
        with self._lock:
            self._events = []

    @property
    def calls(self) -> int:
        with self._lock:
            return self._calls

    def _next(self, lane: Optional[int] = None,
              ) -> Tuple[int, Optional[FaultEvent]]:
        with self._lock:
            idx = self._calls
            self._calls += 1
            lidx = None
            if lane is not None:
                lidx = self._lane_calls.get(lane, 0)
                self._lane_calls[lane] = lidx + 1
            elapsed = time.monotonic() - self._epoch

            def fires(e: FaultEvent) -> bool:
                if e.lane is not None and e.lane != lane:
                    return False
                if e.t_start is not None:
                    return e.matches_time(elapsed)
                return e.matches(idx if e.lane is None else lidx)

            ev = next((e for e in self._events if fires(e)), None)
            if ev is not None:
                self.faults_injected += 1
            # Report the index in the DOMAIN the event matched on: an
            # untagged event firing on a lane call matched the
            # plan-global counter, and the fault message / on_fault
            # forensics must name an index that exists in the spec.
            report = (lidx if (lane is not None and ev is not None
                              and ev.lane is not None) else idx)
            return report, ev

    # ------------------------------------------------------------- wrapping
    def wrap(self, fn: Callable, on_fault: Optional[Callable] = None,
             lane: Optional[int] = None) -> Callable:
        """Wrap ``fn`` so each invocation consults the plan first.

        ``on_fault`` (e.g. ``ServingCounters.count_fault``) fires once
        per injected fault, before the fault takes effect. A hook that
        accepts two positional arguments (the engine's tracing hook,
        PR 8) is called as ``on_fault(kind, call_index)`` so the fault
        lands on the request timeline with its identity; anything else
        keeps the historical no-argument call. The arity is resolved
        ONCE at wrap time, not per dispatch.

        ``lane`` (PR 13) identifies this callable as dispatch lane N's
        (serving/lanes.py): ``%LANE``-tagged events fire only on the
        matching lane, indexed by that lane's own call counter, while
        untagged events keep hitting every wrapped callable on the
        plan-global index — a plan can kill exactly one lane while its
        siblings serve clean.
        """
        notify = None
        if on_fault is not None:
            import inspect

            try:
                positional = [
                    p for p in
                    inspect.signature(on_fault).parameters.values()
                    if p.kind in (p.POSITIONAL_ONLY,
                                  p.POSITIONAL_OR_KEYWORD)]
                rich = len(positional) >= 2
            except (TypeError, ValueError):
                rich = False
            notify = ((lambda ev, idx: on_fault(ev.kind, idx)) if rich
                      else (lambda ev, idx: on_fault()))

        def chaotic(*args, **kwargs):
            idx, ev = self._next(lane)
            if ev is None:
                return fn(*args, **kwargs)
            if notify is not None:
                notify(ev, idx)
            if ev.kind == "hang":
                # The unkillable-RPC stand-in: block until released.
                # A supervised caller abandons this (daemon) thread at
                # its deadline; the raise after release keeps a stale
                # result from ever surfacing.
                self.release.wait()
                raise InjectedFault(
                    f"chaos: hang at call {idx} released", transient=True)
            if ev.kind == "error":
                raise InjectedFault(
                    f"chaos: UNAVAILABLE injected transient RPC error "
                    f"at call {idx}", transient=True)
            if ev.kind == "fatal":
                raise InjectedFault(
                    f"chaos: INVALID_ARGUMENT injected deterministic "
                    f"failure at call {idx}", transient=False)
            if ev.kind in ("latency", "sat"):
                # sat is semantically a sustained throughput throttle;
                # mechanically both sleep, then run the call.
                time.sleep(ev.param)
                return fn(*args, **kwargs)
            # wrong: silent corruption — runs the call, skews the result.
            return np.asarray(fn(*args, **kwargs)) + ev.param

        return chaotic


def parse_plan(spec: str) -> ChaosPlan:
    """``spec`` (grammar above) -> a fresh ChaosPlan."""
    return ChaosPlan(spec)


# --------------------------------------------------------------------------
# Process-level campaign (PR 20): the same spec-string discipline, one
# level up. A ChaosPlan wraps CALLABLES (a device dispatch dies); a
# ChaosCampaign schedules WHOLE-PROCESS events (a worker dies, the
# proxy dies, a backend partitions, a cold page is damaged) against a
# live fleet — the faults the self-healing tier exists to absorb.
# --------------------------------------------------------------------------

_CAMPAIGN_KINDS = ("kill_worker", "kill_proxy", "partition", "damage_page")
# partition REQUIRES ':SECONDS' (how long the victim stays unreachable
# before the campaign lifts it); the kill/damage kinds are instants and
# take none — same typo-hardening stance as the call-level grammar.
_CAMPAIGN_PARAM_REQUIRED = ("partition",)


class CampaignEvent:
    """One scheduled process-level fault: ``kind`` fired ``at_s``
    seconds after ``ChaosCampaign.start()``."""

    __slots__ = ("kind", "at_s", "param")

    def __init__(self, kind: str, at_s: float, param: float = 0.0):
        self.kind = kind
        self.at_s = at_s
        self.param = param

    def __repr__(self) -> str:
        p = f":{self.param}" if self.param else ""
        return f"CampaignEvent({self.kind}{p}@{self.at_s}s)"


def parse_campaign(spec: str) -> List[CampaignEvent]:
    """Campaign spec -> time-ordered events. Grammar (the call-level
    spec's shape, with the selector REQUIRED to be a time instant —
    process events live on the wall, not on a dispatch counter)::

        KIND[:PARAM]@Ts[, ...]

        kill_worker@2s              SIGKILL one seeded-picked worker
        kill_proxy@4s               SIGKILL the active proxy
        partition:1.5@6s            one backend unreachable for 1.5 s
        damage_page@8s              corrupt one cold row page

    Validated at parse time like ``parse_plan``: unknown kinds,
    call-index selectors (no ``s`` suffix), windows (``T1s-T2s`` — a
    process kill is an instant), missing/forbidden ``:PARAM``, and
    negative times all raise ValueError with the offending token.
    Ties fire in spec order (stable sort)."""
    events = []
    for token in (t.strip() for t in spec.split(",") if t.strip()):
        head, _, sel = token.partition("@")
        if not sel:
            raise ValueError(f"campaign event {token!r} lacks '@Ts'")
        if "-" in sel:
            raise ValueError(
                f"campaign event {token!r}: campaign selectors are "
                "instants (KIND@Ts), not windows")
        if not sel.endswith("s"):
            raise ValueError(
                f"campaign event {token!r}: selector {sel!r} must be "
                "a time instant with the 's' suffix (e.g. @2s) — "
                "process events live on the wall clock, not a call "
                "index")
        at_s = _parse_seconds(sel[:-1], token)
        kind, colon, param_s = head.partition(":")
        if kind not in _CAMPAIGN_KINDS:
            raise ValueError(
                f"unknown campaign kind {kind!r} (one of "
                f"{_CAMPAIGN_KINDS})")
        if kind in _CAMPAIGN_PARAM_REQUIRED and not param_s:
            raise ValueError(
                f"{kind} events need ':SECONDS' (e.g. {kind}:1.5@2s)")
        if colon and kind not in _CAMPAIGN_PARAM_REQUIRED:
            raise ValueError(
                f"campaign event {token!r}: {kind} takes no ':PARAM' "
                f"(only {_CAMPAIGN_PARAM_REQUIRED} do)")
        if param_s:
            param = _parse_seconds(param_s, token)
        else:
            param = 0.0
        events.append(CampaignEvent(kind, at_s, param))
    events.sort(key=lambda e: e.at_s)
    return events


class ChaosCampaign:
    """A deterministic seeded schedule of process-level faults driven
    against a live fleet.

    The DRILL registers one handler per kind (``on``) — the campaign
    owns WHEN and (via :meth:`pick`) WHICH, the handler owns HOW (it
    holds the fleet/proxy/store references; the campaign imports
    nothing above runtime/). Handlers run on the campaign's driver
    thread, exceptions are captured into the audit trail rather than
    killing the campaign mid-drill (a chaos harness that dies on its
    own fault is useless), and every firing lands in ``events_fired``
    with its measured offset — the drill's schedule-vs-actual
    forensics.

    Determinism: victim selection draws from ONE ``numpy`` Generator
    seeded at construction, consumed in event order on the single
    driver thread, over the SORTED candidate list the handler passes
    to :meth:`pick` — same seed + same alive-sets = same victims,
    run after run (the ChaosPlan philosophy at process scope)."""

    def __init__(self, spec: str, seed: int = 0, log=None):
        self.events = parse_campaign(spec)
        self._rng = np.random.default_rng(seed)
        self.seed = int(seed)
        self._handlers: dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._log = log
        self.events_fired: List[dict] = []

    # ------------------------------------------------------------- wiring
    def on(self, kind: str, handler: Callable) -> "ChaosCampaign":
        """Register ``handler(event) -> json-able result`` for one
        kind; chainable. The result (e.g. the victim's name) lands in
        the audit trail."""
        if kind not in _CAMPAIGN_KINDS:
            raise ValueError(
                f"unknown campaign kind {kind!r} (one of "
                f"{_CAMPAIGN_KINDS})")
        self._handlers[kind] = handler
        return self

    def pick(self, candidates):
        """Seeded choice over ``sorted(candidates)`` — handlers call
        this at FIRE time so the victim set reflects who is actually
        alive (a worker healed since the last kill is back in the
        pool). None when the pool is empty."""
        cands = sorted(candidates)
        if not cands:
            return None
        return cands[int(self._rng.integers(len(cands)))]

    # ------------------------------------------------------------- driving
    def start(self) -> "ChaosCampaign":
        """Drive the schedule on a daemon thread (the drill's streams
        keep flowing while faults land). Every scheduled kind must
        have a handler — a campaign that silently skips events would
        read as 'survived' without being tested."""
        missing = sorted({e.kind for e in self.events}
                         - set(self._handlers))
        if missing:
            raise RuntimeError(
                f"campaign kinds with no handler: {missing}")
        if self._thread is not None:
            raise RuntimeError("campaign already started")
        self._thread = threading.Thread(
            target=self._drive, name="mano-chaos-campaign", daemon=True)
        self._thread.start()
        return self

    def _drive(self) -> None:
        epoch = time.monotonic()
        for ev in self.events:
            delay = ev.at_s - (time.monotonic() - epoch)
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            entry = {"kind": ev.kind, "at_s": ev.at_s,
                     "param": ev.param,
                     "fired_s": round(time.monotonic() - epoch, 3)}
            try:
                entry["result"] = self._handlers[ev.kind](ev)
            except Exception as e:  # noqa: BLE001 — audit, don't die
                entry["error"] = f"{type(e).__name__}: {e}"
            with self._lock:
                self.events_fired.append(entry)
            if self._log is not None:
                self._log(f"[campaign] {entry}")

    def join(self, timeout_s: float = 60.0) -> bool:
        """Wait for the schedule to finish; False on timeout."""
        t = self._thread
        if t is None:
            return True
        t.join(timeout_s)
        return not t.is_alive()

    def stop(self) -> None:
        """Abandon the remaining schedule (drill teardown)."""
        self._stop.set()

    def fired(self) -> List[dict]:
        """A snapshot of the audit trail (one lock hold)."""
        with self._lock:
            return [dict(e) for e in self.events_fired]
