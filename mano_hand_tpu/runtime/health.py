"""Device health state machine / circuit breaker for the serving paths.

The tunnel's outage profile (10-15 h, r3/r4) makes per-call retries the
wrong tool past the first seconds: every retry burns a deadline worth
of wall clock against a device that is simply GONE. The breaker turns
repeated failures into a STATE — healthy -> degraded -> down — so the
engine stops paying the primary path and fails over to CPU, and
re-probes the device on a bounded cadence until it comes back.

Probing is the dangerous part, with two hard-won rules baked in:

* a probe must be KILLABLE: `jax.devices()` on a wedged tunnel HANGS
  the calling process (BENCH_r01), so the default probe runs it in a
  subprocess via ``supervise.run_python`` and kills on timeout — never
  in-process;
* a probe must STAND DOWN for the driver bench's priority claim
  (utils/devicelock.py): a recovering engine hammering `jax.devices()`
  during the authoritative end-of-round bench window is exactly the
  contention class the device lock exists to prevent. While the claim
  is fresh the breaker stays open without probing.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from mano_hand_tpu.runtime import supervise

HEALTHY = "healthy"
DEGRADED = "degraded"
DOWN = "down"

# Same platform-selection caveat as bench.py's probe: a site hook on
# this image overrides JAX_PLATFORMS at interpreter startup, so the
# probe must select platforms through the config API.
_PROBE_CODE = (
    "import jax;"
    "plat = {platform!r};"
    "plat and jax.config.update('jax_platforms', plat);"
    "d = jax.devices();"
    "print(d[0].platform + ':' + d[0].device_kind)"
)


def device_probe(platform: str = "", timeout_s: float = 30.0) -> bool:
    """Probe backend liveness in a killable subprocess (True = alive)."""
    return supervise.run_python(
        _PROBE_CODE.format(platform=platform), timeout_s).ok


class CircuitBreaker:
    """healthy -> degraded -> down, with killable re-probe to close.

    * ``record_failure()``: one failed primary attempt. The state moves
      to DEGRADED immediately and to DOWN once ``failure_threshold``
      CONSECUTIVE failures accumulate.
    * ``record_success()``: a primary success resets to HEALTHY.
    * ``allow_primary()``: the dispatch-time gate. True while not DOWN.
      When DOWN it re-probes on a bounded cadence — skipping entirely
      while a driver priority claim is fresh (see module docstring) —
      and a successful probe closes the breaker (HEALTHY) and returns
      True, restoring the primary path; the still-warm executable
      caches make that failback recompile-free (asserted in
      tests/test_runtime.py).

    The re-probe cadence is OUTAGE-LENGTH-AWARE (PR 13): each
    consecutive FAILED probe multiplies the interval by
    ``probe_backoff`` up to ``probe_interval_cap_s`` (default
    ``32 * probe_interval_s``), and any successful probe (or primary
    success) resets it to ``probe_interval_s``. The tunnel's outages
    run hours (r3: ~10 h, r4: 15+ h) — a fleet of N per-lane breakers
    (serving/lanes.py) probing a downed backend at a CONSTANT interval
    multiplies killable-subprocess spawns by N exactly when the box
    should be spending itself on the surviving lanes; the exponential
    schedule keeps the first re-probe prompt (a blip recovers fast)
    while a long outage converges to one cheap probe per cap window.

    Thread-safe; the probe itself runs outside the lock (it can take
    ``probe timeout`` seconds — other dispatchers keep failing over to
    CPU meanwhile instead of queueing on the lock).
    """

    def __init__(
        self,
        failure_threshold: int = 3,
        probe: Optional[Callable[[], bool]] = None,
        probe_interval_s: float = 30.0,
        probe_backoff: float = 2.0,
        probe_interval_cap_s: Optional[float] = None,
        respect_priority_claim: bool = True,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, str], None]] = None,
    ):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        if probe_backoff < 1.0:
            raise ValueError(
                f"probe_backoff must be >= 1.0 (a shrinking re-probe "
                f"interval hammers a downed backend), got {probe_backoff}")
        self.failure_threshold = int(failure_threshold)
        self.probe = probe if probe is not None else device_probe
        self.probe_interval_s = float(probe_interval_s)
        self.probe_backoff = float(probe_backoff)
        self.probe_interval_cap_s = (
            32.0 * self.probe_interval_s if probe_interval_cap_s is None
            else float(probe_interval_cap_s))
        if self.probe_interval_cap_s < self.probe_interval_s:
            raise ValueError(
                f"probe_interval_cap_s {self.probe_interval_cap_s} < "
                f"probe_interval_s {self.probe_interval_s}")
        self.respect_priority_claim = bool(respect_priority_claim)
        self.clock = clock
        # Observability hook (PR 8): called as ``on_transition(old,
        # new)`` on every state CHANGE, outside the breaker lock (the
        # hook may take its own — e.g. an obs.Tracer appending the
        # transition to the request timeline). A tracing ServingEngine
        # wires this automatically when the slot is free.
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = HEALTHY
        self._consecutive_failures = 0
        self._last_probe_t: Optional[float] = None
        self._probing = False
        self._failed_probes = 0    # consecutive — drives the backoff
        self.probes = 0            # lifetime probe attempts (audit)
        self.opens = 0             # times the breaker tripped to DOWN

    # -------------------------------------------------------------- state
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def consecutive_failed_probes(self) -> int:
        """Failed re-probes since the last success — the backoff
        exponent (telemetry; the drill asserts the schedule grew)."""
        with self._lock:
            return self._failed_probes

    def probe_due(self) -> bool:
        """Cheap, non-probing check: would ``allow_primary()`` run a
        re-probe right now? The lane placement path (serving/lanes.py)
        uses this to kick a DOWN lane's re-probe onto a disposable
        thread WITHOUT paying the probe (or even a thread spawn) on
        the dispatch path when none is due."""
        with self._lock:
            if self._state != DOWN or self._probing:
                return False
            if self.respect_priority_claim:
                from mano_hand_tpu.utils import devicelock

                if devicelock.priority_claim_active():
                    return False
            return (self._last_probe_t is None
                    or self.clock() - self._last_probe_t
                    >= self._probe_wait_locked())

    def probe_wait_s(self) -> float:
        """The CURRENT re-probe interval: ``probe_interval_s`` grown
        ``probe_backoff``-fold per consecutive failed probe, capped at
        ``probe_interval_cap_s``."""
        with self._lock:
            return self._probe_wait_locked()

    def _probe_wait_locked(self) -> float:
        return min(self.probe_interval_cap_s,
                   self.probe_interval_s
                   * self.probe_backoff ** self._failed_probes)

    def _notify(self, old: str, new: str) -> None:
        """Fire ``on_transition`` for a state CHANGE — outside the
        lock, and never letting a broken hook poison the dispatch path
        that carried the state change."""
        if old != new and self.on_transition is not None:
            try:
                self.on_transition(old, new)
            except Exception:  # noqa: BLE001 — telemetry, not control
                pass

    def reset(self) -> None:
        with self._lock:
            old = self._state
            self._state = HEALTHY
            self._consecutive_failures = 0
            self._last_probe_t = None
            self._failed_probes = 0
        self._notify(old, HEALTHY)

    def record_failure(self) -> str:
        with self._lock:
            old = self._state
            self._consecutive_failures += 1
            if self._consecutive_failures >= self.failure_threshold:
                if self._state != DOWN:
                    self.opens += 1
                self._state = DOWN
            elif self._state == HEALTHY:
                self._state = DEGRADED
            new = self._state
        self._notify(old, new)
        return new

    def record_success(self) -> str:
        with self._lock:
            old = self._state
            self._consecutive_failures = 0
            self._failed_probes = 0
            self._state = HEALTHY
        self._notify(old, HEALTHY)
        return HEALTHY

    # ----------------------------------------------------------- the gate
    def allow_primary(self) -> bool:
        with self._lock:
            if self._state != DOWN:
                return True
            if self.respect_priority_claim:
                # Lazy import so CPU-only users never touch the lock
                # module's env resolution unless a breaker actually
                # opens with claim-awareness on.
                from mano_hand_tpu.utils import devicelock

                if devicelock.priority_claim_active():
                    # The driver bench owns the device window: no
                    # probes, no primary traffic, stay failed over.
                    return False
            now = self.clock()
            if (self._probing
                    or (self._last_probe_t is not None
                        and now - self._last_probe_t
                        < self._probe_wait_locked())):
                return False
            self._probing = True       # one prober at a time
            self._last_probe_t = now
            self.probes += 1
        try:
            ok = bool(self.probe())
        except Exception:  # noqa: BLE001 — a crashing probe is a failed one
            ok = False
        with self._lock:
            self._probing = False
            old = self._state
            if ok:
                self._state = HEALTHY
                self._consecutive_failures = 0
                self._failed_probes = 0
            else:
                # One more failed re-probe: the NEXT wait doubles (up
                # to the cap) — the outage-length-aware schedule.
                self._failed_probes += 1
        if ok:
            self._notify(old, HEALTHY)
        return ok


def failover_ladder(failed: int, n_lanes: int, backlog_rows,
                    allow: Callable[[int], bool]):
    """Sibling order for the per-lane failover LADDER (PR 13):
    device -> least-loaded healthy sibling lane -> CPU tier.

    Given the index of the lane whose primary dispatch just exhausted
    supervision, returns its sibling lane indices in the order the
    dispatcher should try them: every sibling ``allow`` admits (its
    breaker not DOWN), least-backlogged first (``backlog_rows`` maps
    lane index -> queued+in-flight rows), index as the tie-break so
    the order is deterministic under equal load. The CPU degradation
    tier is NOT in the list — it is the ladder's implicit last rung,
    owned by the caller (serving/lanes.py), exactly as the PR-3
    single-device breaker handed "device -> CPU"; this function only
    generalizes the middle rung. An empty list means every sibling is
    down too: go straight to CPU.
    """
    sibs = [i for i in range(int(n_lanes)) if i != failed and allow(i)]
    sibs.sort(key=lambda i: (backlog_rows.get(i, 0), i))
    return sibs
