"""Fault-tolerant device runtime (PR 3 tentpole).

The operational defenses bench.py and scripts/ accreted against the
flaky TPU tunnel — killable probes, deadline watchdogs, bounded
classified retries, priority-claim awareness — promoted into a tested,
reusable subsystem the library itself uses:

* ``runtime.chaos``     — deterministic fault injection (hang, transient
                          /persistent error, latency spike, silent wrong
                          output) so every tunnel failure mode reproduces
                          on CPU in the quick test lane;
* ``runtime.supervise`` — supervised calls (per-attempt deadlines on a
                          disposable thread, exponential backoff +
                          jitter, transient-vs-deterministic failure
                          classification), the unified ``Watchdog``
                          thread, and the killable-subprocess escalation
                          path (``run_python``);
* ``runtime.health``    — the healthy/degraded/down circuit breaker with
                          killable re-probe and device-lock awareness.

``serving.ServingEngine`` composes all three through a
``DispatchPolicy``: supervised per-batch dispatch, breaker-gated CPU
graceful degradation, and recompile-free failback.
"""

from mano_hand_tpu.runtime.chaos import (
    ChaosPlan,
    FaultEvent,
    InjectedFault,
    parse_plan,
)
from mano_hand_tpu.runtime.health import (
    DEGRADED,
    DOWN,
    HEALTHY,
    CircuitBreaker,
    device_probe,
)
from mano_hand_tpu.runtime.supervise import (
    DETERMINISTIC,
    TRANSIENT,
    DeadlineExceeded,
    DispatchPolicy,
    RetriesExhausted,
    Watchdog,
    backoff_delay,
    call_with_deadline,
    classify_failure,
    run_python,
    supervised_call,
)

__all__ = [
    "ChaosPlan",
    "FaultEvent",
    "InjectedFault",
    "parse_plan",
    "CircuitBreaker",
    "device_probe",
    "HEALTHY",
    "DEGRADED",
    "DOWN",
    "DispatchPolicy",
    "DeadlineExceeded",
    "RetriesExhausted",
    "Watchdog",
    "TRANSIENT",
    "DETERMINISTIC",
    "backoff_delay",
    "call_with_deadline",
    "classify_failure",
    "run_python",
    "supervised_call",
]
