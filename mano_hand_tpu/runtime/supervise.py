"""Supervised execution of device calls: deadlines, retries, watchdogs.

bench.py grew these defenses one incident at a time (BENCH_r01 hung
init, r3's unbounded retry loop, r4's empty stdout, r5's undeliverable
SIGTERM); this module is their promotion into ONE audited code path the
library itself can use (serving/engine.py's dispatch loop, the fitting
wrappers' opt-in supervision, cli.py's serve-bench watchdog).

Why SIGTERM is insufficient — the fact every primitive here is built
around: a tunnel drop mid-dispatch leaves the calling thread blocked
inside a C-level PJRT RPC. CPython delivers signal handlers only on the
MAIN thread, between bytecodes — a thread parked in a C call never
reaches the next bytecode, so SIGTERM is accepted by the process and
then never acted on (observed live, r5: 20 min at ~1% CPU, TERM no-op,
only SIGKILL landed). The survivable defenses are therefore:

* run the risky call on a DISPOSABLE worker thread and bound the wait
  (``call_with_deadline``) — the wedged thread is abandoned (daemon),
  the caller gets ``DeadlineExceeded`` and keeps its guarantees;
* keep a daemon WATCHDOG thread that can still run while the main
  thread is wedged — a blocked RPC releases the GIL — and have it
  escalate (emit artifacts, ``os._exit``) (``Watchdog``);
* for work that must be KILLABLE for real (backend probes that can hang
  the whole process at init), run it in a SUBPROCESS and ``kill()`` it
  (``run_python``) — SIGKILL is the one signal a wedged RPC cannot
  block, and it only works from outside the process.

Failure classification: retrying a deterministic failure (a compile
error, a shape mismatch) burns the retry budget reproducing the same
crash — exactly the r3 bare-retry-loop incident generalized. So
``supervised_call`` retries only what ``classify_failure`` deems
transient, with exponential backoff + jitter bounded by a cap.
"""

from __future__ import annotations

import random
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

TRANSIENT = "transient"
DETERMINISTIC = "deterministic"

# gRPC/PJRT status markers that indicate the tunnel, not the program:
# worth a bounded retry. INVALID_ARGUMENT et al. are deliberately absent
# — those are compile/shape errors that reproduce deterministically.
_TRANSIENT_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED", "CANCELLED",
    "UNKNOWN: ", "INTERNAL: ", "connection reset", "connection refused",
    "socket closed", "broken pipe", "tunnel",
)


class DeadlineExceeded(RuntimeError):
    """A supervised call outlived its deadline and was abandoned."""


class RetriesExhausted(RuntimeError):
    """Every allowed attempt of a supervised call failed transiently."""

    def __init__(self, message: str, cause: BaseException, attempts: int):
        super().__init__(message)
        self.cause = cause
        self.attempts = attempts


def classify_failure(exc: BaseException) -> str:
    """``transient`` (bounded retry is rational) or ``deterministic``
    (retrying reproduces the failure — never retry).

    Unknown exception types default to DETERMINISTIC: the r3 incident
    showed an optimistic retry loop is worse than a clean failure.
    """
    transient = getattr(exc, "transient", None)
    if transient is not None:          # chaos.InjectedFault and friends
        return TRANSIENT if transient else DETERMINISTIC
    if isinstance(exc, DeadlineExceeded):
        return TRANSIENT
    if isinstance(exc, (ValueError, TypeError, KeyError, IndexError,
                        AttributeError, NotImplementedError,
                        ZeroDivisionError, AssertionError)):
        return DETERMINISTIC
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return TRANSIENT
    msg = f"{type(exc).__name__}: {exc}"
    if any(marker in msg for marker in _TRANSIENT_MARKERS):
        return TRANSIENT
    return DETERMINISTIC


def call_with_deadline(fn: Callable, deadline_s: Optional[float],
                       name: str = "supervised-call"):
    """Run ``fn()`` with a hard wall-clock bound.

    ``deadline_s=None`` calls inline (no thread). Otherwise the call
    runs on a disposable daemon thread; if it has not finished inside
    the deadline the thread is ABANDONED (it cannot be killed — see the
    module docstring) and ``DeadlineExceeded`` raises in the caller.
    The abandoned thread's eventual result/exception is discarded.
    """
    if deadline_s is None:
        return fn()
    box: list = []

    def run() -> None:
        try:
            box.append((True, fn()))
        except BaseException as e:  # noqa: BLE001 — relayed to the caller
            box.append((False, e))

    t = threading.Thread(target=run, name=name, daemon=True)
    t.start()
    t.join(deadline_s)
    if not box:
        # Still running (or died without reporting — impossible short of
        # interpreter teardown): the caller moves on, the thread is
        # leaked by design.
        raise DeadlineExceeded(
            f"{name} exceeded its {deadline_s:.3g}s deadline and was "
            "abandoned (a wedged device RPC cannot be interrupted "
            "in-process — only a subprocess kill -9 truly clears one)")
    ok, payload = box[0]
    if ok:
        return payload
    raise payload


def backoff_delay(attempt: int, base_s: float, cap_s: float,
                  jitter: float, rng: Optional[random.Random] = None,
                  ) -> float:
    """Exponential backoff with full-ish jitter: ``base * 2^attempt``
    capped at ``cap_s``, scaled by a uniform factor in
    ``[1 - jitter, 1 + jitter]``. ``jitter=0`` is fully deterministic
    (tests)."""
    delay = min(base_s * (2.0 ** attempt), cap_s)
    if jitter:
        r = rng if rng is not None else random
        delay *= 1.0 + jitter * (2.0 * r.random() - 1.0)
    return max(0.0, delay)


def batch_give_up_by(deadlines) -> Optional[float]:
    """The end-to-end supervision bound for one coalesced batch: the
    LATEST member deadline, or None when any member is deadline-less
    (one unbounded consumer keeps the whole batch's budget unbounded).

    THE shared reconstruction for ``give_up_by`` — used by the engine's
    dispatch path (serving/engine.py), the lane ladder
    (serving/lanes.py), and the pipelined completion stage (PR 17), so
    the rule cannot drift between them. The deadlines are absolute
    ``time.monotonic`` timestamps, which is what makes the bound
    survive the launch/completion split: a batch that sat queued in the
    completion stage has ALREADY spent that wait against the same
    absolute budget — ``supervised_call`` clips each attempt to
    ``give_up_by - clock()`` at attempt START, so no re-arming or
    budget hand-off is needed across the stage boundary.
    """
    deadlines = list(deadlines)
    if not deadlines or any(d is None for d in deadlines):
        return None
    return max(deadlines)


def supervised_call(
    fn: Callable,
    *,
    deadline_s: Optional[float] = None,
    retries: int = 0,
    backoff_s: float = 0.05,
    backoff_cap_s: float = 2.0,
    jitter: float = 0.5,
    give_up_by: Optional[float] = None,
    classify: Callable[[BaseException], str] = classify_failure,
    keep_trying: Optional[Callable[[], bool]] = None,
    on_retry: Optional[Callable] = None,
    on_deadline_kill: Optional[Callable] = None,
    on_attempt_failure: Optional[Callable] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    name: str = "supervised-call",
):
    """THE supervised dispatch primitive: ``fn()`` under a per-attempt
    deadline, with bounded classified retries.

    * deterministic failures raise IMMEDIATELY, unretried (a compile
      error rerun is the same compile error, minutes later);
    * transient failures (including deadline kills) are retried up to
      ``retries`` times with exponential backoff + jitter;
    * ``keep_trying`` (e.g. a circuit breaker's ``allow_primary``) is
      consulted before each retry so an opened breaker short-circuits
      the remaining budget;
    * ``give_up_by`` (a ``clock()``-domain timestamp — ``time.monotonic``
      by default) is an END-TO-END bound over ALL attempts: no retry
      starts past it, and each attempt's deadline is clipped to the
      remaining budget. The serving engine passes the latest request
      deadline of the batch here, so supervision never burns retry
      budget producing a result every caller has already expired out of
      (PR 5: shedding late work beats serving it);
    * hooks (``on_retry``/``on_deadline_kill``/``on_attempt_failure``)
      feed counters and breakers without coupling this module to them.

    Raises the deterministic failure as-is, or ``RetriesExhausted``
    (carrying ``.cause`` and ``.attempts``) when the budget runs out —
    including when ``give_up_by`` cut it short.
    """
    last: Optional[BaseException] = None
    attempts = 0
    for attempt in range(max(0, retries) + 1):
        if attempt > 0:
            if keep_trying is not None and not keep_trying():
                break
            if give_up_by is not None and clock() >= give_up_by:
                # The whole-call budget is spent: a retry now could only
                # finish after every consumer's deadline. RetriesExhausted
                # below carries the last transient cause.
                break
            sleep(backoff_delay(attempt - 1, backoff_s, backoff_cap_s,
                                jitter))
            if give_up_by is not None and clock() >= give_up_by:
                # The backoff itself consumed the remaining budget:
                # launching the attempt now would still start fn() on a
                # disposable thread (call_with_deadline only bounds the
                # JOIN) — a real dispatch for a result nobody will read,
                # and on the tunnel a thread that can wedge in a C-level
                # RPC. Checked AFTER the sleep so no retry ever starts
                # past give_up_by, as documented.
                break
            if on_retry is not None:
                on_retry()
        attempts += 1
        eff_deadline = deadline_s
        if give_up_by is not None:
            remaining = give_up_by - clock()
            # Clip, never extend (the attempt itself starts pre-budget;
            # only its join window shrinks).
            eff_deadline = (remaining if eff_deadline is None
                            else min(eff_deadline, remaining))
        try:
            return call_with_deadline(fn, eff_deadline, name=name)
        except DeadlineExceeded as e:
            last = e
            if on_deadline_kill is not None:
                on_deadline_kill()
            if on_attempt_failure is not None:
                on_attempt_failure()
        except BaseException as e:  # noqa: BLE001 — classified below
            if classify(e) == DETERMINISTIC:
                raise
            last = e
            if on_attempt_failure is not None:
                on_attempt_failure()
    raise RetriesExhausted(
        f"{name} failed {attempts} attempt(s); last: "
        f"{type(last).__name__}: {last}", cause=last, attempts=attempts)


@dataclass
class DispatchPolicy:
    """Supervision knobs for ``ServingEngine`` dispatch (serving/engine.py).

    ``deadline_s`` bounds each device call (None = unbounded — the
    pre-PR-3 behavior, kept for directly-attached devices where hangs
    are not a failure mode). ``breaker`` is a
    ``runtime.health.CircuitBreaker`` (None = no health tracking);
    ``chaos`` a ``runtime.chaos.ChaosPlan`` injected into the PRIMARY
    executables only (the fallback path stays clean, so failover is
    observable recovery, not roulette). ``cpu_fallback`` enables
    graceful degradation to CPU-bucketed executables when the primary
    path is exhausted or the breaker is open.
    """

    deadline_s: Optional[float] = None
    retries: int = 2
    backoff_s: float = 0.05
    backoff_cap_s: float = 2.0
    jitter: float = 0.5
    breaker: Optional[object] = None
    chaos: Optional[object] = None
    cpu_fallback: bool = True


class Watchdog:
    """The unified deadline/stall watchdog THREAD (satellite of PR 3).

    One implementation behind bench.py's ``--stall-timeout``/
    ``--emit-by``, cli.py serve-bench's hard-exit deadline, and any
    future long-running device loop. A daemon thread polls two
    triggers and fires ``on_trigger(cause)`` at most once:

    * **deadline**: ``now - t0 >= deadline_s`` — the artifact MUST be
      out before an external killer (the driver harness's ~30-min
      ``timeout``) cuts the process mid-line;
    * **stall**: no progress (caller-updated timestamp) for
      ``stall_s`` while ``armed()`` — the hung-RPC trigger; see the
      module docstring for why a signal handler cannot cover this.

    ``on_trigger`` runs ON the watchdog thread and typically ends in
    ``os._exit`` — it must not assume the main thread is runnable.
    """

    def __init__(
        self,
        on_trigger: Callable[[str], None],
        *,
        deadline_s: Optional[float] = None,
        stall_s: Optional[float] = None,
        t0: Optional[float] = None,
        progress: Optional[Callable[[], float]] = None,
        armed: Optional[Callable[[], bool]] = None,
        poll_s: float = 2.0,
        name: str = "watchdog",
        clock: Callable[[], float] = time.time,
        tracer=None,
    ):
        if stall_s and progress is None:
            raise ValueError("a stall trigger needs a progress() source")
        self.on_trigger = on_trigger
        # Observability hook (PR 8): a firing watchdog is the incident
        # class the flight recorder exists for — the kill lands on the
        # tracer's timeline (and triggers any recorder subscribed to
        # it) BEFORE on_trigger runs, because on_trigger typically ends
        # in os._exit.
        self.tracer = tracer
        self.deadline_s = deadline_s or None
        self.stall_s = stall_s or None
        self.t0 = clock() if t0 is None else t0
        self.progress = progress
        self.armed = armed
        self.poll_s = poll_s
        self.name = name
        self.clock = clock
        self._disarmed = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        if self.deadline_s is None and self.stall_s is None:
            return self  # nothing to watch: spawn no thread at all
        self._thread = threading.Thread(
            target=self._loop, name=self.name, daemon=True)
        self._thread.start()
        return self

    def disarm(self) -> None:
        """Permanently stand the watchdog down (e.g. the guarded phase
        finished, or the backend resolved to one that cannot hang)."""
        self._disarmed.set()

    def _fire(self, cause: str) -> None:
        if self.tracer is not None:
            try:
                self.tracer.incident("watchdog_kill", cause=cause)
            except Exception:  # noqa: BLE001 — the kill must still land
                pass
        self.on_trigger(cause)

    def _loop(self) -> None:
        while not self._disarmed.wait(self.poll_s):
            now = self.clock()
            if self.deadline_s and now - self.t0 >= self.deadline_s:
                self._fire(
                    f"{self.name}: emit-by deadline "
                    f"({self.deadline_s:.0f}s) hit")
                return
            if (self.stall_s and (self.armed is None or self.armed())
                    and now - self.progress() >= self.stall_s):
                self._fire(
                    f"{self.name}: no progress for {self.stall_s:.0f}s "
                    "(hung device RPC — tunnel drop mid-measurement?)")
                return


@dataclass
class ProbeResult:
    ok: bool
    out: str = ""
    err: str = ""
    rc: Optional[int] = None
    killed: bool = field(default=False)


def run_python(code: str, timeout_s: float) -> ProbeResult:
    """Run ``python -c code`` in a KILLABLE subprocess.

    The in-process primitives above can only abandon a wedged call;
    this is the escalation path that truly clears one — SIGKILL from
    outside the process (bench.py's backend-probe pattern, reusable).
    A hang past ``timeout_s`` is killed and reported, never waited out.
    """
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    except OSError as e:
        return ProbeResult(ok=False, err=f"spawn failed: {e}")
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return ProbeResult(ok=proc.returncode == 0, out=out.strip(),
                           err=err.strip(), rc=proc.returncode)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate()
        return ProbeResult(ok=False, out=(out or "").strip(),
                           err=f"probe hung > {timeout_s:.0f}s (killed)",
                           rc=proc.returncode, killed=True)
