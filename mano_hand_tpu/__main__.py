"""``python -m mano_hand_tpu`` — the CLI entry point (see cli.py)."""

import sys

from mano_hand_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
