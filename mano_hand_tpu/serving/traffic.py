"""Production traffic traces: deterministic, seeded, replayable
(PR 19).

Every drill so far paced load with hand-rolled loops (fixed-rate
waves, square bursts); a controller drill needs *shaped* load — the
arrival patterns production actually sees — and it needs the SAME
trace replayed under every leg (static vs controlled vs crashed), or
the comparison measures the generator, not the controller.  This
module generates arrival traces as plain data (a tuple of ``(t_s,
tier)`` pairs, offsets from trace start) from a seed and a named
shape:

* ``diurnal`` — one sinusoidal day compressed into the trace window:
  load swings between ``floor_fraction`` and 1.0 of ``peak_hz``.
* ``bursty``  — on/off square bursts (duty-cycled) over a baseline,
  the PR-13 lane-chaos arrival analogue.
* ``flash_crowd`` — steady baseline, then at ``crowd_at_fraction`` of
  the window the rate steps to ``peak_hz`` and decays exponentially
  back: the "everyone opened the app at once" shape the config22
  drill throws at the controller.

Arrivals come from an inhomogeneous Poisson process via Lewis
thinning: candidates at the peak rate, each kept with probability
``rate(t)/peak``.  All randomness is one ``random.Random(seed)``
(Mersenne Twister — bit-stable across platforms and Python builds in
a way re-seeded NumPy global state is not), so the determinism
contract is exact: same (kind, seed, knobs) → byte-identical
``serialize()`` output, pinned by test.  Tier assignment rides the
same stream (tier 0 with ``tier0_fraction``, else tier 1).

No wall clock anywhere — traces are pure offsets; the replayer
(``measure.py:control_drill_run``) owns pacing.
"""

from __future__ import annotations

import math
import random
from typing import Callable, List, Tuple

__all__ = ["TRACE_KINDS", "make_trace", "serialize", "trace_stats"]

TRACE_KINDS = ("diurnal", "bursty", "flash_crowd")


def _rate_fn(kind: str, duration_s: float, base_hz: float,
             peak_hz: float, *, floor_fraction: float,
             burst_duty: float, burst_period_s: float,
             crowd_at_fraction: float, crowd_decay_s: float,
             ) -> Callable[[float], float]:
    """rate(t) in arrivals/s for one named shape; peak_hz is the
    thinning envelope so every shape must stay <= peak_hz."""
    if kind == "diurnal":
        lo = floor_fraction * peak_hz

        def rate(t: float) -> float:
            # One full "day": trough at t=0, peak mid-window.
            phase = 2.0 * math.pi * (t / duration_s)
            return lo + (peak_hz - lo) * 0.5 * (1.0 - math.cos(phase))
        return rate
    if kind == "bursty":
        def rate(t: float) -> float:
            in_burst = (t % burst_period_s) < burst_duty * burst_period_s
            return peak_hz if in_burst else base_hz
        return rate
    if kind == "flash_crowd":
        t0 = crowd_at_fraction * duration_s

        def rate(t: float) -> float:
            if t < t0:
                return base_hz
            spike = (peak_hz - base_hz) * math.exp(-(t - t0)
                                                   / crowd_decay_s)
            return base_hz + spike
        return rate
    raise ValueError(
        f"unknown trace kind {kind!r}; expected one of {TRACE_KINDS}")


def make_trace(kind: str, *, seed: int, duration_s: float,
               base_hz: float, peak_hz: float,
               tier0_fraction: float = 0.5,
               floor_fraction: float = 0.2,
               burst_duty: float = 0.25,
               burst_period_s: float = 1.0,
               crowd_at_fraction: float = 0.35,
               crowd_decay_s: float = 1.0,
               ) -> Tuple[Tuple[float, int], ...]:
    """A seeded arrival trace: tuple of ``(t_offset_s, tier)`` sorted
    by time. Deterministic — same arguments, same bytes (see
    ``serialize``)."""
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0, got {duration_s}")
    if not 0.0 < base_hz <= peak_hz:
        raise ValueError(
            f"rates must satisfy 0 < base_hz <= peak_hz, got "
            f"({base_hz}, {peak_hz})")
    if not 0.0 <= tier0_fraction <= 1.0:
        raise ValueError(
            f"tier0_fraction must be in [0, 1], got {tier0_fraction}")
    rate = _rate_fn(kind, duration_s, base_hz, peak_hz,
                    floor_fraction=floor_fraction,
                    burst_duty=burst_duty,
                    burst_period_s=burst_period_s,
                    crowd_at_fraction=crowd_at_fraction,
                    crowd_decay_s=crowd_decay_s)
    rng = random.Random(seed)
    out: List[Tuple[float, int]] = []
    t = 0.0
    while True:
        # Lewis thinning: exponential gaps at the envelope rate,
        # accept each candidate with rate(t)/peak.
        t += rng.expovariate(peak_hz)
        if t >= duration_s:
            break
        if rng.random() * peak_hz <= rate(t):
            tier = 0 if rng.random() < tier0_fraction else 1
            out.append((t, tier))
    return tuple(out)


def serialize(trace: Tuple[Tuple[float, int], ...]) -> bytes:
    """Canonical bytes for a trace — fixed-precision offsets so the
    byte-identity determinism test has no float-repr ambiguity."""
    lines = [f"{t:.9f} {tier}" for t, tier in trace]
    return ("\n".join(lines) + "\n").encode("ascii")


def trace_stats(trace: Tuple[Tuple[float, int], ...]) -> dict:
    """Headline numbers for logs/artifacts: arrival counts by tier and
    the peak 100 ms-window rate (the number the admission bound has to
    survive)."""
    n0 = sum(1 for _, tier in trace if tier == 0)
    peak = 0
    win: List[float] = []
    for t, _ in trace:
        win.append(t)
        while win and win[0] < t - 0.1:
            win.pop(0)
        peak = max(peak, len(win))
    return {
        "arrivals": len(trace),
        "tier0": n0,
        "tier1": len(trace) - n0,
        "peak_rate_hz": peak * 10.0,
        "duration_s": trace[-1][0] if trace else 0.0,
    }
