"""Per-tier precision policy for the serving engine (PR 14).

The blend matmul runs at ~45% of bf16 peak and the whole serving hot
path was f32 (ROADMAP item 7; bench_results/r03_tpu_full1.json) — a
bf16 posed path is the single biggest untapped raw-speed lever left
after the PR-10 kernel fusion. It was too dangerous before: two silent
precision collapses in this repo's history were only ever caught by
on-chip probes. PR 9's NumericsSentinel changed the calculus — it
probes every live program family through the engine's OWN cached
executables in production — so a bf16 serving TIER can be continuously
guarded rather than hoped-correct.

The policy is deliberately narrow:

* **Only the baked-shape/pose (gathered) path ever serves bf16.** The
  steady-state interactive workload is ``submit(pose, subject=key)`` —
  matmul-dominated pose blend + skinning over baked subject rows
  (PAPER.md: shape blendshapes -> joint regression -> pose blendshapes
  -> LBS; the shape half is baked at ``specialize`` time). Full-path
  requests, fitting/batch tiers, the CPU-failover rung, and the PR-6
  AOT lattice ALL stay f32: the lattice's contract is bit-identity
  with the live f32 jit, failover is the clean reference tier every
  parity criterion measures against, and solvers live or die on f32
  conditioning (the measured LM dead-ends, docs/roadmap.md).
* **bf16 means bf16 compute with f32 accumulation.** The two MXU-bound
  contractions of the pose stage (pose-corrective blend, LBS skinning)
  take bf16 operands and accumulate into f32
  (``preferred_element_type`` — models/core.py ``compute_dtype``);
  FK/Rodrigues (tiny, conditioning-sensitive) and every residual add
  stay f32, and the served vertices are f32. Measured on this stack:
  ~4e-4 m max vertex error vs the f32 path — well inside the stated
  envelope below. On the fused Pallas tier the same policy selects the
  kernel's single-pass bf16 MXU form (ops/pallas_posed.py).
* **The envelope is part of the policy.** ``max_vertex_err_m`` is the
  STATED per-request vertex-error budget (meters) the bf16 tier must
  hold; the sentinel turns it into a standing guard (bf16 probes are
  judged against this envelope relative to the f32 truth — f32-digest
  equality is the wrong comparator for a reduced-precision family),
  and bench config17's ``judge_precision`` gates it per round.

Tiers not named in ``bf16_tiers`` default to f32 — an engine with no
policy at all is byte-for-byte the pre-PR-14 engine.
"""

from __future__ import annotations

import dataclasses
from typing import FrozenSet, Iterable

#: The compute dtypes a tier can be mapped to.
F32 = "f32"
BF16 = "bf16"

#: Default stated vertex-error budget of the bf16 tier: 2 mm in model
#: units (meters) — 5x the ~4e-4 m measured bf16-vs-f32 error, small
#: against fingertip dimensions (PAPER.md interactive tracking), and
#: loose enough that it gates real drift, not float weather.
DEFAULT_ENVELOPE_M = 2e-3


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Which admission tiers serve the bf16 baked-shape/pose path.

    Parameters
    ----------
    bf16_tiers: tiers whose POSE-ONLY (subject) requests are served by
        the bf16-compute/f32-accumulate gathered family. Default:
        tier 0 only — interactive traffic, the tier with a latency SLO
        and a stated mm-level error budget. Full-path requests on any
        tier stay f32 (the bf16 family exists only where the shape
        stage is pre-baked).
    accumulate: accumulation dtype of the bf16 contractions. Only
        ``"f32"`` is supported — single-pass bf16 accumulation is the
        exact silent-collapse class the sentinel exists to catch, and
        the jaxpr auditor asserts the f32-accumulate shape of every
        committed bf16 family (analysis/jaxpr_audit.py).
    max_vertex_err_m: the stated per-request vertex-error envelope
        (meters) vs the f32 path. The sentinel judges bf16 probes
        against it; bench config17 gates it per round.
    """

    bf16_tiers: FrozenSet[int] = frozenset({0})
    accumulate: str = F32
    max_vertex_err_m: float = DEFAULT_ENVELOPE_M

    def __post_init__(self):
        tiers = frozenset(int(t) for t in self.bf16_tiers)
        if any(t < 0 for t in tiers):
            raise ValueError(
                f"bf16_tiers must be non-negative, got {sorted(tiers)}")
        object.__setattr__(self, "bf16_tiers", tiers)
        if self.accumulate != F32:
            raise ValueError(
                f"accumulate must be {F32!r} (single-pass bf16 "
                f"accumulation is the silent-collapse class the "
                f"sentinel guards against), got {self.accumulate!r}")
        if not (self.max_vertex_err_m > 0):
            raise ValueError(
                f"max_vertex_err_m must be > 0, got "
                f"{self.max_vertex_err_m}")

    def dtype_for_tier(self, tier: int) -> str:
        """``"bf16"`` | ``"f32"`` for one admission tier's pose-only
        requests — a tier without an entry defaults f32 (the
        satellite edge: absence of policy is never a precision
        change)."""
        return BF16 if int(tier) in self.bf16_tiers else F32

    def tiers_snapshot(self, extra_tiers: Iterable[int] = (0, 1)) -> dict:
        """{tier: dtype} over ``bf16_tiers`` plus ``extra_tiers`` —
        the ``load()``/metrics export shape (PR-14 satellite)."""
        tiers = sorted(set(int(t) for t in extra_tiers)
                       | set(self.bf16_tiers))
        return {str(t): self.dtype_for_tier(t) for t in tiers}
