"""Engine-overhead measurement shared by `mano serve-bench` and bench.py.

The one number that judges the engine (acceptance bound: >= 0.9x a
direct jit call at the same warm batch size) is a wall-clock ratio on a
busy 1-core box where background load drifts 5x between seconds. Two
defenses, both load-bearing:

* **interleave** the engine and direct passes per trial, alternating
  which side goes first, so a load spike or monotone drift costs both
  sides instead of whichever side it happened to land on (observed
  live: a 0.12x "ratio" whose engine pass ate a spike the direct pass
  missed);
* **min-time over trials** for both sides: rates and the headline ratio
  come from each side's fastest trial (the least-loaded window — the
  time_jax_fn min-of-iters reasoning), with the per-trial ratios and
  their median kept alongside as the noise record.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from mano_hand_tpu.obs import Tracer, flight_record
from mano_hand_tpu.obs import log as obs_log

#: Progress messages default to the leveled stderr logger (PR 8
#: structured-logging satellite): silent at the default "warning"
#: level, visible under MANO_LOG=info — and NEVER stdout, which
#: bench.py and `mano serve-bench` own as a one-JSON-line channel.
#: Callers with their own sink (bench.py's log, the CLI's info logger)
#: still pass ``log=``.
_LOG = obs_log.get_logger("serving.measure")


def _logger(log: Optional[Callable[[str], None]]):
    return _LOG.info if log is None else log


def measure_overhead(
    engine,
    direct: Callable[[np.ndarray, np.ndarray], None],
    fixed: Sequence[Tuple[np.ndarray, np.ndarray]],
    trials: int = 7,
) -> dict:
    """Interleaved engine-vs-direct timing over fixed-size batches.

    ``fixed`` is a list of (pose, shape) request pairs, every one at the
    SAME batch size (the warm bucket); ``direct`` runs one pair through
    the direct jit path and blocks until done. Returns engine/direct
    rates (evals/s, fastest trial), the headline ratio from those SAME
    fastest trials (min-time is the stable estimator on a drifting box
    — the time_jax_fn min-of-iters reasoning; and the headline ratio
    must be the quotient of the two rates printed next to it, not a
    third number that can contradict them), plus the per-trial ratios
    and their median for the noise record.
    """
    rows = sum(p.shape[0] for p, _ in fixed)
    ratios: List[float] = []
    dt_e_best = dt_d_best = float("inf")

    def run_engine():
        t0 = time.perf_counter()
        futs = [engine.submit(p, s) for p, s in fixed]
        for f in futs:
            f.result()
        return time.perf_counter() - t0

    def run_direct():
        t0 = time.perf_counter()
        for p, s in fixed:
            direct(p, s)
        return time.perf_counter() - t0

    for t in range(max(1, trials)):
        # Alternate which side goes first: a monotone drift (thermal,
        # cache settling, a background process ramping) otherwise lands
        # on the same side every trial and biases every ratio one way.
        if t % 2 == 0:
            dt_e, dt_d = run_engine(), run_direct()
        else:
            dt_d, dt_e = run_direct(), run_engine()
        ratios.append(dt_d / dt_e)
        dt_e_best = min(dt_e_best, dt_e)
        dt_d_best = min(dt_d_best, dt_d)
    return {
        "engine_fixed_evals_per_sec": float(f"{rows / dt_e_best:.5g}"),
        "direct_evals_per_sec": float(f"{rows / dt_d_best:.5g}"),
        "engine_vs_direct_ratio": float(f"{dt_d_best / dt_e_best:.4g}"),
        "ratio_median": float(f"{float(np.median(ratios)):.4g}"),
        "ratio_trials": [float(f"{r:.3g}") for r in ratios],
    }


def serve_bench_run(
    params,
    *,
    requests: int = 192,
    min_rows: int = 1,
    max_rows: int = 32,
    max_bucket: int = 64,
    max_delay_s: float = 0.002,
    aot_dir=None,
    seed: int = 0,
    trials: int = 7,
    policy=None,
    tracer=None,
    metrics=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE serving benchmark protocol — shared by ``bench.py`` config7
    and `mano serve-bench` so the two artifacts cannot diverge.

    Phases: warm every bucket; settle the pipeline with one ragged pass;
    time a second ragged pass (engine_evals_per_sec) and count steady
    recompiles; then the fixed-warm-bucket overhead bound via
    ``measure_overhead``. The fixed requests are exactly the LARGEST
    bucket — coalescing cannot merge two of them (they would overflow),
    so each dispatch is one request at one batch size, directly
    comparable to a direct jit call at that size.

    Returns the flat serving metrics dict (rates + overhead + counters
    snapshot). Raises on engine failure — callers own fault isolation.
    """
    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.models import core
    from mano_hand_tpu.serving.engine import ServingEngine

    if requests < 1:
        raise ValueError(f"requests must be >= 1, got {requests}")
    # Request sizes can never exceed the largest bucket (the engine
    # rejects them at submit); clamp rather than crash the leg.
    max_rows = min(max_rows, max_bucket)
    min_rows = max(1, min(min_rows, max_rows))
    # The asset's own joint/shape dims, NOT the MANO constants: the CLI
    # serves SMPL-family body assets (24/52 joints) through the same
    # engine, and the engine validates request shapes against params.
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    sizes = rng.integers(min_rows, max_rows + 1, size=requests)
    stream = [
        (rng.normal(scale=0.4, size=(n, n_joints, 3)).astype(np.float32),
         rng.normal(size=(n, n_shape)).astype(np.float32))
        for n in (int(s) for s in sizes)
    ]
    # ``policy`` (a runtime.DispatchPolicy) runs the whole protocol
    # under supervised dispatch — `mano serve-bench --chaos <plan>`
    # uses it to measure what a fault schedule does to live metrics.
    log = _logger(log)
    # ``tracer`` (PR 8, `serve-bench --trace`): spans the whole stream;
    # None keeps the historical untraced protocol (config7's numbers
    # stay tracer-free — the overhead question has its own leg,
    # ``tracing_overhead_run``/config12).
    eng = ServingEngine(params, max_bucket=max_bucket,
                        max_delay_s=max_delay_s, aot_dir=aot_dir,
                        policy=policy, tracer=tracer)
    # ``metrics`` (an obs.metrics.MetricsRegistry, PR 9 — `serve-bench
    # --metrics DIR`): the run's engine registers its telemetry
    # sources as pull collectors; the CALLER owns scrape timing and
    # export, so the protocol's measured numbers stay registry-free.
    if metrics is not None:
        from mano_hand_tpu.obs.metrics import register_engine_collectors

        register_engine_collectors(metrics, eng, tracer=tracer)

    def run_stream():
        futs = [eng.submit(p, s) for p, s in stream]
        for f in futs:
            f.result()

    prm_dev = params.astype(np.float32).device_put()

    def direct(p, s):
        # THE existing shared direct entry (core.jit_forward_batched) —
        # the same program family the bit-identity tests compare the
        # engine against; a private re-jit here would be a second
        # definition of "the direct path" free to drift from it.
        jax.block_until_ready(core.jit_forward_batched(
            prm_dev, jnp.asarray(p), jnp.asarray(s)).verts)

    with eng:
        if log:
            log(f"serving: warming buckets {eng.buckets}")
        eng.warmup()
        # Numerics probe in the SAME process/backend as the timed path
        # (the CLAUDE.md on-chip rule): the engine's compiled per-bucket
        # executables — including an AOT-loaded one when aot_dir is warm
        # — against the direct jit forward. A silent precision collapse
        # in the serving path must surface as a number here, not ship.
        probe_p, probe_s = stream[0]
        got = eng.forward(probe_p, probe_s)
        want = np.asarray(core.jit_forward_batched(
            prm_dev, jnp.asarray(probe_p), jnp.asarray(probe_s)).verts)
        numerics_err = float(np.abs(got - want).max())
        run_stream()                       # settle the pipeline
        compiles_warm = eng.counters.compiles
        t0 = time.perf_counter()
        run_stream()                       # the measured steady pass
        dt = time.perf_counter() - t0
        steady_recompiles = eng.counters.compiles - compiles_warm
        # Snapshot HERE: the counters must describe the RAGGED stream
        # (its padding waste, queue depth, latency) — the synthetic
        # fixed-bucket overhead burst below would dilute padding_waste
        # toward zero and overwrite the latency picture.
        snapshot = eng.counters.snapshot()

        warm_bucket = eng.buckets[-1]
        # Enough batches that one scheduler hiccup cannot carry a whole
        # phase: ~100 ms+ per side per trial on this box, not ~50 ms.
        fixed = [
            (rng.normal(scale=0.4,
                        size=(warm_bucket, n_joints, 3)).astype(np.float32),
             rng.normal(size=(warm_bucket, n_shape)).astype(np.float32))
            for _ in range(max(24, requests // 4))
        ]
        eng.forward(*fixed[0])             # settle
        direct(*fixed[0])                  # compile outside the timing
        overhead = measure_overhead(eng, direct, fixed, trials=trials)

    out = {
        "engine_evals_per_sec": float(f"{float(sizes.sum()) / dt:.5g}"),
        **overhead,
        "engine_vs_direct_max_abs_err": numerics_err,
        "warm_bucket": warm_bucket,
        "steady_recompiles": int(steady_recompiles),
        "requests": int(requests),
        "rows": [int(sizes.min()), int(sizes.max())],
        "buckets": list(eng.buckets),
        **snapshot,
    }
    if tracer is not None:
        out["flight_record"] = flight_record(
            tracer, eng.counters, reason="serve_bench_complete")
    return out


def coalesce_bench_run(
    params,
    *,
    subjects: int = 8,
    requests: int = 96,
    min_rows: int = 1,
    max_rows: int = 4,
    max_bucket: int = 64,
    max_delay_s: float = 0.002,
    seed: int = 0,
    trials: int = 7,
    max_subjects=None,
    policy=None,
    tracer=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE mixed-subject coalescing benchmark protocol — shared by
    ``bench.py`` config9 and `mano serve-bench --subjects` so the two
    artifacts cannot diverge (the config7 pattern).

    The scenario PR 4 exists for: ``subjects`` users each with their own
    baked betas submit small pose-only requests in one interleaved
    stream. The ENGINE side coalesces them into gathered mixed-subject
    dispatches (core.forward_posed_gather); the SPLIT side is the
    pre-PR-4 dispatch family driven the way a subject-split coalescer
    degenerates on this stream — one per-subject posed dispatch per
    request (ShapedHand as the per-batch constant, padded to its own
    bucket, blocking). Both sides run warm and are timed with the
    interleaved min-over-trials defense of ``measure_overhead`` (this
    box's load drifts 5x between seconds; a sequential pair hands one
    side the spike and the ratio lies).

    Returned criteria numbers (scripts/bench_report.py judges):

    * ``engine_vs_split_ratio`` >= 1.3 on a >= 8-subject stream;
    * ``gather_vs_posed_max_abs_err`` == 0.0 — the gathered engine path
      is f32 BIT-identical to the per-subject posed program at the same
      padded size (probed through the live engine, CLAUDE.md rule);
    * ``steady_recompiles`` == 0 after warmup + table growth —
      capacity doublings all happen at specialize time here, so the
      timed passes compile nothing.
    """
    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.models import core
    from mano_hand_tpu.serving import buckets as bucket_mod
    from mano_hand_tpu.serving.engine import ServingEngine

    if subjects < 1:
        raise ValueError(f"subjects must be >= 1, got {subjects}")
    if requests < 1:
        raise ValueError(f"requests must be >= 1, got {requests}")
    max_rows = min(max_rows, max_bucket)
    min_rows = max(1, min(min_rows, max_rows))
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    betas = [rng.normal(size=(n_shape,)).astype(np.float32)
             for _ in range(subjects)]
    sizes = rng.integers(min_rows, max_rows + 1, size=requests)
    subj_of = rng.integers(0, subjects, size=requests)
    stream = [
        (rng.normal(scale=0.4,
                    size=(int(n), n_joints, 3)).astype(np.float32), int(s))
        for n, s in zip(sizes, subj_of)
    ]

    log = _logger(log)
    # Every drill attaches a flight record (PR 8): a default tracer
    # rides along when the caller brings none. Tracing is a measured
    # <= 3% (config12); the criteria here carry order-of-magnitude
    # margins.
    if tracer is None:
        tracer = Tracer()
    kw = {} if max_subjects is None else {"max_subjects": max_subjects}
    eng = ServingEngine(params, max_bucket=max_bucket,
                        max_delay_s=max_delay_s, policy=policy,
                        tracer=tracer, **kw)

    prm_dev = params.astype(np.float32).device_put()
    shaped = [core.jit_specialize(prm_dev, jnp.asarray(b)) for b in betas]
    # The split baseline's executable IS the pre-PR-4 program family
    # (forward_posed_batched, ShapedHand as runtime arg) — also the
    # bit-identity reference for the gathered path.
    split_exe = jax.jit(lambda sh, p: core.forward_posed_batched(sh, p).verts)

    def split_one(pose, si):
        b = bucket_mod.bucket_for(pose.shape[0], eng.buckets)
        out = split_exe(shaped[si],
                        jnp.asarray(bucket_mod.pad_rows(pose, b)))
        return np.asarray(out)[:pose.shape[0]]

    ratios: List[float] = []
    dt_e_best = dt_s_best = float("inf")
    with eng:
        keys = [eng.specialize(b) for b in betas]
        if log:
            log(f"coalesce: {subjects} subjects baked "
                f"({eng.counters.table_growths} table growths), "
                f"warming buckets {eng.buckets}")
        eng.warmup_posed()
        for b in eng.buckets:   # warm the split side's buckets too
            jax.block_until_ready(split_exe(
                shaped[0], np.zeros((b, n_joints, 3), np.float32)))
        # Numerics probe through the LIVE engine in the same
        # process/backend as the timed path (CLAUDE.md rule): the
        # gathered dispatch vs the per-subject posed program at the
        # same padded size must agree BIT-for-bit (f32 ==).
        gerr = 0.0
        for pose, si in stream[:min(8, len(stream))]:
            got = eng.forward(pose, subject=keys[si])
            gerr = max(gerr, float(np.abs(got - split_one(pose, si)).max()))

        def run_engine():
            t0 = time.perf_counter()
            futs = [eng.submit(p, subject=keys[si]) for p, si in stream]
            for f in futs:
                f.result()
            return time.perf_counter() - t0

        def run_split():
            t0 = time.perf_counter()
            for p, si in stream:
                split_one(p, si)
            return time.perf_counter() - t0

        run_engine()
        run_split()             # settle both sides outside the timing
        compiles_warm = eng.counters.compiles
        for t in range(max(1, trials)):
            if t % 2 == 0:
                dt_e, dt_s = run_engine(), run_split()
            else:
                dt_s, dt_e = run_split(), run_engine()
            ratios.append(dt_s / dt_e)
            dt_e_best = min(dt_e_best, dt_e)
            dt_s_best = min(dt_s_best, dt_s)
        steady_recompiles = eng.counters.compiles - compiles_warm
        snapshot = eng.counters.snapshot()

    rows_total = int(sizes.sum())
    if log:
        log(f"coalesce: engine {rows_total / dt_e_best:,.0f} vs split "
            f"{rows_total / dt_s_best:,.0f} evals/s "
            f"({dt_s_best / dt_e_best:.2f}x), width "
            f"{snapshot['coalesce_width_mean']}, gather err {gerr:.1e}")
    return {
        "subjects": int(subjects),
        "requests": int(requests),
        "rows": [int(sizes.min()), int(sizes.max())],
        "buckets": list(eng.buckets),
        "engine_evals_per_sec": float(f"{rows_total / dt_e_best:.5g}"),
        "split_evals_per_sec": float(f"{rows_total / dt_s_best:.5g}"),
        "engine_vs_split_ratio": float(f"{dt_s_best / dt_e_best:.4g}"),
        "ratio_median": float(f"{float(np.median(ratios)):.4g}"),
        "ratio_trials": [float(f"{r:.3g}") for r in ratios],
        "gather_vs_posed_max_abs_err": gerr,
        "steady_recompiles": int(steady_recompiles),
        "table_growths": snapshot["table_growths"],
        "specializations_evicted": snapshot["specializations_evicted"],
        "coalesce_overflows": snapshot["coalesce_overflows"],
        "mixed_subject_batches": snapshot["mixed_subject_batches"],
        "coalesce_width_mean": snapshot["coalesce_width_mean"],
        "padding_waste": snapshot["padding_waste"],
        "dispatches": snapshot["dispatches"],
        "flight_record": flight_record(
            tracer, eng.counters, reason="coalesce_drill_complete"),
    }


def overload_drill_run(
    params,
    *,
    saturation: float = 4.0,
    bursts: int = 40,
    burst_interval_s: float = 0.01,
    tier0_fraction: float = 0.125,
    # Defaults sized for this box's load drift (5x between seconds,
    # CLAUDE.md): an admitted request's worst-case queue wait is
    # max_queued / service_rate (~135 ms healthy at the measured ~300
    # req/s), so deadline_s=0.4 keeps tier-0 goodput green through a
    # ~3x transient service collapse while still expiring work a real
    # tracker would consider stale.
    max_queued: int = 40,
    tier1_quota: int = 14,
    deadline_s: float = 0.4,
    sat_latency_s: float = 0.02,
    max_bucket: int = 8,
    batch_deadline_s: float = 0.5,
    shed_probe_submits: int = 256,
    seed: int = 0,
    tracer=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE overload/saturation drill protocol — shared by ``bench.py``
    config10, `mano serve-bench --overload`, and tests/test_overload.py
    so the three artifacts cannot diverge (the recovery-drill pattern).

    The scenario PR 5 exists for: a sustained arrival rate ABOVE device
    throughput. The device half is simulated with a chaos saturation
    plan (``sat:T@0-`` throttles every dispatch, capping service rate
    deterministically on CPU); the arrival half is a burst submitter
    (every ``burst_interval_s``, a burst sized to ``saturation`` x the
    MEASURED service rate — calibrated in-protocol, so "4x" means 4x
    this box today, not a guess). Two priority tiers ride the stream:
    tier 0 (interactive, ``tier0_fraction`` of arrivals — deliberately
    under capacity on its own) and tier 1 (batch), against a bounded
    engine (``max_queued`` total, ``tier1_quota`` for tier 1) with a
    per-request ``deadline_s``.

    Returned criteria numbers (scripts/bench_report.py judges):

    * ``resolved_within_budget_fraction`` == 1.0 — EVERY submitted
      future resolves inside its budget (``deadline_s`` plus one
      supervised-batch window for the pre-dispatch sweep to run) as
      result, shed, or expired — never a hang, never a quietly-late
      result;
    * ``tier0_goodput`` >= 0.95 at >= 4x achieved saturation — the
      quota headroom actually protects interactive traffic while tier 1
      absorbs the shedding;
    * ``shed_probe.dispatches`` == 0 — shed decisions are admission
      bookkeeping: the probe engine (``max_queued=0``) sheds every
      submit without ever starting its dispatcher, touching a device,
      or even device_put-ting params, and the per-decision wall time is
      recorded in µs;
    * ``steady_recompiles`` == 0 — overload grows NO new programs: the
      warm bucket executables serve the whole drill.

    Everything runs on whatever backend is up; saturation is injected
    in-process, so no chip is required and none is harmed.
    """
    from mano_hand_tpu.runtime.chaos import ChaosPlan
    from mano_hand_tpu.runtime.supervise import DispatchPolicy
    from mano_hand_tpu.serving.engine import ServingEngine, ServingError

    if saturation <= 0:
        raise ValueError(f"saturation must be > 0, got {saturation}")
    if bursts < 1:
        raise ValueError(f"bursts must be >= 1, got {bursts}")
    if not 0.0 < tier0_fraction < 1.0:
        raise ValueError(
            f"tier0_fraction must be in (0, 1), got {tier0_fraction}")
    if max_queued < 1:
        raise ValueError(
            f"max_queued={max_queued} admits nothing — the drill needs "
            "at least one admitted request to calibrate (the shed-only "
            "path is the probe's job)")
    log = _logger(log)
    # One tracer spans BOTH engines (PR 8): the probe's pure-shed spans
    # and the saturated engine's full mix land on one timeline, and the
    # flight record's closed-exactly-once accounting covers every
    # submit the drill made. A sustained shed run fires the tracer's
    # shed_burst incident — the recorder trigger overload exists for.
    if tracer is None:
        tracer = Tracer()
    n_joints = params.n_joints
    rng = np.random.default_rng(seed)

    def one_pose():
        return rng.normal(
            scale=0.4, size=(1, n_joints, 3)).astype(np.float32)

    # ---- Phase A: the shed probe (no device, no dispatcher) -----------
    # max_queued=0 sheds EVERY submit at admission; the engine is never
    # started, so the numbers below prove the shed path is pure host
    # bookkeeping: zero dispatches, no dispatcher thread, params never
    # transferred — and each decision lands in microseconds.
    probe = ServingEngine(params, max_bucket=max_bucket, max_queued=0,
                          tracer=tracer)
    probe_pose = one_pose()
    shed_us: List[float] = []
    for _ in range(max(1, shed_probe_submits)):
        t0 = time.perf_counter()
        try:
            probe.submit(probe_pose, deadline_s=deadline_s)
            raise RuntimeError("shed probe submit was admitted at "
                               "max_queued=0")
        except ServingError as e:
            if e.kind != "shed":
                raise
        shed_us.append((time.perf_counter() - t0) * 1e6)
    shed_probe = {
        "sheds": len(shed_us),
        "dispatches": probe.counters.dispatches,
        "engine_started": probe._thread is not None,
        "params_device_put": probe._params_dev is not None,
        "decision_p50_us": float(f"{np.percentile(shed_us, 50):.4g}"),
        "decision_p99_us": float(f"{np.percentile(shed_us, 99):.4g}"),
    }
    if log:
        log(f"overload: shed probe {shed_probe['sheds']} sheds, "
            f"{shed_probe['dispatches']} dispatches, p50 "
            f"{shed_probe['decision_p50_us']:.1f} µs")

    # ---- Phase B: the saturated engine --------------------------------
    plan = ChaosPlan(f"sat:{sat_latency_s}@0-")
    policy = DispatchPolicy(
        deadline_s=batch_deadline_s, retries=0, backoff_s=0.0,
        backoff_cap_s=0.0, jitter=0.0, breaker=None, chaos=plan,
        # The fallback tier would bypass the sat throttle and quietly
        # raise capacity mid-drill; overload is not a fault, so keep
        # one deterministic service rate.
        cpu_fallback=False,
    )
    eng = ServingEngine(
        params, max_bucket=max_bucket, max_delay_s=0.001, policy=policy,
        max_queued=max_queued, tier_quotas={1: tier1_quota},
        tracer=tracer)

    outcomes = {"ok": 0, "shed": 0, "expired": 0, "error": 0,
                "unresolved": 0}
    by_tier = {0: dict(outcomes), 1: dict(outcomes)}
    records: List[tuple] = []   # (tier, t_submit, future|None, done_box)
    load_mid = None

    with eng:
        eng.warmup()
        # Calibrate THIS box's saturated service rate: waves sized under
        # the tier-0 quota headroom (so calibration itself never sheds),
        # submitted-then-drained three times. Includes the sat throttle
        # and the real coalescing path — "4x saturation" is defined
        # against this number.
        # Clamped to max_queued: the tier-0 quota defaults to the whole
        # queue, so a wave <= max_queued is never shed even when the cap
        # is smaller than a bucket.
        wave = min(max(max_bucket, min(max_queued // 2, 3 * max_bucket)),
                   max_queued)
        served = 0
        t0 = time.perf_counter()
        for _ in range(3):
            futs = [eng.submit(one_pose()) for _ in range(wave)]
            for f in futs:
                f.result()
            served += wave
        service_rate = served / (time.perf_counter() - t0)
        compiles_warm = eng.counters.compiles
        offered_rate = saturation * service_rate
        burst_n = max(1, int(round(offered_rate * burst_interval_s)))
        budget_s = deadline_s + batch_deadline_s + 0.25
        if log:
            log(f"overload: service rate {service_rate:,.0f} req/s "
                f"(sat throttle {sat_latency_s}s), offering "
                f"{offered_rate:,.0f} req/s = {burst_n}/burst x "
                f"{bursts} bursts")

        t_stream0 = time.monotonic()
        next_t = t_stream0
        for b in range(bursts):
            for _ in range(burst_n):
                tier = 0 if rng.random() < tier0_fraction else 1
                t_sub = time.monotonic()
                done_box: List[float] = []
                try:
                    fut = eng.submit(one_pose(), priority=tier,
                                     deadline_s=deadline_s)
                except ServingError as e:
                    if e.kind != "shed":
                        raise
                    records.append((tier, t_sub, None, done_box))
                    continue
                fut.add_done_callback(
                    lambda f, box=done_box: box.append(time.monotonic()))
                records.append((tier, t_sub, fut, done_box))
            if b == bursts // 2:
                load_mid = eng.load()
            next_t += burst_interval_s
            lag = next_t - time.monotonic()
            if lag > 0:
                time.sleep(lag)
            # Behind schedule: submit the next burst immediately — a
            # slow submitter must compress bursts, not quietly lower
            # the offered rate.
        t_stream1 = time.monotonic()

        # Resolution wait: every future must be DONE within its budget;
        # the wait itself gets a grace window past the last budget so a
        # straggler is recorded as unresolved, not crashed into.
        wait_end = t_stream1 + budget_s + 10.0
        for tier, t_sub, fut, done_box in records:
            if fut is None:
                continue
            try:
                fut.result(timeout=max(0.0, wait_end - time.monotonic()))
            except ServingError:
                pass
            except Exception:   # noqa: BLE001 — a timeout IS the bug
                pass
        steady_recompiles = eng.counters.compiles - compiles_warm
        snap = eng.counters.snapshot()

    # ---- Classification ----------------------------------------------
    # concurrent.futures wakes result() waiters BEFORE invoking done-
    # callbacks, so a future can be done() for a moment before its
    # done_box timestamp lands. The engine's stop() join sequences the
    # dispatcher's callbacks ahead of this point in the normal case;
    # the short drain below closes the remaining (wedged-stop) window
    # so a resolved-in-budget future is never misclassified unresolved.
    drain_end = time.monotonic() + 1.0
    for _, _, fut, done_box in records:
        while (fut is not None and fut.done() and not done_box
               and time.monotonic() < drain_end):
            time.sleep(0.001)
    in_budget = 0
    resolve_lat: List[float] = []
    for tier, t_sub, fut, done_box in records:
        if fut is None:
            outcome = "shed"        # resolved AT submit: latency ~0
            in_budget += 1
        elif not fut.done() or not done_box:
            outcome = "unresolved"
        else:
            lat = done_box[0] - t_sub
            resolve_lat.append(lat)
            if lat <= budget_s:
                in_budget += 1
            exc = fut.exception()
            if exc is None:
                outcome = "ok"
            elif isinstance(exc, ServingError) and exc.kind == "expired":
                outcome = "expired"
            elif isinstance(exc, ServingError) and exc.kind == "shed":
                outcome = "shed"
            else:
                outcome = "error"
        outcomes[outcome] += 1
        by_tier[tier][outcome] += 1

    submitted = len(records)
    stream_s = max(t_stream1 - t_stream0, 1e-9)
    achieved = (submitted / stream_s) / service_rate if service_rate else 0.0
    t0_total = sum(by_tier[0].values())
    tier0_goodput = by_tier[0]["ok"] / t0_total if t0_total else None
    if log:
        log(f"overload: {submitted} submitted at {achieved:.2f}x "
            f"achieved saturation -> {outcomes['ok']} ok / "
            f"{outcomes['shed']} shed / {outcomes['expired']} expired / "
            f"{outcomes['unresolved']} unresolved; tier-0 goodput "
            f"{tier0_goodput if tier0_goodput is None else f'{tier0_goodput:.1%}'}, "
            f"{steady_recompiles} steady recompiles")
    return {
        "saturation_target": float(saturation),
        "saturation_achieved": float(f"{achieved:.4g}"),
        "service_rate_req_per_s": float(f"{service_rate:.5g}"),
        "offered_rate_req_per_s": float(f"{offered_rate:.5g}"),
        "bursts": int(bursts),
        "burst_requests": int(burst_n),
        "burst_interval_s": burst_interval_s,
        "deadline_s": deadline_s,
        "budget_s": float(f"{budget_s:.4g}"),
        "tier0_fraction": tier0_fraction,
        "max_queued": int(max_queued),
        "tier1_quota": int(tier1_quota),
        "sat_latency_s": sat_latency_s,
        "submitted": submitted,
        "outcomes": outcomes,
        "by_tier": {str(t): c for t, c in by_tier.items()},
        "tier0_goodput": (None if tier0_goodput is None
                          else float(f"{tier0_goodput:.6g}")),
        "resolved_within_budget_fraction": float(
            f"{in_budget / submitted if submitted else 0.0:.6g}"),
        "resolve_p99_s": (float(f"{np.percentile(resolve_lat, 99):.4g}")
                          if resolve_lat else None),
        "shed_probe": shed_probe,
        "steady_recompiles": int(steady_recompiles),
        "backlog_peak": snap["backlog_peak"],
        "shed": snap["shed"],
        "expired": snap["expired"],
        "dispatches": snap["dispatches"],
        "coalesce_width_mean": snap["coalesce_width_mean"],
        "tiers": snap["tiers"],
        "load_mid_drill": load_mid,
        "flight_record": flight_record(
            tracer, eng.counters, reason="overload_drill_complete"),
    }


def cold_start_drill_run(
    params,
    *,
    subjects: int = 6,
    requests: int = 48,
    max_rows: int = 4,
    max_bucket: int = 8,
    max_subjects: int = 8,
    aot_dir=None,
    p99_waves: int = 6,
    hang_deadline_s: float = 2.0,
    seed: int = 0,
    tracer=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE cold-start/restart drill protocol — shared by ``bench.py``
    config11, `mano serve-bench --cold-start`, and tests/test_coldstart.py
    so the three artifacts cannot diverge (the recovery-drill pattern).

    The scenario PR 6 exists for: at scale, process restarts are routine
    — and a recompile storm at boot is an outage, while every subject
    specialized since PR 2/4 evaporates with the process. The drill
    treats restart as a fault class with measured criteria:

    * **Phase A (the doomed process)**: a warm engine — ``subjects``
      baked, every bucket warmed — ``bake_lattice()``s its reachable
      executable lattice, checkpoints its SubjectTable, then is KILLED
      mid-traffic (a burst of in-flight futures + ``stop(timeout_s=)``):
      every outstanding future must still resolve (result or structured
      ServingError) — the PR-3 no-hang guarantee at death.
    * **Phase B (the cold start)**: a fresh engine on the same artifacts
      restores the checkpoint and warms every program, measuring
      process-start -> restore done -> warm done -> FIRST served result
      -> p99-stable (wave p99s within 1.5x of the settled p99). The
      criteria: ``compiles_after_restore`` == 0 with ``aot_loads`` ==
      the full reachable program count (the lattice served everything —
      proof by accounting, not hope), and a restored subject's pose-only
      results f32 BIT-identical to a freshly-baked one.
    * **Phase C (damage injections)**: a truncated lattice entry, a
      schema-bumped manifest (the versioning rule), a digest-mismatched
      manifest, and a half-written SubjectTable checkpoint — each boots
      a fresh engine against the damaged artifacts and must DEGRADE to
      counted recompiles/re-specializes (``aot_load_failures``) while
      still resolving 100% of its stream; never a crash, never a
      silently-wrong executable.
    * **Phase D (chaos composes)**: the restore/boot runs under a
      ``hang`` chaos fault with a supervised policy — the wedged first
      dispatch must hit the PR-3 deadline-kill path (and the lattice-
      loaded CPU failover tier stands warm behind it), not wedge boot.

    Everything runs on whatever backend is up; restarts are simulated
    in-process (fresh engine == cold executable caches; the jit
    persistent compilation cache is not consulted by the counters), so
    no chip is required and none is harmed.
    """
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.io.export_aot import LATTICE_MANIFEST
    from mano_hand_tpu.models import core
    from mano_hand_tpu.runtime.chaos import ChaosPlan
    from mano_hand_tpu.runtime.supervise import DispatchPolicy
    from mano_hand_tpu.serving import buckets as bucket_mod
    from mano_hand_tpu.serving.engine import ServingEngine, ServingError

    if subjects < 1:
        raise ValueError(f"subjects must be >= 1, got {subjects}")
    if requests < 1:
        raise ValueError(f"requests must be >= 1, got {requests}")
    log = _logger(log)
    # One tracer spans EVERY engine of the drill (PR 8): the doomed
    # process, the cold boot, the damage-injection legs, and the
    # hang-composed boot — so the flight record proves every submit
    # across every restart phase closed exactly once, and the lattice
    # loads / deadline kills land on one timeline.
    if tracer is None:
        tracer = Tracer()
    max_rows = min(max_rows, max_bucket)
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    betas = [rng.normal(size=(n_shape,)).astype(np.float32)
             for _ in range(subjects)]

    tmp_root = None
    from pathlib import Path

    if aot_dir is None:
        tmp_root = tempfile.mkdtemp(prefix="mano_coldstart_")
        aot_dir = Path(tmp_root)
    else:
        # The drill OWNS a subdirectory of the caller's dir: its engines
        # are drill-sized, and although bake_lattice merges into a
        # same-digest manifest, a production lattice living in aot_dir
        # proper must never share a manifest (or damage-leg copies)
        # with drill artifacts. Re-runs still reuse the warm drill
        # lattice — the restart-measures-something-real property.
        aot_dir = Path(aot_dir) / "coldstart_drill"
        aot_dir.mkdir(parents=True, exist_ok=True)
    ckpt = aot_dir / "subjects_ckpt"

    def make_stream(n, keys):
        """Half full-path, half pose-only across the baked subjects —
        both program kinds exercise the lattice. (pose, shape, subject)
        submit triples, same shape as the recovery drill's."""
        sizes = rng.integers(1, max_rows + 1, size=n)
        out = []
        for i, s in enumerate(sizes):
            pose = rng.normal(
                scale=0.4, size=(int(s), n_joints, 3)).astype(np.float32)
            if keys and i % 2 == 1:
                out.append((pose, None, keys[i % len(keys)]))
            else:
                out.append((pose, rng.normal(
                    size=(int(s), n_shape)).astype(np.float32), None))
        return out

    def run_stream(eng, stream, timeout_s=60.0):
        """(resolved_ok, resolved_error, unresolved, wall_s)."""
        t0 = time.perf_counter()
        futs = [eng.submit(p, s, subject=k) for p, s, k in stream]
        ok = err = un = 0
        for f in futs:
            try:
                f.result(timeout=timeout_s)
                ok += 1
            except ServingError:
                err += 1
            except Exception:   # noqa: BLE001 — a timeout IS the bug
                un += 1
        return ok, err, un, time.perf_counter() - t0

    engine_kw = dict(max_bucket=max_bucket, max_delay_s=0.001,
                     max_subjects=max_subjects, tracer=tracer)

    # ---- Phase A: the doomed process ----------------------------------
    eng_a = ServingEngine(params, aot_dir=aot_dir, **engine_kw)
    probe_pose = rng.normal(
        scale=0.4, size=(2, n_joints, 3)).astype(np.float32)
    with eng_a:
        keys = [eng_a.specialize(b) for b in betas]
        eng_a.warmup()
        eng_a.warmup_posed()
        manifest = eng_a.bake_lattice(include_cpu_fallback=True)
        stream_a = make_stream(requests, keys)
        ok_a, err_a, un_a, _ = run_stream(eng_a, stream_a)
        # The reference results a restored subject must reproduce
        # bitwise, captured through the LIVE warm engine.
        want_posed = [np.asarray(eng_a.forward(probe_pose, subject=k))
                      for k in keys[:min(3, len(keys))]]
        eng_a.checkpoint_subjects(ckpt)
        # The kill: a burst left in flight, then a bounded stop — the
        # process dies with work outstanding, as real kills do.
        kill_futs = [eng_a.submit(p, s, subject=k)
                     for p, s, k in make_stream(
                         min(requests, 16), keys)]
    # context exit == stop(): every future must be DONE now (result or
    # structured error), the PR-3 guarantee at death.
    killed_resolved = sum(f.done() for f in kill_futs)
    baked_compiles = eng_a.counters.compiles
    if log:
        log(f"cold-start A: {len(manifest['entries'])} lattice entries "
            f"baked, checkpoint written, killed with "
            f"{killed_resolved}/{len(kill_futs)} in-flight futures "
            f"resolved")

    # ---- Phase B: the cold start --------------------------------------
    # Expected reachable programs at boot: every bucket's full program +
    # every bucket's gathered program at the restored capacity. (The CPU
    # failover tier is unreachable without a supervising policy; phase D
    # accounts for it.)
    eng_b = ServingEngine(params, aot_dir=aot_dir, **engine_kw)
    t0 = time.perf_counter()
    with eng_b:
        restore = eng_b.restore_subjects(ckpt)
        t_restore = time.perf_counter() - t0
        warm_full = eng_b.warmup()
        warm_posed = eng_b.warmup_posed()
        t_warm = time.perf_counter() - t0
        first = eng_b.forward(probe_pose, subject=keys[0])
        t_first = time.perf_counter() - t0
        # Bit-identity: the restored subject vs the phase-A warm engine,
        # AND vs a freshly-baked ShapedHand through the posed program at
        # the same padded size (the PR-4 gather contract, now across a
        # restart).
        restored_err = 0.0
        for k, want in zip(keys, want_posed):
            got = np.asarray(eng_b.forward(probe_pose, subject=k))
            restored_err = max(restored_err,
                               float(np.abs(got - want).max()))
        b = bucket_mod.bucket_for(probe_pose.shape[0], eng_b.buckets)
        fresh = core.jit_specialize(
            params.astype(np.float32).device_put(), jnp.asarray(betas[0]))
        fresh_out = np.asarray(core.jit_forward_posed_batched(
            fresh, jnp.asarray(bucket_mod.pad_rows(probe_pose, b)))
            .verts)[:probe_pose.shape[0]]
        got0 = np.asarray(eng_b.forward(probe_pose, subject=keys[0]))
        restored_vs_fresh = float(np.abs(got0 - fresh_out).max())
        # p99 settling: waves of the steady stream; stable once every
        # later wave's p99 sits within 1.5x of the settled p99.
        wave_p99 = []
        wave_t = []
        for _ in range(max(1, p99_waves)):
            stream = make_stream(requests, keys)
            t_w0 = time.perf_counter()
            futs = [(eng_b.submit(p, s, subject=k), time.perf_counter())
                    for p, s, k in stream]
            lats = []
            for f, t_sub in futs:
                f.result(timeout=60.0)
                lats.append(time.perf_counter() - t_sub)
            wave_p99.append(float(np.percentile(lats, 99)))
            wave_t.append(time.perf_counter() - t0)
        settled = float(np.median(wave_p99[-min(3, len(wave_p99)):]))
        t_p99 = wave_t[-1]
        for i, p99 in enumerate(wave_p99):
            if all(w <= 1.5 * settled for w in wave_p99[i:]):
                t_p99 = wave_t[i]
                break
        compiles_after_restore = eng_b.counters.compiles
        aot_loads = eng_b.counters.aot_loads
        snap_b = eng_b.counters.snapshot()
    expected_programs = 2 * len(eng_b.buckets)
    if log:
        log(f"cold-start B: restore {restore}, first result at "
            f"{t_first * 1e3:.0f} ms, p99 stable at {t_p99 * 1e3:.0f} ms "
            f"({compiles_after_restore} compiles, {aot_loads}/"
            f"{expected_programs} programs from the lattice, restored-vs-"
            f"fresh err {restored_vs_fresh:.1e})")

    # ---- Phase C: damage injections -----------------------------------
    import json

    injections = {}

    def injection_leg(name: str, damage):
        """Copy the artifacts, apply ``damage(dir)``, cold-boot against
        them; the leg must resolve its whole stream with the damage
        degraded to counted recompiles/re-specializes."""
        leg_dir = aot_dir.parent / f"{aot_dir.name}_{name}"
        if leg_dir.exists():
            shutil.rmtree(leg_dir)
        shutil.copytree(aot_dir, leg_dir)
        damage(leg_dir)
        eng = ServingEngine(params, aot_dir=leg_dir, **engine_kw)
        import warnings

        with eng, warnings.catch_warnings():
            warnings.simplefilter("ignore")
            rs = eng.restore_subjects(leg_dir / "subjects_ckpt")
            eng.warmup()
            leg_keys = [eng.specialize(b) for b in betas]
            eng.warmup_posed()
            ok, err, un, _ = run_stream(
                eng, make_stream(requests, leg_keys))
        injections[name] = {
            "submitted": requests,
            "resolved_ok": ok,
            "resolved_error": err,
            "unresolved": un,
            "futures_resolved_fraction": 1.0 - un / requests,
            "aot_load_failures": eng.counters.aot_load_failures,
            "recompiles": eng.counters.compiles,
            "aot_loads": eng.counters.aot_loads,
            "subjects_restored": eng.counters.subjects_restored,
            "restore": rs,
        }
        shutil.rmtree(leg_dir, ignore_errors=True)
        if log:
            i = injections[name]
            log(f"cold-start C [{name}]: {i['aot_load_failures']} load "
                f"failures -> {i['recompiles']} recompiles, "
                f"{i['resolved_ok']}/{i['submitted']} ok, "
                f"{i['unresolved']} unresolved")

    def truncate_entry(d):
        # Key off the engine's REAL bucket ladder: a non-power-of-two
        # max_bucket argument rounds UP at bucket_sizes(), so the raw
        # argument may name an entry that was never baked.
        key = f"full/b{eng_b.buckets[-1]}"
        ent = manifest["entries"][key]
        f = d / ent["file"]
        f.write_bytes(f.read_bytes()[:64])
        # Remove the legacy per-bucket artifacts too: this leg pins the
        # FULL degradation chain (lattice -> legacy -> jit) ending in a
        # counted recompile, not a quiet save by the older tier. The
        # other legs keep them, demonstrating tier fallback instead.
        for legacy in d.glob("serve_*.jaxexp"):
            legacy.unlink()

    def bump_schema(d):
        man = json.loads((d / LATTICE_MANIFEST).read_text())
        man["schema"] = man["schema"] + 1
        (d / LATTICE_MANIFEST).write_text(json.dumps(man))

    def mismatch_digest(d):
        man = json.loads((d / LATTICE_MANIFEST).read_text())
        man["params_digest"] = "0" * len(man["params_digest"])
        (d / LATTICE_MANIFEST).write_text(json.dumps(man))

    def damage_ckpt(d):
        # A process killed mid-checkpoint: the meta file never landed
        # (save_state writes it LAST), so restore must degrade cleanly.
        meta = d / "subjects_ckpt" / "state_meta.json"
        meta.write_text(meta.read_text()[: max(1, meta.stat().st_size // 2)])

    injection_leg("truncated_entry", truncate_entry)
    injection_leg("schema_bump", bump_schema)
    injection_leg("digest_mismatch", mismatch_digest)
    injection_leg("damaged_checkpoint", damage_ckpt)

    # ---- Phase D: restore under a hang fault --------------------------
    # The boot itself runs supervised: the chaos plan wedges the FIRST
    # post-restore dispatch; the deadline kill must clear it (the PR-3
    # path), the retry serve the result, and the lattice-loaded CPU
    # failover tier stand warm behind the whole arrangement — boot can
    # degrade, never wedge.
    plan = ChaosPlan("hang@0")
    policy = DispatchPolicy(
        deadline_s=hang_deadline_s, retries=1, backoff_s=0.01,
        backoff_cap_s=0.02, jitter=0.0, breaker=None, chaos=plan,
        cpu_fallback=True,
    )
    eng_d = ServingEngine(params, aot_dir=aot_dir, policy=policy,
                          **engine_kw)
    try:
        with eng_d:
            rs_d = eng_d.restore_subjects(ckpt)
            eng_d.warmup()          # primary + CPU failover tiers
            eng_d.warmup_posed()    # gathered tier (restored capacity)
            hang_stream = make_stream(min(requests, 12), keys)
            ok_d, err_d, un_d, _ = run_stream(
                eng_d, hang_stream,
                timeout_s=hang_deadline_s * 4 + 30.0)
    finally:
        plan.release.set()   # free the abandoned hung worker thread
    hang_leg = {
        "submitted": len(hang_stream),
        "resolved_ok": ok_d,
        "resolved_error": err_d,
        "unresolved": un_d,
        "futures_resolved_fraction": 1.0 - un_d / len(hang_stream),
        "deadline_kills": eng_d.counters.deadline_kills,
        "compiles_after_restore": eng_d.counters.compiles,
        "aot_loads": eng_d.counters.aot_loads,
        "expected_programs": 3 * len(eng_d.buckets),
        "subjects_restored": eng_d.counters.subjects_restored,
        "restore": rs_d,
    }
    if log:
        log(f"cold-start D [hang]: {hang_leg['deadline_kills']} deadline "
            f"kill(s), {ok_d}/{len(hang_stream)} ok, "
            f"{hang_leg['aot_loads']}/{hang_leg['expected_programs']} "
            f"programs from the lattice, "
            f"{hang_leg['compiles_after_restore']} compiles")

    if tmp_root is not None:
        shutil.rmtree(tmp_root, ignore_errors=True)

    return {
        "subjects": int(subjects),
        "requests": int(requests),
        "max_subjects": int(max_subjects),
        "buckets": list(eng_b.buckets),
        "lattice_entries": len(manifest["entries"]),
        "baked_compiles": int(baked_compiles),
        "killed_inflight": len(kill_futs),
        "killed_futures_resolved_fraction": float(
            f"{killed_resolved / len(kill_futs):.6g}"),
        "restore": restore,
        "warmup_sources": {str(b): s for b, s in warm_full.items()},
        "warmup_posed_sources": {str(b): s for b, s in warm_posed.items()},
        "compiles_after_restore": int(compiles_after_restore),
        "aot_loads": int(aot_loads),
        "aot_load_failures": int(snap_b["aot_load_failures"]),
        "expected_programs": int(expected_programs),
        "subjects_restored": int(snap_b["subjects_restored"]),
        "restored_vs_warm_max_abs_err": float(restored_err),
        "restored_vs_fresh_max_abs_err": float(restored_vs_fresh),
        "t_restore_s": float(f"{t_restore:.5g}"),
        "t_warm_s": float(f"{t_warm:.5g}"),
        "t_first_result_s": float(f"{t_first:.5g}"),
        "t_p99_stable_s": float(f"{t_p99:.5g}"),
        "wave_p99_ms": [float(f"{w * 1e3:.4g}") for w in wave_p99],
        "injections": injections,
        "hang_leg": hang_leg,
        "phase_a": {"submitted": requests, "resolved_ok": ok_a,
                    "resolved_error": err_a, "unresolved": un_a},
        # Counters are eng_b's (the cold boot the criteria judge); the
        # span accounting inside covers every engine of the drill.
        "flight_record": flight_record(
            tracer, eng_b.counters, reason="coldstart_drill_complete"),
    }


def recovery_drill_run(
    params,
    *,
    requests_per_class: int = 12,
    max_rows: int = 5,
    max_bucket: int = 8,
    deadline_s: float = 2.0,
    latency_spike_s: float = 0.05,
    seed: int = 0,
    tracer=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE fault-recovery drill protocol — shared by ``bench.py``
    config7_recovery, `mano serve-bench --chaos drill`, and
    tests/test_runtime.py so the three artifacts cannot diverge.

    One supervised ``ServingEngine`` (runtime.DispatchPolicy: per-batch
    deadline, 1 retry, circuit breaker with a drill-controlled probe,
    CPU fallback) is driven through every tunnel failure class via a
    rescheduled ``ChaosPlan`` — transient error, latency spike, hang,
    persistent outage — then through recovery. The done-criteria
    (scripts/bench_report.py) read the returned numbers:

    * ``futures_resolved_fraction`` == 1.0: every submitted future
      resolved (result or structured ServingError) under every fault;
    * ``failover_vs_cpu_direct_max_abs_err`` == 0.0: failover results
      are bit-identical to a direct CPU bucketed call (the fallback
      runs the same params-as-runtime-args program family);
    * ``post_recovery_steady_recompiles`` == 0: after the fault clears
      and the breaker re-closes, the still-warm primary executables
      serve with zero recompiles — failback is free.

    PR 4 widens the drill to MIXED-SUBJECT traffic: three subjects are
    specialized up front and half of every stream is pose-only requests
    across them, so gathered mixed-subject batches are in flight under
    every fault class. Their failover re-runs the full forward with
    per-row betas — ``failover_posed_vs_cpu_direct_max_abs_err`` == 0.0
    pins that path to the same bit-identity bar, and the coalesce
    telemetry (``mixed_subject_batches`` et al.) is asserted present in
    the counters snapshot so it provably survives failover.

    ``failover_overhead_ratio`` (failover vs healthy seconds/request,
    single-pass wall clock on a drifting box — an indicator, not a
    slope-grade measurement) quantifies what degraded mode costs.
    Everything runs on whatever backend is up; faults are injected
    in-process, so no chip is required and none is harmed.
    """
    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.models import core
    from mano_hand_tpu.runtime.chaos import ChaosPlan
    from mano_hand_tpu.runtime.health import CircuitBreaker
    from mano_hand_tpu.runtime.supervise import DispatchPolicy
    from mano_hand_tpu.serving.engine import ServingEngine, ServingError

    log = _logger(log)
    # The drill's tracer (PR 8): every fault class's spans — including
    # the deadline-killed and failed-over ones — plus the breaker
    # transitions and chaos faults as runtime events, attached to the
    # artifact as a flight record.
    if tracer is None:
        tracer = Tracer()
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    # Three subjects for the mixed-subject half of every stream; their
    # keys are filled in once the engine is up.
    subj_betas = [rng.normal(size=(n_shape,)).astype(np.float32)
                  for _ in range(3)]
    subj_keys: list = []

    def make_stream(n):
        """Half full-path, half pose-only across the baked subjects —
        every fault class sees gathered mixed-subject batches in
        flight. Elements are (pose, shape, subject) submit triples."""
        sizes = rng.integers(1, max_rows + 1, size=n)
        out = []
        for i, s in enumerate(sizes):
            pose = rng.normal(
                scale=0.4, size=(int(s), n_joints, 3)).astype(np.float32)
            if subj_keys and i % 2 == 1:
                out.append((pose, None, subj_keys[i % len(subj_keys)]))
            else:
                out.append((pose, rng.normal(
                    size=(int(s), n_shape)).astype(np.float32), None))
        return out

    tunnel_ok = [True]           # the drill's hand on the simulated tunnel
    plan = ChaosPlan()
    breaker = CircuitBreaker(
        failure_threshold=2,
        probe=lambda: tunnel_ok[0],
        probe_interval_s=0.0,           # drill wants instant re-probes
        respect_priority_claim=False,   # the fake tunnel needs no lock
    )
    policy = DispatchPolicy(
        deadline_s=deadline_s, retries=1, backoff_s=0.01,
        backoff_cap_s=0.02, jitter=0.0, breaker=breaker, chaos=plan,
        cpu_fallback=True,
    )
    eng = ServingEngine(params.astype(np.float32), max_bucket=max_bucket,
                        max_delay_s=0.001, policy=policy, tracer=tracer)
    resolve_timeout = deadline_s * (policy.retries + 2) + 30.0

    # Bit-identity reference: the SAME program family as the fallback
    # (params as runtime args, forward_batched), pinned to CPU.
    cpu = jax.devices("cpu")[0]
    prm_cpu = jax.device_put(params.astype(np.float32), cpu)
    ref = jax.jit(lambda q, p, s: core.forward_batched(q, p, s).verts)

    def cpu_direct(p, s):
        return np.asarray(ref(prm_cpu, jax.device_put(jnp.asarray(p), cpu),
                              jax.device_put(jnp.asarray(s), cpu)))

    def run_pass(stream):
        t0 = time.perf_counter()
        futs = [eng.submit(p, s, subject=k) for p, s, k in stream]
        ok = err = unresolved = 0
        for f in futs:
            try:
                f.result(timeout=resolve_timeout)
                ok += 1
            except ServingError:
                err += 1
            except Exception:       # noqa: BLE001 — a timeout IS the bug
                unresolved += 1
        return ok, err, unresolved, time.perf_counter() - t0

    before = {}

    def delta(counters):
        out = {k: getattr(eng.counters, k) - before.get(k, 0)
               for k in ("retries", "faults_injected", "deadline_kills",
                         "failovers")}
        for k in out:
            before[k] = getattr(eng.counters, k)
        return out

    classes = {}
    try:
        with eng:
            eng.warmup()
            # Mixed-subject tier: bake the subjects and warm the
            # gathered pose-only executables BEFORE the compile cursor
            # is read — gather compiles are warm-up-class work, and the
            # post-recovery zero-recompile criterion covers them too.
            subj_keys.extend(eng.specialize(b) for b in subj_betas)
            eng.warmup_posed()
            warm_compiles = eng.counters.compiles
            # Healthy baseline for the failover-overhead ratio.
            healthy = make_stream(requests_per_class)
            ok, err, un, t_healthy = run_pass(healthy)
            delta(eng.counters)   # zero the counter cursor
            healthy_per_req = t_healthy / max(1, len(healthy))
            if log:
                log(f"recovery drill: healthy baseline "
                    f"{healthy_per_req * 1e3:.2f} ms/request")

            specs = [
                ("transient", "error@0,error@3", True),
                ("latency", f"latency:{latency_spike_s}@0-2", True),
                ("hang", "hang@0", True),
                ("persistent", "error@0-", False),
            ]
            t_failover = None
            failover_err = None
            failover_posed_err = None
            for name, spec, tunnel_up in specs:
                breaker.reset()
                tunnel_ok[0] = tunnel_up
                plan.schedule(spec)
                stream = make_stream(requests_per_class)
                ok, err, un, dt = run_pass(stream)
                d = delta(eng.counters)
                classes[name] = {
                    "submitted": len(stream),
                    "resolved_ok": ok,
                    "resolved_error": err,
                    "unresolved": un,
                    **d,
                }
                if name == "persistent":
                    # The first pass opened the breaker and compiled the
                    # fallback executables; a SECOND pass, still under
                    # fault, times steady degraded serving so the
                    # overhead ratio describes failover, not the one-off
                    # fallback compiles.
                    stream2 = make_stream(requests_per_class)
                    ok2, err2, un2, dt2 = run_pass(stream2)
                    t_failover = dt2 / max(1, len(stream2))
                    for k, v in (("submitted", len(stream2)),
                                 ("resolved_ok", ok2),
                                 ("resolved_error", err2),
                                 ("unresolved", un2)):
                        classes[name][k] += v
                    # Failover parity probes, compared bitwise against
                    # the direct CPU program: one full request, and one
                    # POSE-ONLY (subject) request — its fallback re-runs
                    # the full forward with per-row betas, the PR-4
                    # mixed-batch failover path.
                    p, s, _ = make_stream(1)[0]
                    got = eng.forward(p, s)
                    failover_err = float(
                        np.abs(got - cpu_direct(p, s)).max())
                    p2 = rng.normal(scale=0.4, size=(2, n_joints, 3),
                                    ).astype(np.float32)
                    got2 = eng.forward(p2, subject=subj_keys[0])
                    failover_posed_err = float(np.abs(
                        got2 - cpu_direct(p2, np.broadcast_to(
                            subj_betas[0][None], (2, n_shape)))).max())
                    d2 = delta(eng.counters)
                    for k, v in d2.items():
                        classes[name][k] += v
                plan.clear()
                tunnel_ok[0] = True
                if log:
                    log(f"recovery drill [{name}]: {ok} ok / {err} err / "
                        f"{un} unresolved over {len(stream)} requests "
                        f"({d})")

            # Recovery: fault cleared, tunnel probe green. The breaker
            # is still DOWN from the persistent class — the first
            # dispatch re-probes, closes it, and fails back to the warm
            # primary executables, which must serve with ZERO further
            # compiles (the failback-is-free criterion).
            run_pass(make_stream(requests_per_class))      # settle
            compiles_settled = eng.counters.compiles
            ok, err, un, t_rec = run_pass(make_stream(requests_per_class))
            steady = eng.counters.compiles - compiles_settled
            delta(eng.counters)
            snap = eng.counters.snapshot()
    finally:
        plan.release.set()   # free any abandoned hung worker threads

    # The coalesce telemetry must SURVIVE the failover/recovery cycle
    # (the PR-4 observability satellite): a refactor that drops these
    # keys from the snapshot fails the drill, not just a dashboard.
    for k in ("mixed_subject_batches", "coalesce_width_mean",
              "coalesce_overflows", "specializations_evicted",
              "requests_dispatched"):
        if k not in snap:
            raise RuntimeError(
                f"coalesce telemetry {k!r} missing from the counters "
                "snapshot after the drill")

    total_submitted = sum(c["submitted"] for c in classes.values())
    total_unresolved = sum(c["unresolved"] for c in classes.values())
    resolved_fraction = (
        1.0 - total_unresolved / total_submitted if total_submitted else 0.0)
    ratio = (t_failover / healthy_per_req
             if t_failover and healthy_per_req else None)
    return {
        "deadline_s": deadline_s,
        "requests_per_class": requests_per_class,
        "classes": classes,
        "futures_resolved_fraction": float(f"{resolved_fraction:.6g}"),
        "failover_vs_cpu_direct_max_abs_err": failover_err,
        "failover_posed_vs_cpu_direct_max_abs_err": failover_posed_err,
        "mixed_subject_batches": snap["mixed_subject_batches"],
        "coalesce_width_mean": snap["coalesce_width_mean"],
        "failover_overhead_ratio": (float(f"{ratio:.4g}")
                                    if ratio is not None else None),
        "healthy_s_per_request": float(f"{healthy_per_req:.5g}"),
        "failover_s_per_request": (float(f"{t_failover:.5g}")
                                   if t_failover is not None else None),
        "post_recovery_steady_recompiles": int(steady),
        "post_recovery_ok": ok,
        "warmup_compiles": int(warm_compiles),
        "breaker_opens": breaker.opens,
        "breaker_probes": breaker.probes,
        "breaker_state_final": breaker.state,
        "flight_record": flight_record(
            tracer, eng.counters, reason="recovery_drill_complete"),
    }


def tracing_overhead_run(
    params,
    *,
    requests: int = 160,
    min_rows: int = 1,
    max_rows: int = 16,
    max_bucket: int = 32,
    max_delay_s: float = 0.002,
    seed: int = 0,
    trials: int = 9,
    trace_dir=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE tracing-overhead protocol — bench.py config12 (PR 8).

    Observability that slows the thing it observes gets turned off in
    the exact incident it exists for, so the tracer's cost is a judged
    number, not a belief. Two engines serve the SAME ragged request
    stream — one with a live ``obs.Tracer`` spanning every request,
    one untraced — interleaved per trial with alternating order (this
    box's load moves 5x between seconds; a sequential pair hands one
    side the spike and the ratio lies).

    The headline estimator differs from the throughput legs on
    purpose: ``tracing_overhead_ratio`` is the MEDIAN of the per-trial
    paired ratios, not a min-over-min. Each trial's quotient cancels
    the load drift common to its interleaved pair, and the median
    rejects spike trials; min-over-min compares each side's fastest
    WINDOW, and when those land in different load windows the quotient
    carries window noise larger than the 3% bound being judged
    (observed live while building this leg: per-trial ratios
    0.97-1.02, min-over-min 1.05). The min-time rates still ride along
    as the throughput record.

    Returned criteria numbers (scripts/bench_report.py judges):

    * ``tracing_overhead_ratio`` <= 1.03 — tracing costs at most 3%
      end-to-end (median paired ratio, above; judged at >= 64 requests
      — a plumbing-size run's per-pass time is noise-dominated, so
      bench_report records its ratio without judging it, the coalesce
      >= 8-subjects precedent);
    * ``steady_recompiles`` == 0 on the TRACED engine — the tracer
      must never change program identity (events are host tuples; no
      shape, no constant, no jit boundary moves);
    * ``span_accounting``: every submitted request's span closed
      exactly once (started == closed, open == 0) — the config12 half
      of the criterion the drills' flight records carry for the fault
      paths.

    ``trace_dir`` additionally exports the traced engine's Chrome-trace
    timeline + final flight record there (obs.write_trace_dir), giving
    `scripts/trace_report.py` a host-spans capture even when the
    tunnel is down (the interpret lane's acceptance path).
    """
    from mano_hand_tpu.serving.engine import ServingEngine

    if requests < 1:
        raise ValueError(f"requests must be >= 1, got {requests}")
    log = _logger(log)
    max_rows = min(max_rows, max_bucket)
    min_rows = max(1, min(min_rows, max_rows))
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    sizes = rng.integers(min_rows, max_rows + 1, size=requests)
    stream = [
        (rng.normal(scale=0.4, size=(n, n_joints, 3)).astype(np.float32),
         rng.normal(size=(n, n_shape)).astype(np.float32))
        for n in (int(s) for s in sizes)
    ]
    rows_total = int(sizes.sum())

    tracer = Tracer()
    eng_off = ServingEngine(params, max_bucket=max_bucket,
                            max_delay_s=max_delay_s)
    eng_on = ServingEngine(params, max_bucket=max_bucket,
                           max_delay_s=max_delay_s, tracer=tracer)

    def run(eng):
        t0 = time.perf_counter()
        futs = [eng.submit(p, s) for p, s in stream]
        for f in futs:
            f.result()
        return time.perf_counter() - t0

    ratios: List[float] = []
    dt_on_best = dt_off_best = float("inf")
    with eng_off, eng_on:
        eng_off.warmup()
        eng_on.warmup()
        run(eng_off)                 # settle both pipelines
        run(eng_on)
        compiles_warm = eng_on.counters.compiles
        for t in range(max(1, trials)):
            # Alternate which engine goes first: a monotone load drift
            # otherwise lands on the same side every trial and biases
            # the ratio one way (the measure_overhead defense).
            if t % 2 == 0:
                dt_on, dt_off = run(eng_on), run(eng_off)
            else:
                dt_off, dt_on = run(eng_off), run(eng_on)
            ratios.append(dt_on / dt_off)
            dt_on_best = min(dt_on_best, dt_on)
            dt_off_best = min(dt_off_best, dt_off)
        steady_recompiles = eng_on.counters.compiles - compiles_warm
    # Both engines are STOPPED here: the span accounting below is the
    # final word — anything still open is a leak, not in-flight work.
    accounting = tracer.accounting()
    stages = tracer.stage_breakdown()
    ratio = float(np.median(ratios))
    log(f"tracing: traced {rows_total / dt_on_best:,.0f} vs untraced "
        f"{rows_total / dt_off_best:,.0f} evals/s (median paired ratio "
        f"{ratio:.3f}, best-window {dt_on_best / dt_off_best:.3f}), "
        f"{steady_recompiles} steady recompiles, spans "
        f"{accounting['spans_closed']}/{accounting['spans_started']} "
        f"closed")
    out = {
        "requests": int(requests),
        "trials": int(max(1, trials)),
        "rows": [int(sizes.min()), int(sizes.max())],
        "buckets": list(eng_on.buckets),
        "traced_evals_per_sec": float(f"{rows_total / dt_on_best:.5g}"),
        "untraced_evals_per_sec": float(
            f"{rows_total / dt_off_best:.5g}"),
        "tracing_overhead_ratio": float(f"{ratio:.4g}"),
        "ratio_best_window": float(f"{dt_on_best / dt_off_best:.4g}"),
        "ratio_trials": [float(f"{r:.3g}") for r in ratios],
        "steady_recompiles": int(steady_recompiles),
        "span_accounting": accounting,
        "stage_breakdown": stages,
        "flight_record": flight_record(
            tracer, eng_on.counters, reason="tracing_overhead_complete"),
    }
    if trace_dir is not None:
        from mano_hand_tpu.obs import write_trace_dir

        out["trace_export"] = write_trace_dir(
            tracer, trace_dir, counters=eng_on.counters,
            reason="tracing_overhead_complete")
    return out


def metrics_overhead_run(
    params,
    *,
    requests: int = 160,
    min_rows: int = 1,
    max_rows: int = 16,
    max_bucket: int = 32,
    max_delay_s: float = 0.002,
    seed: int = 0,
    trials: int = 13,
    reps: int = 3,
    metrics_dir=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE metrics+sentinel protocol — bench.py config13 (PR 9).

    Two questions, one leg. (1) **What does the aggregate health
    surface cost?** An OBSERVED engine (tracer + metrics registry +
    numerics sentinel — the full PR-9 wiring a production process
    would run) serves the same ragged stream as a bare engine,
    interleaved per trial with alternating order; the headline is the
    MEDIAN paired ratio (the config12 estimator — min-over-min carries
    window noise larger than the 3% bound, dead-end recorded there).
    Each timed pass is ``reps`` stream repetitions ending in ONE
    registry scrape (snapshot + Prometheus render) and ONE sentinel
    probe, all inside the window — a scrape/probe rate still ~100x
    denser than a production 15 s scrape interval against this pass
    length. Two protocol choices are measured dead-ends, not style:
    scraping INSIDE the submit loop serializes the scrape against
    coalescing on this 1-core box and read 13% overhead for work that
    costs 0.8 ms; and at reps=1 the ~3 ms scrape+probe tail is ~2% of
    a ~0.14 s pass before the tracer's ~1.7% even starts — the bound
    only becomes a statement about steady-state cost once the pass
    amortizes the fixed tail (reps=3: measured median 1.002).
    (2) **Does the sentinel actually catch silent corruption?** The
    drill composes the chaos ``wrong``-output fault (the one failure
    mode no retry, breaker, or deadline can see) into a live
    supervised engine: traffic keeps resolving "successfully" with
    corrupt floats, and the sentinel's next probe MUST flag the
    primary family drifted while the un-wrapped CPU tier probes clean
    — then recover once the fault clears. Detection is judged, not
    hoped (scripts/bench_report.py).

    Returned criteria numbers:

    * ``metrics_overhead_ratio`` <= 1.03 at >= 64 requests (median
      paired; smaller runs record without judging — the config12
      precedent);
    * ``steady_recompiles`` == 0 on the observed engine — scrapes and
      probes must never change program identity (the sentinel probes
      only already-live families by construction);
    * ``sentinel_drill``: clean probe clean, injected ``wrong`` fault
      DETECTED (``numerics_drift`` incident recorded + flight capture),
      CPU tier clean, recovery after the fault clears, every future
      resolved, probe spans closed exactly once;
    * ``slo``: per-tier error-budget burn rates from the same counters
      snapshot the export serves.

    ``metrics_dir`` persists the observed engine's final registry
    snapshot as ``metrics.json`` + ``metrics.prom`` (the scrape files
    `mano status --metrics-dir` re-reads).
    """
    from mano_hand_tpu.obs.metrics import (
        engine_registry, prometheus_text, slo_report,
    )
    from mano_hand_tpu.obs.recorder import FlightRecorder
    from mano_hand_tpu.obs.sentinel import NumericsSentinel
    from mano_hand_tpu.runtime.chaos import ChaosPlan
    from mano_hand_tpu.runtime.supervise import DispatchPolicy
    from mano_hand_tpu.serving.engine import ServingEngine

    if requests < 1:
        raise ValueError(f"requests must be >= 1, got {requests}")
    log = _logger(log)
    max_rows = min(max_rows, max_bucket)
    min_rows = max(1, min(min_rows, max_rows))
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    sizes = rng.integers(min_rows, max_rows + 1, size=requests)
    stream = [
        (rng.normal(scale=0.4, size=(n, n_joints, 3)).astype(np.float32),
         rng.normal(size=(n, n_shape)).astype(np.float32))
        for n in (int(s) for s in sizes)
    ]
    rows_total = int(sizes.sum())

    tracer = Tracer()
    eng_bare = ServingEngine(params, max_bucket=max_bucket,
                             max_delay_s=max_delay_s)
    eng_obs = ServingEngine(params, max_bucket=max_bucket,
                            max_delay_s=max_delay_s, tracer=tracer)
    sentinel = NumericsSentinel(eng_obs, tracer=tracer,
                                interval_s=3600.0)
    reg = engine_registry(eng_obs, tracer=tracer, sentinel=sentinel)
    reps = max(1, int(reps))

    def run_bare():
        t0 = time.perf_counter()
        for _ in range(reps):
            futs = [eng_bare.submit(p, s) for p, s in stream]
            for f in futs:
                f.result()
        return time.perf_counter() - t0

    def run_obs():
        # The observed pass carries the FULL health surface: the
        # traced engine serves the stream, then ONE registry scrape
        # (snapshot + Prometheus render) and ONE sentinel probe land
        # inside the window — at the pass boundary, never inside the
        # submit loop (the starved-coalescing dead-end above).
        t0 = time.perf_counter()
        for _ in range(reps):
            futs = [eng_obs.submit(p, s) for p, s in stream]
            for f in futs:
                f.result()
        prometheus_text(reg.snapshot())
        sentinel.probe()
        return time.perf_counter() - t0

    ratios: List[float] = []
    dt_obs_best = dt_bare_best = float("inf")
    with eng_bare, eng_obs:
        eng_bare.warmup()
        eng_obs.warmup()
        golden = sentinel.arm()     # goldens check + reference compiles
        sentinel.probe()            # land the probe-shape compiles
        run_bare()                  # settle both pipelines
        run_obs()
        compiles_warm = eng_obs.counters.compiles
        for t in range(max(1, trials)):
            # Alternate which engine goes first (the measure_overhead
            # monotone-drift defense).
            if t % 2 == 0:
                dt_obs, dt_bare = run_obs(), run_bare()
            else:
                dt_bare, dt_obs = run_bare(), run_obs()
            ratios.append(dt_obs / dt_bare)
            dt_obs_best = min(dt_obs_best, dt_obs)
            dt_bare_best = min(dt_bare_best, dt_bare)
        steady_recompiles = eng_obs.counters.compiles - compiles_warm
        # Background-loop proof: the low-rate daemon probe fires on its
        # own (bounded wait, not load-bearing for the ratio above).
        before = sentinel.status()["probes"]
        sentinel.interval_s = 0.02
        sentinel.start()
        deadline = time.monotonic() + 10.0
        while (sentinel.status()["probes"] <= before
               and time.monotonic() < deadline):
            time.sleep(0.01)
        sentinel.stop()
        background_probes = sentinel.status()["probes"] - before
        slo = slo_report(eng_obs.counters.snapshot())
        final_snapshot = reg.snapshot()
    accounting = tracer.accounting()
    ratio = float(np.median(ratios))
    rows_total *= reps              # rows served per timed pass
    log(f"metrics: observed {rows_total / dt_obs_best:,.0f} vs bare "
        f"{rows_total / dt_bare_best:,.0f} evals/s (median paired "
        f"ratio {ratio:.3f}), {steady_recompiles} steady recompiles, "
        f"{sentinel.status()['probes']} probes "
        f"({background_probes} background), golden "
        f"{golden['golden_status']}")

    # ---- the sentinel drill: injected silent corruption MUST be seen.
    plan = ChaosPlan()
    pol = DispatchPolicy(deadline_s=20.0, retries=0, chaos=plan)
    tr3 = Tracer()
    eng3 = ServingEngine(params, min_bucket=8, max_bucket=8,
                         max_delay_s=max_delay_s, policy=pol,
                         tracer=tr3)
    rec3 = FlightRecorder(tr3, eng3.counters)
    s3 = NumericsSentinel(eng3, tracer=tr3, interval_s=3600.0)
    wave = [
        (rng.normal(scale=0.4, size=(int(n), n_joints, 3)).astype(
            np.float32),
         rng.normal(size=(int(n), n_shape)).astype(np.float32))
        for n in rng.integers(1, 5, size=12)
    ]

    def submit_wave():
        # "Resolved" = the engine guarantee: a RESULT or a structured
        # error within the window — never a hang. (The wrong-output
        # fault resolves every future with a result; that it is the
        # WRONG result is exactly what only the sentinel can see.)
        import concurrent.futures as cf

        futs = [eng3.submit(p, s) for p, s in wave]
        resolved = 0
        for f in futs:
            try:
                f.result(timeout=60.0)
                resolved += 1
            except cf.TimeoutError:
                pass
            except Exception:  # noqa: BLE001 — structured error resolves
                resolved += 1
        return resolved, len(futs)

    with eng3:
        eng3.warmup()               # primary + CPU-failover tier
        s3.arm()
        ok0, n0 = submit_wave()     # clean traffic
        clean = s3.probe()
        drill_compiles_warm = eng3.counters.compiles
        # The silent-corruption fault: every wrapped (primary) call
        # from here returns verts + 1.0 — no exception, so
        # supervision/retries/failover never fire and every future
        # still resolves "ok". Only the sentinel can see this.
        plan.schedule("wrong:1.0@0-")
        ok1, n1 = submit_wave()
        detected = s3.probe()
        plan.clear()                # the fault clears (tunnel healed)
        recovered = s3.probe()
        drill_recompiles = eng3.counters.compiles - drill_compiles_warm
    drill_acc = tr3.accounting()
    fam = detected["families"]
    drill = {
        "submitted": n0 + n1,
        "futures_resolved_fraction": (ok0 + ok1) / (n0 + n1),
        "clean_probe_drift": bool(clean["drift"]),
        "detected": bool(detected["drift"]),
        "drifted_families": detected["drifted_families"],
        "drift_max_abs_err": max(
            (fam[f]["max_abs_err"] for f in
             detected["drifted_families"]), default=None),
        "cpu_family_clean": ("cpu" in fam
                             and not fam["cpu"]["drift"]),
        "recovered": not recovered["drift"],
        "incidents": drill_acc["incidents"],
        "flight_capture_reasons": [c.get("reason")
                                   for c in rec3.captures],
        "faults_injected": int(eng3.counters.faults_injected),
        "steady_recompiles": int(drill_recompiles),
        "span_accounting": drill_acc,
    }
    log(f"sentinel drill: detected={drill['detected']} "
        f"(families {drill['drifted_families']}, max err "
        f"{drill['drift_max_abs_err']}), cpu clean "
        f"{drill['cpu_family_clean']}, recovered "
        f"{drill['recovered']}, {drill['futures_resolved_fraction']:.0%}"
        f" of {drill['submitted']} futures resolved, "
        f"{drill['incidents']} incident(s)")

    out = {
        "requests": int(requests),
        "trials": int(max(1, trials)),
        "reps_per_pass": int(reps),
        "scrapes_per_pass": 1,
        "probes_per_pass": 1,
        "rows": [int(sizes.min()), int(sizes.max())],
        "buckets": list(eng_obs.buckets),
        "observed_evals_per_sec": float(
            f"{rows_total / dt_obs_best:.5g}"),
        "bare_evals_per_sec": float(
            f"{rows_total / dt_bare_best:.5g}"),
        "metrics_overhead_ratio": float(f"{ratio:.4g}"),
        "ratio_best_window": float(
            f"{dt_obs_best / dt_bare_best:.4g}"),
        "ratio_trials": [float(f"{r:.3g}") for r in ratios],
        "steady_recompiles": int(steady_recompiles),
        "span_accounting": accounting,
        "registry_metrics": len(final_snapshot.get("metrics", {})),
        "registry_errors": final_snapshot.get("errors"),
        "sentinel": {k: v for k, v in sentinel.status().items()
                     if k != "last"},
        "sentinel_background_probes": int(background_probes),
        "golden": golden,
        "slo": slo,
        "sentinel_drill": drill,
        "flight_record": flight_record(
            tracer, eng_obs.counters,
            reason="metrics_overhead_complete"),
    }
    if metrics_dir is not None:
        from mano_hand_tpu.obs.metrics import export_metrics_dir

        out["metrics_export"] = export_metrics_dir(
            final_snapshot, metrics_dir, slo=slo)
    return out


def posed_kernel_bench_run(
    params,
    *,
    subjects: int = 8,
    requests: int = 96,
    min_rows: int = 1,
    max_rows: int = 4,
    max_bucket: int = 64,
    max_delay_s: float = 0.002,
    seed: int = 0,
    trials: int = 5,
    lm_batch: int = 32,
    lm_steps: Tuple[int, int] = (4, 10),
    lm_iters: int = 3,
    interpret: Optional[bool] = None,
    trace_dir=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE fused-vs-XLA gathered-dispatch benchmark protocol — bench.py
    config14 (PR 10).

    The serving hot path's kernel tier, measured where it serves: the
    SAME mixed-subject pose-only stream drives TWO engines — one on the
    fused Pallas gathered kernel (``posed_kernel="fused"``,
    ops/pallas_posed.py), one on the PR-4 XLA gathered program — and
    the comparison is SLOPE-TIMED through the engine (t(all requests)
    minus t(half), so per-eval cost sheds the fixed submit/coalesce/
    dispatcher overhead both sides share; naive per-pass timing on the
    tunnel lies — bench.py:slope_time's reasoning applied at the
    request-stream level). All four timing points run INTERLEAVED per
    trial with alternating order and min-over-trials per point (the
    measure_overhead drift defense; this box's load moves 5x between
    seconds).

    Returned criteria numbers (scripts/bench_report.py judges):

    * ``fused_vs_gather_max_abs_err`` <= 1e-5 — the fused tier's rows
      vs the per-subject posed program (== ``forward_posed_gather``
      bit-identically) at matched padded size, probed through the LIVE
      engine, mixed-subject coalesced batches included (the kernel's
      rows are computed independently, so parity is row-wise
      well-defined at any batch composition);
    * ``xla_vs_gather_max_abs_err`` == 0.0 — the control side keeps the
      PR-4 bit-identity contract (a nonzero here means the harness, not
      the kernel, drifted);
    * ``steady_recompiles_fused`` == ``steady_recompiles_xla`` == 0 —
      both tiers serve every subject mixture from warm executables
      (table + index are runtime args on BOTH);
    * ``fused_vs_xla_ratio`` — the headline speed number, judged ONLY
      on a real TPU (``platform``/``interpret`` ride in the artifact:
      the CPU lane runs the kernel through the Pallas interpreter,
      where the ratio measures emulation overhead, not the chip — the
      chip leg is queued via scripts/bench_tpu_wait.sh).

    The ``lm_e2e_*`` sub-leg rides along (ROADMAP item 2b): end-to-end
    ``fit_lm`` steps/s with the batched-LU normal equations that landed
    8x the vmapped Cholesky in ISOLATION but were never measured
    end-to-end on chip — slope-timed over ``lm_steps`` so the fixed
    setup cost cancels, recorded here so the first tunnel-up window
    measures both halves of ROADMAP item 2 in one artifact.

    ``trace_dir`` exports the fused engine's Chrome-trace timeline +
    flight record into ``<trace_dir>/posed_kernel/`` (a subdirectory so
    config12's export is not clobbered); ``scripts/trace_report.py``
    globs recursively and reports both.
    """
    import jax

    from mano_hand_tpu.models import core
    from mano_hand_tpu.serving import buckets as bucket_mod
    from mano_hand_tpu.serving.engine import ServingEngine

    if subjects < 1:
        raise ValueError(f"subjects must be >= 1, got {subjects}")
    if requests < 2:
        raise ValueError(f"requests must be >= 2, got {requests}")
    log = _logger(log)
    max_rows = min(max_rows, max_bucket)
    min_rows = max(1, min(min_rows, max_rows))
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    betas = [rng.normal(size=(n_shape,)).astype(np.float32)
             for _ in range(subjects)]
    sizes = rng.integers(min_rows, max_rows + 1, size=requests)
    subj_of = rng.integers(0, subjects, size=requests)
    stream = [
        (rng.normal(scale=0.4,
                    size=(int(n), n_joints, 3)).astype(np.float32), int(s))
        for n, s in zip(sizes, subj_of)
    ]
    # The two slope points: the full stream and its first half. Request
    # mix (sizes, subjects) is identical over the shared prefix, so the
    # slope is the marginal cost of the TAIL requests with the fixed
    # overhead (dispatcher wake, first-batch assembly) cancelled.
    m1 = max(1, requests // 2)
    m2 = requests
    rows_m1 = int(sizes[:m1].sum())
    rows_m2 = int(sizes.sum())
    d_rows = rows_m2 - rows_m1

    tracer_f, tracer_x = Tracer(), Tracer()
    eng_f = ServingEngine(params, max_bucket=max_bucket,
                          max_delay_s=max_delay_s, tracer=tracer_f,
                          posed_kernel="fused",
                          posed_kernel_interpret=interpret)
    eng_x = ServingEngine(params, max_bucket=max_bucket,
                          max_delay_s=max_delay_s, tracer=tracer_x,
                          posed_kernel="xla")

    prm_dev = params.astype(np.float32).device_put()
    shaped = [core.jit_specialize(prm_dev, b) for b in betas]
    # The row-wise parity reference: the per-subject posed program —
    # the PR-4 gathered family is f32 bit-identical to it per row, so
    # one reference serves both sides' parity numbers.
    ref_exe = jax.jit(
        lambda sh, p: core.forward_posed_batched(sh, p).verts)

    def ref_one(pose, si):
        b = bucket_mod.bucket_for(pose.shape[0], eng_f.buckets)
        out = ref_exe(shaped[si],
                      np.asarray(bucket_mod.pad_rows(pose, b)))
        return np.asarray(out)[:pose.shape[0]]

    def run_stream(eng, keys, m):
        t0 = time.perf_counter()
        futs = [eng.submit(p, subject=keys[si]) for p, si in stream[:m]]
        for f in futs:
            f.result()
        return time.perf_counter() - t0

    results = {}
    with eng_f, eng_x:
        keys_f = [eng_f.specialize(b) for b in betas]
        keys_x = [eng_x.specialize(b) for b in betas]
        log(f"posed-kernel: {subjects} subjects baked on both engines, "
            f"warming buckets {eng_f.buckets}")
        src_f = eng_f.warmup_posed()
        eng_x.warmup_posed()
        for b in eng_f.buckets:   # warm the parity reference's buckets
            jax.block_until_ready(ref_exe(
                shaped[0], np.zeros((b, n_joints, 3), np.float32)))

        # Parity through the LIVE engines (the CLAUDE.md in-context
        # rule): sequential single requests AND a concurrently-
        # submitted mixed-subject burst that coalesces into gathered
        # batches on each side.
        err_f = err_x = 0.0
        probe = stream[:min(8, len(stream))]
        for pose, si in probe:
            err_f = max(err_f, float(np.abs(
                eng_f.forward(pose, subject=keys_f[si])
                - ref_one(pose, si)).max()))
            err_x = max(err_x, float(np.abs(
                eng_x.forward(pose, subject=keys_x[si])
                - ref_one(pose, si)).max()))
        futs_f = [eng_f.submit(p, subject=keys_f[si]) for p, si in probe]
        futs_x = [eng_x.submit(p, subject=keys_x[si]) for p, si in probe]
        for (pose, si), ff, fx in zip(probe, futs_f, futs_x):
            want = ref_one(pose, si)
            err_f = max(err_f, float(np.abs(ff.result() - want).max()))
            err_x = max(err_x, float(np.abs(fx.result() - want).max()))

        run_stream(eng_f, keys_f, m2)
        run_stream(eng_x, keys_x, m2)   # settle both sides untimed
        compiles_f = eng_f.counters.compiles
        compiles_x = eng_x.counters.compiles

        thunks = {
            "f1": lambda: run_stream(eng_f, keys_f, m1),
            "f2": lambda: run_stream(eng_f, keys_f, m2),
            "x1": lambda: run_stream(eng_x, keys_x, m1),
            "x2": lambda: run_stream(eng_x, keys_x, m2),
        }
        best = {k: float("inf") for k in thunks}
        for t in range(max(1, trials)):
            order = sorted(thunks) if t % 2 == 0 \
                else sorted(thunks, reverse=True)
            for k in order:
                best[k] = min(best[k], thunks[k]())
        steady_f = eng_f.counters.compiles - compiles_f
        steady_x = eng_x.counters.compiles - compiles_x
        snap_f = eng_f.counters.snapshot()
        cap = eng_f.numerics_probe_targets()
        results.update({
            "capacity": cap["table"].capacity,
            "gather_fused_active": bool(cap["gather_fused"]),
            "interpret": bool(cap["gather_fused_interpret"]),
        })

    d_f = best["f2"] - best["f1"]
    d_x = best["x2"] - best["x1"]
    fused_rate = d_rows / d_f if d_f > 0 else float("nan")
    xla_rate = d_rows / d_x if d_x > 0 else float("nan")
    ratio = d_x / d_f if d_f > 0 and d_x > 0 else float("nan")
    platform = jax.default_backend()
    log(f"posed-kernel: fused {fused_rate:,.0f} vs xla {xla_rate:,.0f} "
        f"evals/s (slope ratio {ratio:.2f}x, platform {platform}, "
        f"interpret={results.get('interpret')}), parity fused "
        f"{err_f:.2e} / xla {err_x:.2e}, steady recompiles "
        f"{steady_f}/{steady_x}")

    # -- ROADMAP 2b sub-leg: end-to-end LM steps/s (batched-LU solve) --
    # lm_batch=0 skips it: the two fit_lm step-count programs are cold
    # compiles in a fresh cache, which plumbing-size lanes (the bench
    # tiny-e2e test inside the tier-1 budget) cannot afford — the
    # config13 skip precedent. The judge prints lm_e2e only when
    # present, so a skipped sub-leg is unmeasured, never failed.
    lm = {}
    if lm_batch > 0:
        from mano_hand_tpu.fitting import fit_lm

        lm_pose = rng.normal(
            scale=0.3, size=(lm_batch, n_joints, 3)).astype(np.float32)
        lm_beta = rng.normal(size=(n_shape,)).astype(np.float32)
        targets = core.jit_forward_batched(
            prm_dev, lm_pose,
            np.broadcast_to(lm_beta, (lm_batch, n_shape))).verts

        def run_lm(steps):
            return float(fit_lm(prm_dev, targets,
                                n_steps=steps).final_loss.sum())

        run_lm(lm_steps[0])   # compile + settle both step-count programs
        run_lm(lm_steps[1])
        best_lm = {s: float("inf") for s in lm_steps}
        for t in range(max(1, lm_iters)):
            order = lm_steps if t % 2 == 0 else lm_steps[::-1]
            for s in order:
                t0 = time.perf_counter()
                run_lm(s)
                best_lm[s] = min(best_lm[s], time.perf_counter() - t0)
        d_lm = best_lm[lm_steps[1]] - best_lm[lm_steps[0]]
        lm_rate = ((lm_steps[1] - lm_steps[0]) / d_lm
                   if d_lm > 0 else float("nan"))
        log(f"posed-kernel lm_e2e b={lm_batch}: {lm_rate:,.1f} steps/s "
            f"(batched-LU normal equations, analytic Jacobian)")
        lm = {
            "lm_e2e_steps_per_sec": float(f"{lm_rate:.5g}"),
            "lm_e2e_batch": int(lm_batch),
            "lm_e2e_steps": list(lm_steps),
            "lm_e2e_jacobian": "analytic",
            "lm_e2e_normal_eq": "high",
        }

    results.update({
        "subjects": int(subjects),
        "requests": int(requests),
        "rows": [int(sizes.min()), int(sizes.max())],
        "buckets": list(eng_f.buckets),
        "platform": platform,
        "warmup_posed_sources": src_f,
        "slope_points": {"m1": m1, "m2": m2,
                         "rows_m1": rows_m1, "rows_m2": rows_m2},
        "fused_evals_per_sec": float(f"{fused_rate:.5g}"),
        "xla_evals_per_sec": float(f"{xla_rate:.5g}"),
        "fused_vs_xla_ratio": float(f"{ratio:.4g}"),
        "fused_vs_gather_max_abs_err": err_f,
        "xla_vs_gather_max_abs_err": err_x,
        "steady_recompiles_fused": int(steady_f),
        "steady_recompiles_xla": int(steady_x),
        "mixed_subject_batches": snap_f["mixed_subject_batches"],
        "coalesce_width_mean": snap_f["coalesce_width_mean"],
        "dispatches": snap_f["dispatches"],
        **lm,
        "flight_record": flight_record(
            tracer_f, eng_f.counters, reason="posed_kernel_complete"),
    })
    if trace_dir is not None:
        import os

        from mano_hand_tpu.obs import write_trace_dir

        results["trace_export"] = write_trace_dir(
            tracer_f, os.path.join(str(trace_dir), "posed_kernel"),
            counters=eng_f.counters, reason="posed_kernel_complete")
    return results


def stream_drill_run(
    params,
    *,
    streams: int = 208,
    frames_per_stream: int = 4,
    subjects: Optional[int] = None,
    workers: int = 16,
    warm_steps: int = 4,
    cold_steps_candidates: Sequence[int] = (8, 16, 32),
    target_loss: float = 1e-9,
    frame_deadline_s: float = 5.0,
    batch_deadline_s: float = 10.0,
    min_bucket: int = 8,
    max_bucket: int = 64,
    max_delay_s: float = 0.002,
    chaos_spec: str = "error@0-",
    calib_probes: int = 12,
    fit_trials: int = 5,
    seed: int = 0,
    tracer=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE streaming-session drill protocol — shared by ``bench.py``
    config15, `mano serve-bench --streams`, and tests/test_streams.py
    so the three artifacts cannot diverge (the recovery-drill pattern).

    The scenario PR 12 exists for: hundreds of per-user tracking
    sessions, each a stream of correlated frames. Every stream gets its
    own synthetic subject (assets/synthetic.py betas) and a SMOOTH pose
    track (models/anim.py:resample_poses over seeded keyframes — the
    correlated-frames premise is the product premise), and each frame
    runs the full session step: frozen-shape LM fit warm-started from
    the last converged pose, then the posed verts through the gathered
    SubjectTable dispatch at tier 0 with a per-frame deadline.
    Concurrent streams submit from a ``workers``-wide pool, so frames
    coalesce into mixed-subject batches exactly as production traffic
    would.

    Phases: bake every subject BEFORE warming the gathered executables
    (growth compiles are warm-up-class work, and pre-baking means zero
    growth-rebuilds), warm every tier (primary + gathered + the CPU
    failover tier the chaos leg will need), open every stream, run a
    settle round (the fit program's one compile lands there), TIMED
    steady rounds, the warm-vs-cold calibration, then a CHAOS round
    under ``chaos_spec`` (persistent primary fault: every frame must
    resolve through supervised retries + CPU failover, bit-identical),
    then close.

    Returned criteria numbers (scripts/bench_report.py judges):

    * ``frames_resolved_fraction`` == 1.0 with ``outcomes.error`` == 0
      and ``outcomes.stranded`` == 0 — every frame of every stream,
      chaos round included, resolves as ok/shed/expired, never a hang;
    * ``warm_vs_cold_fit_ratio`` >= 1.2 (judged when
      ``warm_loss_matched``) — the warm-started per-frame fit vs the
      cheapest cold fit reaching the same ``target_loss``, both
      SLOPE-TIMED (marginal per-fit cost over two in-pass repeat
      counts, the bench.py:slope_time reasoning — fixed dispatch
      overhead cancels);
    * ``failover_vs_cpu_direct_max_abs_err`` == 0.0 — a chaos-round
      frame served by CPU failover is bit-identical to a direct CPU
      call at the same pose/betas, and the warm start it leaves behind
      is the fit's own pose (serving faults never touch the solver);
    * ``steady_recompiles`` == 0 — N streams share one program family;
      the whole drill compiles nothing after warm-up;
    * ``slo.tiers["0"]`` carries burn rates INCLUDING the frame-latency
      p99 objective (``p99_target_ms`` = the frame deadline) computed
      from the drill's end-to-end frame latencies;
    * stream spans: every opened session reaches exactly one terminal
      (``closed`` for the explicit closes, ``shutdown`` for the ones
      ``stop()`` sweeps), and the flight record's request-span
      accounting balances.

    Everything runs on whatever backend is up; faults are injected
    in-process, so no chip is required and none is harmed.
    """
    import concurrent.futures as cf

    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.fitting import lm as lm_mod
    from mano_hand_tpu.models import anim, core
    from mano_hand_tpu.runtime.chaos import ChaosPlan
    from mano_hand_tpu.runtime.supervise import DispatchPolicy
    from mano_hand_tpu.serving.engine import ServingEngine, ServingError

    if streams < 1:
        raise ValueError(f"streams must be >= 1, got {streams}")
    min_frames = 3 if chaos_spec else 2
    if frames_per_stream < min_frames:
        # With a chaos spec the LAST round is the chaos round, so the
        # floor is settle + >= 1 TIMED steady round + chaos — fewer
        # and the latency record is empty, which would fail the judged
        # SLO latency-burn criterion on an otherwise clean run.
        raise ValueError(
            f"frames_per_stream must be >= {min_frames} (a settle "
            f"round, at least one timed steady round"
            f"{', and the chaos round' if chaos_spec else ''}), got "
            f"{frames_per_stream}")
    if workers < 1:
        raise ValueError(f"workers must be >= 1, got {workers}")
    log = _logger(log)
    if tracer is None:
        tracer = Tracer()
    subjects = streams if subjects is None else max(1, int(subjects))
    calib_probes = max(1, min(calib_probes, streams))
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    prm32 = params.astype(np.float32)

    # ---- Synthetic per-user tracks (the correlated-frames premise) ----
    betas = [rng.normal(size=(n_shape,)).astype(np.float32)
             for _ in range(subjects)]
    subj_of = [s % subjects for s in range(streams)]
    # Keyframes rest -> two random poses, retimed to the frame count
    # (anim.resample_poses): smooth, so the warm start is always near.
    keys = np.zeros((streams, 3, n_joints, 3), np.float32)
    keys[:, 1] = rng.normal(scale=0.2, size=(streams, n_joints, 3))
    keys[:, 2] = keys[:, 1] + rng.normal(
        scale=0.1, size=(streams, n_joints, 3))
    tracks = np.stack([
        anim.resample_poses(keys[s], frames_per_stream)
        for s in range(streams)]).astype(np.float32)   # [S, T, J, 3]
    flat_pose = tracks.reshape(streams * frames_per_stream, n_joints, 3)
    flat_beta = np.stack([betas[subj_of[s]]
                          for s in range(streams)
                          for _ in range(frames_per_stream)])
    gt = core.jit_forward_batched(prm32, jnp.asarray(flat_pose),
                                  jnp.asarray(flat_beta))
    targets = np.asarray(gt.posed_joints).reshape(
        streams, frames_per_stream, n_joints, 3)

    # ---- Engine: supervised + chaos-wrappable + CPU failover ----------
    plan = ChaosPlan()
    policy = DispatchPolicy(
        deadline_s=batch_deadline_s, retries=1, backoff_s=0.01,
        backoff_cap_s=0.02, jitter=0.0, breaker=None, chaos=plan,
        cpu_fallback=True,
    )
    eng = ServingEngine(prm32, min_bucket=min_bucket,
                        max_bucket=max_bucket, max_delay_s=max_delay_s,
                        policy=policy, tracer=tracer)

    # Bit-identity reference for the failover parity probe: the same
    # params-as-runtime-args program family, pinned to host CPU.
    cpu = jax.devices("cpu")[0]
    prm_cpu = jax.device_put(prm32, cpu)
    ref = jax.jit(lambda q, p, s: core.forward_batched(q, p, s).verts)

    def cpu_direct(pose, beta):
        return np.asarray(ref(
            prm_cpu, jax.device_put(jnp.asarray(pose[None]), cpu),
            jax.device_put(jnp.asarray(beta[None]), cpu)))[0]

    outcomes = {"ok": 0, "shed": 0, "expired": 0, "error": 0,
                "stranded": 0}
    chaos_outcomes = dict(outcomes)
    frame_lat: List[float] = []
    round_times: List[float] = []
    failover_err = None

    pool = cf.ThreadPoolExecutor(max_workers=workers,
                                 thread_name_prefix="stream-drill")
    try:
        with eng:
            keys_subj = [eng.specialize(b) for b in betas]
            growths = eng.counters.table_growths
            if log:
                log(f"streams: {subjects} subjects baked ({growths} "
                    f"table growths), warming buckets {eng.buckets}")
            eng.warmup()          # primary full + CPU failover tiers
            eng.warmup_posed()    # gathered tier at final capacity
            sessions = [
                eng.open_stream(keys_subj[subj_of[s]],
                                n_steps=warm_steps, data_term="joints",
                                frame_deadline_s=frame_deadline_s)
                for s in range(streams)]

            resolve_timeout = (frame_deadline_s
                               + batch_deadline_s * (policy.retries + 2)
                               + 30.0)

            def tally_frame(ff, tally):
                """Classify one frame future into the outcome tally
                (THE one classification — settle/chaos and timed
                rounds must never diverge on what counts as resolved);
                returns the FrameResult on ``ok``, else None."""
                try:
                    res = ff.result(timeout=resolve_timeout)
                    tally["ok"] += 1
                    return res
                except ServingError as e:
                    tally[e.kind if e.kind in tally else "error"] += 1
                except Exception:  # noqa: BLE001 — a timeout IS the bug
                    tally["stranded"] += 1
                return None

            def run_round(r, tally, deadline=True):
                """Submit frame r of every stream from the pool; wait
                for every frame future; tally outcomes.
                Returns (wall seconds, [FrameResult|None per stream]).
                ``deadline=False`` submits un-deadlined — the settle
                round, where the fit program's one compile holds the
                first frame wave for seconds of warm-up-class time
                that must not be judged as frame latency."""
                t0 = time.perf_counter()
                outer = [pool.submit(
                    sessions[s].submit_frame, targets[s, r],
                    deadline_s=frame_deadline_s if deadline else None)
                         for s in range(streams)]
                inner = []
                for of in outer:
                    try:
                        inner.append(of.result(timeout=120.0))
                    except Exception:  # noqa: BLE001 — a refused frame
                        inner.append(None)   # counts as stranded below
                results_r = []
                for ff in inner:
                    if ff is None:
                        tally["stranded"] += 1
                        results_r.append(None)
                        continue
                    results_r.append(tally_frame(ff, tally))
                return time.perf_counter() - t0, results_r

            # Frame latency must be END-TO-END (fit + dispatch), so
            # re-measure per frame around the whole submit+resolve in
            # the steady rounds below; the per-future wait above only
            # covers the dispatch tail. One honest clock: wrap the
            # round and divide is wrong (concurrency), so each frame's
            # latency is stamped by its own submit/resolve pair.
            def run_round_timed(r, tally):
                t0 = time.perf_counter()
                boxes = []

                def one(s):
                    t_sub = time.perf_counter()
                    ff = sessions[s].submit_frame(targets[s, r])
                    box = []
                    ff.add_done_callback(
                        lambda f, b=box, t=t_sub:
                            b.append(time.perf_counter() - t))
                    return ff, box

                outer = [pool.submit(one, s) for s in range(streams)]
                pairs = [of.result(timeout=120.0) for of in outer]
                for ff, box in pairs:
                    tally_frame(ff, tally)
                    boxes.append(box)
                dt = time.perf_counter() - t0
                frame_lat.extend(b[0] for b in boxes if b)
                return dt

            # Round 0: settle — the fit program's one compile and every
            # stream's frame-0 Kabsch seed land here, outside timing
            # and un-deadlined (compile latency is warm-up, not frame
            # latency; a cold start that must bound it has the PR-6
            # lattice for the serving half).
            dt0, _ = run_round(0, outcomes, deadline=False)
            compiles_settled = eng.counters.compiles
            if log:
                log(f"streams: settle round {dt0:.2f}s "
                    f"({eng.counters.compiles} warm-up compiles); "
                    f"{streams} streams x {frames_per_stream} frames")
            chaos_round = frames_per_stream - 1 if chaos_spec else None
            steady = [r for r in range(1, frames_per_stream)
                      if r != chaos_round]
            for r in steady:
                round_times.append(run_round_timed(r, outcomes))

            # ---- Warm-vs-cold calibration (slope-timed) --------------
            calib = _stream_fit_calibration(
                prm32, sessions[:calib_probes],
                [betas[subj_of[s]] for s in range(calib_probes)],
                [targets[s, chaos_round if chaos_round is not None
                         else frames_per_stream - 1]
                 for s in range(calib_probes)],
                lm_mod, warm_steps=warm_steps,
                cold_steps_candidates=tuple(cold_steps_candidates),
                target_loss=target_loss, trials=fit_trials, log=log)

            # ---- Chaos round: persistent primary fault ---------------
            failovers_before = eng.counters.failovers
            warm_start_consistent = None
            if chaos_round is not None:
                probe_s = 0
                plan.schedule(chaos_spec)
                try:
                    _, results_c = run_round(chaos_round, chaos_outcomes)
                finally:
                    plan.clear()
                res = results_c[probe_s]
                if res is not None:
                    # Failover parity: the frame's verts vs a direct
                    # CPU call at the SAME (pose, betas); and the warm
                    # start it left behind is the fit's own converged
                    # pose — the serving fault never touched the
                    # solver, so the stream resumes seamlessly.
                    failover_err = float(np.abs(
                        res.verts - cpu_direct(
                            res.pose, betas[subj_of[probe_s]])).max())
                    warm_start_consistent = bool(np.array_equal(
                        sessions[probe_s].pose, res.pose))
                for k, v in chaos_outcomes.items():
                    outcomes[k] += v
            failovers = eng.counters.failovers - failovers_before

            steady_recompiles = (eng.counters.compiles
                                 - compiles_settled)
            # Close all but two sessions explicitly; stop() must sweep
            # the stragglers to the ``shutdown`` terminal.
            for sess in sessions[:-2]:
                sess.close()
            load_final = eng.load()
            snap = eng.counters.snapshot()
    finally:
        pool.shutdown(wait=False)
        plan.release.set()

    # AFTER stop(): the sweep moved the straggler sessions to the
    # ``shutdown`` terminal, so this snapshot carries the full
    # closed-by-kind ledger the span criterion judges.
    streams_snap = eng.load()["streams"]
    submitted = sum(outcomes.values())
    resolved_fraction = (1.0 - outcomes["stranded"] / submitted
                         if submitted else 0.0)
    lat_ms = np.asarray(frame_lat) * 1e3 if frame_lat else None
    p50 = float(np.percentile(lat_ms, 50)) if lat_ms is not None else None
    p99 = float(np.percentile(lat_ms, 99)) if lat_ms is not None else None
    fps = (max(streams / t for t in round_times)
           if round_times else None)
    from mano_hand_tpu.obs.metrics import (
        DEFAULT_SLO_OBJECTIVES, slo_report,
    )

    objectives = {
        "0": {**DEFAULT_SLO_OBJECTIVES["0"],
              "p99_target_ms": frame_deadline_s * 1e3},
        "default": DEFAULT_SLO_OBJECTIVES["default"],
    }
    slo = slo_report(
        snap, objectives,
        latency_by_tier={"0": {"p50_ms": p50, "p99_ms": p99,
                               "n": len(frame_lat)}}
        if lat_ms is not None else None)
    if log:
        log(f"streams: {submitted} frames -> {outcomes['ok']} ok / "
            f"{outcomes['shed']} shed / {outcomes['expired']} expired / "
            f"{outcomes['error']} error / {outcomes['stranded']} "
            f"stranded; {fps and f'{fps:,.0f}'} frames/s steady, p99 "
            f"{p99 and f'{p99:.1f}'} ms, warm/cold fit ratio "
            f"{calib.get('warm_vs_cold_fit_ratio')}, {failovers} "
            f"failover(s), {steady_recompiles} steady recompiles")
    return {
        "streams": int(streams),
        "frames_per_stream": int(frames_per_stream),
        "subjects": int(subjects),
        "workers": int(workers),
        "buckets": list(eng.buckets),
        "frame_deadline_s": frame_deadline_s,
        "frames_submitted": int(submitted),
        "frames_resolved_fraction": float(f"{resolved_fraction:.6g}"),
        "outcomes": outcomes,
        "chaos_spec": chaos_spec or None,
        "chaos_outcomes": chaos_outcomes if chaos_spec else None,
        "failovers": int(failovers),
        "failover_vs_cpu_direct_max_abs_err": failover_err,
        "warm_start_after_failover_consistent": warm_start_consistent,
        "frames_per_sec": (None if fps is None
                           else float(f"{fps:.5g}")),
        "frame_p50_ms": (None if p50 is None
                         else float(f"{p50:.4g}")),
        "frame_p99_ms": (None if p99 is None
                         else float(f"{p99:.4g}")),
        **calib,
        "steady_recompiles": int(steady_recompiles),
        "table_growths": snap["table_growths"],
        "mixed_subject_batches": snap["mixed_subject_batches"],
        "coalesce_width_mean": snap["coalesce_width_mean"],
        "dispatches": snap["dispatches"],
        "stream_spans": {
            "opened": streams_snap["opened"],
            "closed_by_kind": streams_snap["closed_by_kind"],
            "active_after_stop": streams_snap["active"],
        },
        "slo": slo,
        "load_final": {k: load_final[k]
                       for k in ("outstanding", "queued", "streams",
                                 "backlog_age_s")
                       if k in load_final},
        "flight_record": flight_record(
            tracer, eng.counters, reason="stream_drill_complete"),
    }


def _stream_fit_calibration(prm32, sessions, betas, next_targets,
                            lm_mod, *, warm_steps, cold_steps_candidates,
                            target_loss, trials, log) -> dict:
    """The warm-start criterion's measurement (stream_drill_run):
    warm-started frozen-shape fits at ``warm_steps`` vs the cheapest
    COLD fit (rest-pose init) reaching the same convergence bar,
    both slope-timed.

    Loss parity first: a speed ratio between solves of different
    quality would be fiction. ``target_loss`` is the converged-for-
    tracking bar (mean-squared joint residual, m^2); the warm side
    must sit under it (``warm_loss_matched``) and the cold side's step
    count is the smallest candidate whose median loss also does.
    Then the slope: per-fit marginal cost over two in-pass repeat
    counts (m and 2m fits, quotient of the difference — the
    bench.py:slope_time reasoning at the call level), interleaved
    warm/cold per trial with min-over-trials per point (this box's
    load drifts 5x between seconds; the measure_overhead defense).
    """
    import jax

    probes = []
    for sess, beta, target in zip(sessions, betas, next_targets):
        probes.append((sess.pose, beta, target))

    def warm_fit(i, n_steps=warm_steps):
        pose, beta, target = probes[i % len(probes)]
        return lm_mod.fit_lm(prm32, target, n_steps=n_steps,
                             data_term="joints", init={"pose": pose},
                             frozen_shape=beta)

    def cold_fit(i, n_steps):
        _, beta, target = probes[i % len(probes)]
        return lm_mod.fit_lm(prm32, target, n_steps=n_steps,
                             data_term="joints", frozen_shape=beta)

    warm_losses = []
    for i in range(len(probes)):
        res = warm_fit(i)
        warm_losses.append(float(jax.block_until_ready(res.final_loss)))
    warm_median = float(np.median(warm_losses))
    warm_ok = warm_median <= target_loss

    cold_steps = None
    cold_median = None
    for k in sorted(cold_steps_candidates):
        losses = []
        for i in range(len(probes)):
            res = cold_fit(i, k)
            losses.append(float(jax.block_until_ready(res.final_loss)))
        med = float(np.median(losses))
        if med <= target_loss:
            cold_steps, cold_median = int(k), med
            break
        cold_steps, cold_median = int(k), med   # keep the best-so-far
    matched = bool(warm_ok and cold_median is not None
                   and cold_median <= target_loss)

    # Slope timing: per-fit marginal cost, warm vs cold, four points
    # interleaved (the posed_kernel_bench_run thunk pattern).
    m1 = len(probes)
    m2 = 2 * m1

    def run_m(fit, m, n_steps):
        t0 = time.perf_counter()
        last = None
        for i in range(m):
            last = fit(i, n_steps)
        jax.block_until_ready(last.pose)
        return time.perf_counter() - t0

    thunks = {
        "w1": lambda: run_m(warm_fit, m1, warm_steps),
        "w2": lambda: run_m(warm_fit, m2, warm_steps),
        "c1": lambda: run_m(cold_fit, m1, cold_steps),
        "c2": lambda: run_m(cold_fit, m2, cold_steps),
    }
    for k in thunks:
        thunks[k]()     # settle: every program warm before timing
    best = {k: float("inf") for k in thunks}
    for t in range(max(1, trials)):
        order = sorted(thunks) if t % 2 == 0 \
            else sorted(thunks, reverse=True)
        for k in order:
            best[k] = min(best[k], thunks[k]())
    s_warm = (best["w2"] - best["w1"]) / (m2 - m1)
    s_cold = (best["c2"] - best["c1"]) / (m2 - m1)
    ratio = s_cold / s_warm if s_warm > 0 and s_cold > 0 else None
    if log:
        log(f"streams calib: warm {warm_steps} steps (median loss "
            f"{warm_median:.2e}) vs cold {cold_steps} steps (median "
            f"{cold_median:.2e}, bar {target_loss:.0e}, matched="
            f"{matched}); slope {s_warm * 1e3:.2f} vs "
            f"{s_cold * 1e3:.2f} ms/fit -> ratio "
            f"{ratio and f'{ratio:.2f}'}x")
    return {
        "warm_fit_steps": int(warm_steps),
        "cold_fit_steps": cold_steps,
        "fit_target_loss": target_loss,
        "warm_fit_loss_median": float(f"{warm_median:.5g}"),
        "cold_fit_loss_median": (None if cold_median is None
                                 else float(f"{cold_median:.5g}")),
        "warm_loss_matched": matched,
        "warm_fit_ms_per_frame": float(f"{s_warm * 1e3:.5g}"),
        "cold_fit_ms_per_frame": float(f"{s_cold * 1e3:.5g}"),
        "warm_fit_frames_per_sec": (
            None if s_warm <= 0 else float(f"{1.0 / s_warm:.5g}")),
        "cold_fit_frames_per_sec": (
            None if s_cold <= 0 else float(f"{1.0 / s_cold:.5g}")),
        "warm_vs_cold_fit_ratio": (
            None if ratio is None else float(f"{ratio:.4g}")),
    }


def lane_drill_run(
    params,
    *,
    lanes: int = 4,
    requests_per_pass: int = 96,
    subjects: int = 6,
    workers: int = 8,
    max_rows: int = 4,
    max_bucket: int = 16,
    deadline_s: float = 5.0,
    kill_lane: int = 1,
    lane_failover_budget: float = 0.05,
    seed: int = 0,
    tracer=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE lane-loss chaos drill (PR 13 tentpole; bench config16).

    One lane-aware ``ServingEngine`` (``lanes=N`` per-device dispatch
    lanes over the available devices — the CPU lane forces N>=4
    virtual host devices via bench.py ``--virtual-devices``; fewer
    devices oversubscribe round-robin, recorded in ``n_devices``) is
    driven by ``workers`` concurrent submitters through three passes:
    a healthy steady pass, a LOSS pass during which a ``%LANE``-tagged
    chaos plan kills exactly ``kill_lane`` (persistent error on that
    lane's own call index + its breaker probe forced false) while
    requests are in flight, and a post-failback steady pass after the
    fault clears. The done-criteria (scripts/bench_report.py:
    judge_lanes) read the returned numbers:

    * ``futures_resolved_fraction`` == 1.0 with zero ``error`` /
      ``stranded`` outcomes: losing one lane degraded CAPACITY, never
      the service — every future through the loss pass resolved ok
      via the sibling ladder;
    * ``loss_vs_reference_max_abs_err`` == 0.0: failover results are
      bit-identical to the single-device engine (same
      params/table-as-runtime-args program families);
    * ``cpu_failovers`` == 0: the ladder's SIBLING rung absorbed the
      loss — the CPU tier (still armed) was never needed while
      healthy siblings existed;
    * ``steady_recompiles_pre`` == 0 AND ``steady_recompiles_post``
      == 0: zero compiles before the loss and after failback (warm
      per-lane caches make the ladder and the failback free);
    * ``spans``: every request span closed exactly once, the loss
      pass included;
    * the killed lane's breaker re-probe schedule GREW while it was
      down (``breaker_probe_backoff_grew`` — the PR-13 probe-backoff
      satellite, observed in its natural habitat).

    Throughput per pass is recorded; the surviving-throughput ratio is
    judged only on a real multi-chip fleet (on this 1-core CPU box all
    virtual lanes share one core, so the ratio carries no signal — the
    config14 precedent). ``survivor_balance_ratio`` (max/min assigned
    among surviving lanes during the loss pass) is the CPU-judgeable
    stand-in: capacity loss spread evenly over the fleet.

    A mid-drill ``future.cancel()`` probe rides the loss pass (the
    PR-13 cancellation satellite): the cancelled future resolves as
    CancelledError, is counted per tier, and frees its admission slot.
    Faults are injected in-process; no chip is required and none is
    harmed.
    """
    import concurrent.futures as cf
    import threading

    from mano_hand_tpu.runtime.chaos import ChaosPlan
    from mano_hand_tpu.runtime.health import CircuitBreaker
    from mano_hand_tpu.runtime.supervise import DispatchPolicy
    from mano_hand_tpu.serving.engine import ServingEngine, ServingError

    log = _logger(log)
    if tracer is None:
        tracer = Tracer(capacity=65536)
    if kill_lane >= lanes:
        raise ValueError(
            f"kill_lane {kill_lane} out of range for {lanes} lanes")
    n_joints, n_shape = params.n_joints, params.n_shape
    prm32 = params.astype(np.float32)
    rng = np.random.default_rng(seed)
    subj_betas = [rng.normal(size=(n_shape,)).astype(np.float32)
                  for _ in range(subjects)]

    # One fixed request universe per pass, shared with the reference
    # engine so bit-identity is comparable request-for-request.
    def make_stream(n, pass_seed):
        r = np.random.default_rng(pass_seed)
        sizes = r.integers(1, max_rows + 1, size=n)
        return [(r.normal(scale=0.4,
                          size=(int(s), n_joints, 3)).astype(np.float32),
                 int(r.integers(0, subjects)))
                for s in sizes]

    streams = {name: make_stream(requests_per_pass, seed + 100 + i)
               for i, name in enumerate(("pre", "loss", "post"))}

    # Reference: the SINGLE-DEVICE engine (no lanes, no policy) over
    # the same subjects — the bit-identity bar for every lane result.
    ref_eng = ServingEngine(prm32, max_bucket=max_bucket,
                            max_delay_s=0.001)
    reference = {}
    with ref_eng:
        ref_keys = [ref_eng.specialize(b) for b in subj_betas]
        for name, stream in streams.items():
            reference[name] = [
                ref_eng.forward(p, subject=ref_keys[si])
                for p, si in stream]

    lane_ok = [True] * lanes
    plan = ChaosPlan()
    breaker_proto = CircuitBreaker(
        failure_threshold=2,
        # A tiny but NONZERO base interval: re-probes stay drill-fast,
        # and the exponential backoff (default 2.0x, capped 32x) is
        # observable in probe_wait_s — the PR-13 probe-backoff
        # satellite judged in its natural habitat.
        probe_interval_s=0.001,
        respect_priority_claim=False)
    policy = DispatchPolicy(
        deadline_s=deadline_s, retries=1, backoff_s=0.005,
        backoff_cap_s=0.01, jitter=0.0, breaker=breaker_proto,
        chaos=plan, cpu_fallback=True)
    eng = ServingEngine(
        prm32, max_bucket=max_bucket, max_delay_s=0.002,
        policy=policy, tracer=tracer, lanes=lanes,
        lane_probe=lambda i: lane_ok[i])
    resolve_timeout = deadline_s * (policy.retries + 2) * (lanes + 1) + 60.0

    def run_pass(stream, keys, cancel_probe=False):
        """Submit via a worker pool (concurrent in-flight streams —
        the 'mid-stream' in mid-stream lane loss), resolve everything,
        classify outcomes, and compare served results bitwise against
        the reference engine."""
        outcomes = {"ok": 0, "error": 0, "expired": 0, "stranded": 0,
                    "cancelled": 0}
        results = [None] * len(stream)
        t0 = time.perf_counter()
        lock = threading.Lock()
        cancelled_idx = len(stream) // 2 if cancel_probe else -1

        def submit_one(i):
            p, si = stream[i]
            fut = eng.submit(p, subject=keys[si])
            if i == cancelled_idx:
                fut.cancel()
            try:
                results[i] = fut.result(timeout=resolve_timeout)
                k = "ok"
            except cf.CancelledError:
                k = "cancelled"
            except ServingError as e:
                k = "expired" if e.kind == "expired" else "error"
            except Exception:   # noqa: BLE001 — a timeout IS the bug
                k = "stranded"
            with lock:
                outcomes[k] += 1

        with cf.ThreadPoolExecutor(max_workers=workers) as pool:
            list(pool.map(submit_one, range(len(stream))))
        dt = time.perf_counter() - t0
        return outcomes, results, dt

    def max_err(results, refs, skip=()):
        worst = 0.0
        for i, (got, want) in enumerate(zip(results, refs)):
            if got is None:
                if i in skip:
                    continue
                return None              # an unresolved result: no bar
            worst = max(worst, float(np.abs(got - want).max()))
        return worst

    def lane_block():
        return eng.load()["lanes"]

    try:
        with eng:
            keys = [eng.specialize(b) for b in subj_betas]
            buckets = [b for b in eng.buckets if b <= max_bucket]
            eng.warmup(buckets)
            eng.warmup_posed(buckets)
            warm_compiles = eng.counters.compiles
            log(f"lane drill: {lanes} lanes over "
                f"{eng._get_lanes().n_devices} device(s), "
                f"{warm_compiles} warm-up compiles")

            # -- pass 1: healthy steady state -------------------------
            oc_pre, res_pre, dt_pre = run_pass(streams["pre"], keys)
            recompiles_pre = eng.counters.compiles - warm_compiles
            err_pre = max_err(res_pre, reference["pre"])
            assigned_before_loss = {
                p["lane"]: p["assigned"]
                for p in lane_block()["per_lane"]}

            # -- pass 2: kill one lane MID-STREAM ---------------------
            # The %LANE-tagged plan fires on the killed lane's own
            # call counter (its first dispatch of this pass onward)
            # while `workers` submitters keep frames in flight; the
            # probe override keeps its breaker from closing until the
            # drill clears the fault.
            lane_ok[kill_lane] = False
            plan.schedule(f"error@0-%{kill_lane}")
            killed = eng._get_lanes().lanes[kill_lane]
            oc_loss, res_loss, dt_loss = run_pass(
                streams["loss"], keys, cancel_probe=True)
            probes_down = killed.breaker.probes
            backoff_grew = (killed.breaker.consecutive_failed_probes
                            >= 1)
            probe_wait_down_s = killed.breaker.probe_wait_s()
            snap_loss = lane_block()
            cancelled_i = len(streams["loss"]) // 2
            err_loss = max_err(res_loss, reference["loss"],
                               skip={cancelled_i})

            # -- pass 3: failback ------------------------------------
            plan.clear()
            lane_ok[kill_lane] = True
            # Settle: the next placements kick the killed lane's
            # re-probe, its breaker closes, traffic returns to it.
            oc_settle, res_settle, _ = run_pass(streams["pre"], keys)
            compiles_settled = eng.counters.compiles
            killed_assigned_settled = lane_block()[
                "per_lane"][kill_lane]["assigned"]
            oc_post, res_post, dt_post = run_pass(streams["post"], keys)
            recompiles_post = eng.counters.compiles - compiles_settled
            err_post = max_err(res_post, reference["post"])
            snap_final = lane_block()
            failback_served = (snap_final["per_lane"][kill_lane]
                               ["assigned"] > killed_assigned_settled)
            counters_snap = eng.counters.snapshot()
    finally:
        plan.release.set()

    per_loss = {p["lane"]: p for p in snap_loss["per_lane"]}
    survivors = [i for i in range(lanes) if i != kill_lane]
    surv_assigned = [
        per_loss[i]["assigned"] - assigned_before_loss.get(i, 0)
        for i in survivors]
    balance = (max(surv_assigned) / max(1, min(surv_assigned))
               if surv_assigned else None)
    killed_assigned_during_loss = (
        per_loss[kill_lane]["assigned"]
        - assigned_before_loss.get(kill_lane, 0))
    lane_failovers = sum(p["failovers_out"]
                         for p in snap_final["per_lane"])
    cpu_failovers = sum(p["cpu_failovers"]
                        for p in snap_final["per_lane"])
    # Per-lane availability burn (the PR-9 burn-rate shape at lane
    # granularity): fraction of a lane's batches it could not serve
    # itself, over the failover budget.
    lane_slo = {}
    for p in snap_final["per_lane"]:
        assigned = p["assigned"]
        frac = p["failovers_out"] / assigned if assigned else 0.0
        lane_slo[str(p["lane"])] = {
            "assigned": assigned,
            "failover_fraction": round(frac, 6),
            "burn": round(frac / lane_failover_budget, 4),
            "ok": frac <= lane_failover_budget,
        }

    n_total = 4 * requests_per_pass          # pre + loss + settle + post
    outcomes = {k: oc_pre[k] + oc_loss[k] + oc_settle[k] + oc_post[k]
                for k in oc_pre}
    resolved = n_total - outcomes["stranded"]
    acc = tracer.accounting()
    rate = lambda oc, dt: float(   # noqa: E731
        f"{(requests_per_pass - oc.get('stranded', 0)) / dt:.5g}")
    return {
        "lanes": lanes,
        "distinct_devices": snap_final["n_devices"],
        "kill_lane": kill_lane,
        "requests_per_pass": requests_per_pass,
        "workers": workers,
        "subjects": subjects,
        "futures_resolved_fraction": float(
            f"{resolved / n_total:.6g}"),
        "outcomes": outcomes,
        "pre_vs_reference_max_abs_err": err_pre,
        "loss_vs_reference_max_abs_err": err_loss,
        "post_vs_reference_max_abs_err": err_post,
        "steady_recompiles_pre": int(recompiles_pre),
        "steady_recompiles_post": int(recompiles_post),
        "warmup_compiles": int(warm_compiles),
        "lane_failovers": int(lane_failovers),
        "cpu_failovers": int(cpu_failovers),
        "killed_lane_assigned_during_loss": int(
            killed_assigned_during_loss),
        "survivor_balance_ratio": (float(f"{balance:.4g}")
                                   if balance is not None else None),
        "throughput_pre_per_sec": rate(oc_pre, dt_pre),
        "throughput_loss_per_sec": rate(oc_loss, dt_loss),
        "throughput_post_per_sec": rate(oc_post, dt_post),
        "surviving_throughput_ratio": float(
            f"{dt_pre / dt_loss:.4g}") if dt_loss else None,
        "breaker_probes_while_down": int(probes_down),
        "breaker_probe_backoff_grew": bool(backoff_grew),
        "breaker_probe_wait_down_s": float(
            f"{probe_wait_down_s:.4g}"),
        "failback_served": bool(failback_served),
        "cancelled": int(counters_snap["cancelled"]),
        "lane_slo": lane_slo,
        "lanes_detail": snap_final,
        "spans": {
            "started": acc["spans_started"],
            "closed": acc["spans_closed"],
            "open": acc["spans_open"],
            "closed_by_kind": acc["closed_by_kind"],
        },
        "flight_record": flight_record(
            tracer, eng.counters, reason="lane_drill_complete"),
    }


def dispatch_pipeline_drill_run(
    params,
    *,
    requests_steady: int = 240,
    requests_chaos: int = 48,
    calibrate_requests: int = 128,
    trials: int = 5,
    subjects: int = 6,
    max_rows: int = 2,
    max_bucket: int = 16,
    deadline_s: float = 6.0,
    inflight_depth: int = 2,
    device_rtt_s: float = 0.0015,
    max_delay_s: float = 0.002,
    pace_factor: float = 0.9,
    seed: int = 0,
    log: Callable[[str], None] = None,
) -> dict:
    """THE paired pipelined-vs-serial dispatch drill (PR 17 tentpole;
    bench config20, judged by scripts/bench_report.py:
    judge_dispatch_pipeline).

    Two supervised single-device engines over the SAME params, subjects,
    and deterministic request streams, differing only in the dispatch
    pipeline: ``serial`` is today's baseline (``inflight_depth=1``,
    fixed coalesce window — the depth-1 serial-equivalence contract),
    ``pipelined`` runs the PR-17 path (``inflight_depth`` deep
    completion stage + adaptive window). The timed legs run
    ``trials`` times each, interleaved per trial with ALTERNATING
    side order on the same stream, and rates come from each side's
    FASTEST trial (the module-preamble noise defenses: a load spike
    on this busy 1-core box costs both sides, and min-time reads the
    least-loaded window) while queue-wait percentiles pool every
    trial's spans:

    * **drain** — ``calibrate_requests`` submitted upfront (fully
      saturated backlog, no arrival pacing): the serial drain rate is
      the measured serial CAPACITY, and the pipelined drain alongside
      is the raw capacity-ratio record;
    * **steady** — ``requests_steady`` arriving open-loop at
      ``pace_factor`` x the serial capacity, the matched SATURATED
      load of the acceptance criteria: the serial engine cannot keep
      up by construction, so its backlog (and queue wait) grows at a
      rate the pipelined engine's host/device overlap must beat. Queue
      p50/p99 per engine come from each tracer's steady-leg spans
      (the same submit->launch stage `mano trace-report` prints); the
      full per-bucket stage table rides in the artifact as evidence.
      A mid-leg ``future.cancel()`` probe (same index both engines)
      exercises the cancellation path through the completion stage;
    * **chaos** — transient ``error@`` faults land on ALREADY-LAUNCHED
      batches (on the pipelined engine the supervised envelope runs on
      the completion worker, so the fault fires in-flight by
      construction), retries absorb them, and every span still closes
      exactly once.

    Every leg's results are compared BITWISE against a plain
    single-device reference engine and across the two engines
    (``cross_engine_bit_identical``): pipelining reorders WORK, never
    results. The device-side ``sat:{device_rtt_s}@*`` throttle on BOTH
    engines is the chaos module's documented slow-device model — it
    stands in for the tunnel's dispatch RTT (docs/roadmap.md PR-8: 70
    ms sync on the real chip), the genuinely off-host time whose
    overlap is the point of the PR. Faults are injected in-process; no
    chip is required and none is harmed.
    """
    import concurrent.futures as cf

    from mano_hand_tpu.runtime.chaos import ChaosPlan
    from mano_hand_tpu.runtime.supervise import DispatchPolicy
    from mano_hand_tpu.serving.engine import ServingEngine, ServingError

    log = _logger(log)
    n_joints, n_shape = params.n_joints, params.n_shape
    prm32 = params.astype(np.float32)
    rng = np.random.default_rng(seed)
    subj_betas = [rng.normal(size=(n_shape,)).astype(np.float32)
                  for _ in range(subjects)]

    def make_stream(n, pass_seed):
        r = np.random.default_rng(pass_seed)
        sizes = r.integers(1, max_rows + 1, size=n)
        return [(r.normal(scale=0.4,
                          size=(int(s), n_joints, 3)).astype(np.float32),
                 int(r.integers(0, subjects)))
                for s in sizes]

    streams = {
        "drain": make_stream(calibrate_requests, seed + 300),
        "steady": make_stream(requests_steady, seed + 301),
        "chaos": make_stream(requests_chaos, seed + 302),
    }

    # Bit-identity bar: the plain single-device engine, same subjects.
    ref_eng = ServingEngine(prm32, max_bucket=max_bucket,
                            max_delay_s=0.001)
    reference = {}
    with ref_eng:
        ref_keys = [ref_eng.specialize(b) for b in subj_betas]
        for name, stream in streams.items():
            reference[name] = [
                ref_eng.forward(p, subject=ref_keys[si])
                for p, si in stream]

    sat_spec = (f"sat:{device_rtt_s}@*" if device_rtt_s > 0 else "")

    def build(depth, adaptive):
        plan = ChaosPlan()
        policy = DispatchPolicy(
            deadline_s=deadline_s, retries=1, backoff_s=0.005,
            backoff_cap_s=0.01, jitter=0.0, chaos=plan,
            cpu_fallback=True)
        tracer = Tracer(capacity=65536)
        eng = ServingEngine(
            prm32, max_bucket=max_bucket, max_delay_s=max_delay_s,
            adaptive_coalesce=adaptive, inflight_depth=depth,
            policy=policy, tracer=tracer)
        return {"eng": eng, "plan": plan, "tracer": tracer}

    sides = {"serial": build(1, False),
             "pipelined": build(int(inflight_depth), True)}
    resolve_timeout = deadline_s * 3 + 60.0

    def queue_seconds(side, n0):
        tr = side["tracer"]
        spans = tr.spans()[n0:]
        out = []
        for sp in spans:
            st = tr._span_stages(sp)
            if st is not None:
                out.append(st["queue_s"])
        return out, spans

    def run_leg(side, stream, keys, *, rate=None, cancel_idx=-1):
        """Submit one leg (open-loop paced at ``rate``/s, or all
        upfront when None), resolve everything, classify outcomes."""
        eng = sides[side]["eng"]
        outcomes = {"ok": 0, "error": 0, "expired": 0, "stranded": 0,
                    "cancelled": 0}
        results = [None] * len(stream)
        futs = [None] * len(stream)
        t0 = time.perf_counter()
        for i, (p, si) in enumerate(stream):
            if rate is not None:
                wait = t0 + i / rate - time.perf_counter()
                if wait > 0:
                    time.sleep(wait)
            futs[i] = eng.submit(p, subject=keys[si])
            if i == cancel_idx:
                futs[i].cancel()
        for i, f in enumerate(futs):
            try:
                results[i] = f.result(timeout=resolve_timeout)
                k = "ok"
            except cf.CancelledError:
                k = "cancelled"
            except ServingError as e:
                k = "expired" if e.kind == "expired" else "error"
            except Exception:   # noqa: BLE001 — a timeout IS the bug
                k = "stranded"
            outcomes[k] += 1
        return outcomes, results, time.perf_counter() - t0

    def max_err(results, refs, skip=()):
        worst = 0.0
        for i, (got, want) in enumerate(zip(results, refs)):
            if got is None:
                if i in skip:
                    continue
                return None              # an unresolved result: no bar
            worst = max(worst, float(np.abs(got - want).max()))
        return worst

    pct = lambda xs, q: float(   # noqa: E731
        f"{np.percentile(np.asarray(xs), q) * 1e3:.4g}") if xs else None
    g4 = lambda x: float(f"{x:.4g}")     # noqa: E731

    legs = {"serial": {}, "pipelined": {}}
    cancel_idx = len(streams["steady"]) // 2
    try:
        for name, side in sides.items():
            eng = side["eng"]
            eng.__enter__()
            side["keys"] = [eng.specialize(b) for b in subj_betas]
            buckets = [b for b in eng.buckets if b <= max_bucket]
            eng.warmup(buckets)
            eng.warmup_posed(buckets)
            side["warm_compiles"] = eng.counters.compiles
            if sat_spec:
                side["plan"].schedule(sat_spec)

        def order(t):
            return (("serial", "pipelined") if t % 2 == 0
                    else ("pipelined", "serial"))

        def werr(a, b):
            return None if a is None or b is None else max(a, b)

        def merge(leg, name, oc, err, res, dt):
            st = legs[name].setdefault(leg, {
                "outcomes": {k: 0 for k in oc}, "dts": [], "err": 0.0})
            for k, v in oc.items():
                st["outcomes"][k] += v
            st["dts"].append(dt)
            st["err"] = werr(st["err"], err)
            st["results"] = res

        # -- leg 1: drain (saturated-backlog capacity) ----------------
        for t in range(trials):
            for name in order(t):
                oc, res, dt = run_leg(name, streams["drain"],
                                      sides[name]["keys"])
                merge("drain", name, oc,
                      max_err(res, reference["drain"]), res, dt)
        serial_rate = calibrate_requests / min(
            legs["serial"]["drain"]["dts"])
        pipelined_rate = calibrate_requests / min(
            legs["pipelined"]["drain"]["dts"])
        # Pace the steady leg at pace_factor (default 0.9) of the
        # PIPELINED capacity: when the pipeline genuinely buys
        # headroom, that rate sits decisively above the serial
        # engine's plateau — its backlog grows for the whole leg —
        # while the pipelined engine keeps 10% slack and serves at
        # the arrival rate. The queue-wait gap is the pipeline's
        # capacity headroom made visible. A broken pipeline
        # (capacity <= serial) pulls the pace under BOTH plateaus
        # and the queue ratio honestly collapses to ~1. (A
        # geometric-mean pace was tried first: it lands within
        # calibration noise of the serial plateau, so whether the
        # serial side overloads at all flips run to run.)
        paced_rate = pace_factor * pipelined_rate
        log(f"dispatch pipeline drill: capacities serial "
            f"{serial_rate:.1f} / pipelined {pipelined_rate:.1f} "
            f"req/s, pacing steady leg at {paced_rate:.1f} req/s")

        # -- leg 2: steady (matched saturated open-loop load) ---------
        for name, side in sides.items():
            side["steady_n0"] = len(side["tracer"].spans())
            side["compiles_before_steady"] = side["eng"].counters.compiles
        for t in range(trials):
            for name in order(t):
                oc, res, dt = run_leg(
                    name, streams["steady"], sides[name]["keys"],
                    rate=paced_rate, cancel_idx=cancel_idx)
                merge("steady", name, oc,
                      max_err(res, reference["steady"],
                              skip={cancel_idx}), res, dt)
        for name, side in sides.items():
            qs, spans = queue_seconds(side, side["steady_n0"])
            legs[name]["steady"].update({
                "queue_s": qs,
                "stage_table": side["tracer"].stage_breakdown(spans),
                "recompiles": (side["eng"].counters.compiles
                               - side["compiles_before_steady"]),
            })

        # -- leg 3: chaos (transient faults on in-flight batches) -----
        for name, side in sides.items():
            c0 = {k: getattr(side["eng"].counters, k)
                  for k in ("retries", "faults_injected", "failovers")}
            side["plan"].schedule(
                "error@1,error@4" + ("," + sat_spec if sat_spec else ""))
            oc, res, dt = run_leg(name, streams["chaos"], side["keys"])
            side["plan"].clear()
            merge("chaos", name, oc,
                  max_err(res, reference["chaos"]), res, dt)
            legs[name]["chaos"].update(
                {k: getattr(side["eng"].counters, k) - c0[k]
                 for k in c0})
    finally:
        for side in sides.values():
            side["plan"].release.set()
            side["eng"].__exit__(None, None, None)

    # Cross-engine bit identity, leg by leg (the cancel probe's index
    # is skipped on steady — both engines cancelled the same request).
    cross = True
    for leg_name in ("drain", "steady", "chaos"):
        for i, (a, b) in enumerate(zip(
                legs["serial"][leg_name]["results"],
                legs["pipelined"][leg_name]["results"])):
            if leg_name == "steady" and i == cancel_idx:
                continue
            if a is None or b is None or not np.array_equal(a, b):
                cross = False

    n_legs_total = (trials * (calibrate_requests + requests_steady)
                    + requests_chaos)
    out = {
        "requests_steady": requests_steady,
        "requests_chaos": requests_chaos,
        "calibrate_requests": calibrate_requests,
        "trials": trials,
        "subjects": subjects,
        "max_bucket": max_bucket,
        "pipeline_depth": int(inflight_depth),
        "device_rtt_s": device_rtt_s,
        "pace_factor": pace_factor,
        "serial_capacity_per_sec": g4(serial_rate),
        "pipelined_capacity_per_sec": g4(pipelined_rate),
        "paced_rate_per_sec": g4(paced_rate),
    }
    for name in ("serial", "pipelined"):
        side, lg = sides[name], legs[name]
        qs = lg["steady"]["queue_s"]
        acc = side["tracer"].accounting()
        resolved = n_legs_total - sum(
            lg[leg]["outcomes"]["stranded"]
            for leg in ("drain", "steady", "chaos"))
        outcomes = {k: sum(lg[leg]["outcomes"][k]
                           for leg in ("drain", "steady", "chaos"))
                    for k in lg["steady"]["outcomes"]}
        csnap = side["eng"].counters.snapshot()
        out[f"{name}_queue_p50_ms"] = pct(qs, 50)
        out[f"{name}_queue_p99_ms"] = pct(qs, 99)
        out.update({
            # End-to-end throughput at matched saturated load: the
            # drain leg (full backlog, no arrival pacing) is the
            # capacity comparison; the paced rate is the steady leg's
            # (arrival-bound for whichever side keeps up).
            f"{name}_throughput_per_sec": g4(
                calibrate_requests / min(lg["drain"]["dts"])),
            f"{name}_drain_leg_seconds": [
                g4(dt) for dt in lg["drain"]["dts"]],
            f"{name}_paced_throughput_per_sec": g4(
                requests_steady / min(lg["steady"]["dts"])),
            f"{name}_steady_recompiles": int(lg["steady"]["recompiles"]),
            f"{name}_warmup_compiles": int(side["warm_compiles"]),
            f"{name}_futures_resolved_fraction": float(
                f"{resolved / n_legs_total:.6g}"),
            f"{name}_outcomes": outcomes,
            f"{name}_drain_vs_reference_max_abs_err":
                lg["drain"]["err"],
            f"{name}_steady_vs_reference_max_abs_err":
                lg["steady"]["err"],
            f"{name}_chaos_vs_reference_max_abs_err":
                lg["chaos"]["err"],
            f"{name}_chaos_retries": int(lg["chaos"]["retries"]),
            f"{name}_chaos_faults_injected": int(
                lg["chaos"]["faults_injected"]),
            f"{name}_stage_table": lg["steady"]["stage_table"],
            f"{name}_spans": {
                "started": acc["spans_started"],
                "closed": acc["spans_closed"],
                "open": acc["spans_open"],
                "closed_by_kind": acc["closed_by_kind"],
            },
            f"{name}_pipeline_inflight_peak": int(
                csnap["pipeline_inflight_peak"]),
            f"{name}_pipeline_completions": int(
                csnap["pipeline_completions"]),
        })
    out["queue_p50_speedup"] = (
        g4(out["serial_queue_p50_ms"] / out["pipelined_queue_p50_ms"])
        if out["serial_queue_p50_ms"] and out["pipelined_queue_p50_ms"]
        else None)
    out["throughput_speedup"] = g4(
        out["pipelined_throughput_per_sec"]
        / out["serial_throughput_per_sec"])
    out["cross_engine_bit_identical"] = bool(cross)
    frac = (out["serial_futures_resolved_fraction"]
            + out["pipelined_futures_resolved_fraction"]) / 2
    out["futures_resolved_fraction"] = float(f"{frac:.6g}")
    # The depth-1 serial-equivalence contract, observed: a serial span
    # never carries the optional "staged" stage, a pipelined one does.
    def _has_pipeline_stage(table):
        return any("pipeline_p50_ms" in cell
                   for cell in table["by_bucket_tier"].values())
    out["serial_telemetry_serial_shape"] = (
        not _has_pipeline_stage(out["serial_stage_table"]))
    out["pipelined_overlap_observed"] = _has_pipeline_stage(
        out["pipelined_stage_table"])
    out["serial_flight_record"] = flight_record(
        sides["serial"]["tracer"], sides["serial"]["eng"].counters,
        reason="dispatch_pipeline_serial_leg")
    out["flight_record"] = flight_record(
        sides["pipelined"]["tracer"], sides["pipelined"]["eng"].counters,
        reason="dispatch_pipeline_drill_complete")
    return out


def precision_bench_run(
    params,
    *,
    subjects: int = 8,
    requests: int = 96,
    min_rows: int = 1,
    max_rows: int = 4,
    max_bucket: int = 32,
    max_delay_s: float = 0.002,
    seed: int = 0,
    trials: int = 5,
    envelope_m: float = 2e-3,
    posed_kernel: str = "xla",
    interpret: Optional[bool] = None,
    drill: bool = True,
    trace_dir=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE precision-tier benchmark protocol — bench.py config17 (PR 14).

    The same mixed-subject tier-0 pose-only stream drives TWO live
    engines: one under a ``PrecisionPolicy`` (tier 0 -> the
    bf16-compute/f32-accumulate gathered family), one the f32 control.
    The speed comparison is SLOPE-TIMED through the engines (t(all)
    minus t(half), the config14 protocol: the fixed submit/coalesce
    overhead both sides share cancels; naive timing on the tunnel
    lies), all four timing points interleaved per trial with
    min-over-trials per point.

    Returned criteria numbers (scripts/bench_report.py:judge_precision):

    * ``bf16_max_abs_err`` <= ``bf16_err_envelope`` — the bf16 tier's
      max vertex error vs the f32 posed reference, probed through the
      LIVE engine (sequential requests AND a concurrently-submitted
      burst that coalesces into mixed-subject gathered batches);
    * ``f32_control_max_abs_err`` == 0.0 — the control engine keeps
      the PR-4 f32 bit-identity contract (a nonzero here means the
      harness drifted, not the bf16 tier). Under
      ``posed_kernel="fused"`` the control serves the fused Pallas
      family, which is ~1e-5-close to the XLA posed reference by
      design — the judge applies the config14 1e-5 parity gate there
      instead of exact equality;
    * ``steady_recompiles_bf16`` == ``steady_recompiles_f32`` == 0 —
      both precision families serve every mixture from warm
      executables (warmup_posed warms BOTH on the policy engine);
    * ``sentinel_drill`` — a third, supervised engine composes the
      chaos ``wrong``-output fault into its (chaos-wrapped) bf16
      family: the sentinel's envelope judgment MUST flag
      ``gather_bf16`` drifted (``numerics_drift`` incident + flight
      capture), every future still resolves, and the probe recovers
      once the fault clears — the PR-9 guarantee extended to the tier
      whose whole safety case rests on it;
    * ``bf16_vs_f32_ratio`` — the headline speed number, judged >= 1.2x
      on a real TPU only (the config14 convention: off-chip the bf16
      MXU passes are emulated/invisible, so the CPU-lane ratio is
      recorded unjudged; the chip leg is queued via
      scripts/bench_tpu_wait.sh).

    ``drill=False`` skips the sentinel drill (the bench tiny-e2e
    budget pattern: the drill engine's compiles are cold in a fresh
    cache). ``trace_dir`` exports the policy engine's timeline into
    ``<trace_dir>/precision/``.
    """
    import jax

    from mano_hand_tpu.models import core
    from mano_hand_tpu.serving import buckets as bucket_mod
    from mano_hand_tpu.serving.engine import ServingEngine
    from mano_hand_tpu.serving.precision import PrecisionPolicy

    if subjects < 1:
        raise ValueError(f"subjects must be >= 1, got {subjects}")
    if requests < 2:
        raise ValueError(f"requests must be >= 2, got {requests}")
    log = _logger(log)
    max_rows = min(max_rows, max_bucket)
    min_rows = max(1, min(min_rows, max_rows))
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    betas = [rng.normal(size=(n_shape,)).astype(np.float32)
             for _ in range(subjects)]
    sizes = rng.integers(min_rows, max_rows + 1, size=requests)
    subj_of = rng.integers(0, subjects, size=requests)
    stream = [
        (rng.normal(scale=0.4,
                    size=(int(n), n_joints, 3)).astype(np.float32), int(s))
        for n, s in zip(sizes, subj_of)
    ]
    m1 = max(1, requests // 2)
    m2 = requests
    rows_m1 = int(sizes[:m1].sum())
    rows_m2 = int(sizes.sum())
    d_rows = rows_m2 - rows_m1

    policy = PrecisionPolicy(bf16_tiers=frozenset({0}),
                             max_vertex_err_m=envelope_m)
    tracer_b, tracer_c = Tracer(), Tracer()
    eng_b = ServingEngine(params, max_bucket=max_bucket,
                          max_delay_s=max_delay_s, tracer=tracer_b,
                          posed_kernel=posed_kernel,
                          posed_kernel_interpret=interpret,
                          precision_policy=policy)
    eng_c = ServingEngine(params, max_bucket=max_bucket,
                          max_delay_s=max_delay_s, tracer=tracer_c,
                          posed_kernel=posed_kernel,
                          posed_kernel_interpret=interpret)

    prm_dev = params.astype(np.float32).device_put()
    shaped = [core.jit_specialize(prm_dev, b) for b in betas]
    # The f32 truth: the per-subject posed program — the PR-4 gathered
    # f32 family is bit-identical to it per row, so one reference
    # serves the control's bit-identity AND the bf16 tier's envelope.
    ref_exe = jax.jit(
        lambda sh, p: core.forward_posed_batched(sh, p).verts)

    def ref_one(pose, si):
        b = bucket_mod.bucket_for(pose.shape[0], eng_b.buckets)
        out = ref_exe(shaped[si],
                      np.asarray(bucket_mod.pad_rows(pose, b)))
        return np.asarray(out)[:pose.shape[0]]

    def run_stream(eng, keys, m):
        t0 = time.perf_counter()
        futs = [eng.submit(p, subject=keys[si], priority=0)
                for p, si in stream[:m]]
        for f in futs:
            f.result()
        return time.perf_counter() - t0

    results = {}
    with eng_b, eng_c:
        keys_b = [eng_b.specialize(b) for b in betas]
        keys_c = [eng_c.specialize(b) for b in betas]
        log(f"precision: {subjects} subjects baked on both engines, "
            f"warming buckets {eng_b.buckets} (both precision "
            f"families on the policy side)")
        eng_b.warmup_posed()
        eng_c.warmup_posed()
        for b in eng_b.buckets:   # warm the reference's buckets
            jax.block_until_ready(ref_exe(
                shaped[0], np.zeros((b, n_joints, 3), np.float32)))

        # Envelope/parity through the LIVE engines (the CLAUDE.md
        # in-context rule): sequential tier-0 requests AND a
        # concurrently-submitted burst that coalesces into
        # mixed-subject gathered batches on each side.
        err_b = err_c = 0.0
        probe = stream[:min(8, len(stream))]
        for pose, si in probe:
            err_b = max(err_b, float(np.abs(
                eng_b.forward(pose, subject=keys_b[si], priority=0)
                - ref_one(pose, si)).max()))
            err_c = max(err_c, float(np.abs(
                eng_c.forward(pose, subject=keys_c[si], priority=0)
                - ref_one(pose, si)).max()))
        futs_b = [eng_b.submit(p, subject=keys_b[si], priority=0)
                  for p, si in probe]
        futs_c = [eng_c.submit(p, subject=keys_c[si], priority=0)
                  for p, si in probe]
        for (pose, si), fb, fc in zip(probe, futs_b, futs_c):
            want = ref_one(pose, si)
            err_b = max(err_b, float(np.abs(fb.result() - want).max()))
            err_c = max(err_c, float(np.abs(fc.result() - want).max()))
        # A tier-1 request on the POLICY engine must serve f32 (the
        # tier-without-policy-entry default) — probed live, so a
        # policy-routing regression fails the control criterion here
        # rather than silently widening the bf16 envelope.
        t1_pose, t1_si = stream[0]
        err_c = max(err_c, float(np.abs(
            eng_b.forward(t1_pose, subject=keys_b[t1_si], priority=1)
            - ref_one(t1_pose, t1_si)).max()))

        run_stream(eng_b, keys_b, m2)
        run_stream(eng_c, keys_c, m2)   # settle both sides untimed
        compiles_b = eng_b.counters.compiles
        compiles_c = eng_c.counters.compiles

        thunks = {
            "b1": lambda: run_stream(eng_b, keys_b, m1),
            "b2": lambda: run_stream(eng_b, keys_b, m2),
            "c1": lambda: run_stream(eng_c, keys_c, m1),
            "c2": lambda: run_stream(eng_c, keys_c, m2),
        }
        best = {k: float("inf") for k in thunks}
        for t in range(max(1, trials)):
            order = sorted(thunks) if t % 2 == 0 \
                else sorted(thunks, reverse=True)
            for k in order:
                best[k] = min(best[k], thunks[k]())
        steady_b = eng_b.counters.compiles - compiles_b
        steady_c = eng_c.counters.compiles - compiles_c
        snap_b = eng_b.counters.snapshot()
        targets = eng_b.numerics_probe_targets()
        results.update({
            "capacity": targets["table"].capacity,
            "gather_fused_active": bool(targets["gather_fused"]),
            "precision_tiers": eng_b.load()["precision"]["tiers"],
        })

    d_b = best["b2"] - best["b1"]
    d_c = best["c2"] - best["c1"]
    bf16_rate = d_rows / d_b if d_b > 0 else float("nan")
    f32_rate = d_rows / d_c if d_c > 0 else float("nan")
    ratio = d_c / d_b if d_b > 0 and d_c > 0 else float("nan")
    platform = jax.default_backend()
    log(f"precision: bf16 {bf16_rate:,.0f} vs f32 {f32_rate:,.0f} "
        f"evals/s (slope ratio {ratio:.2f}x, platform {platform}), "
        f"bf16 err {err_b:.2e} vs envelope {envelope_m:.1e}, f32 "
        f"control err {err_c:.2e}, steady recompiles "
        f"{steady_b}/{steady_c}")

    # ---- the bf16 sentinel drill: injected silent corruption on the
    # bf16 TIER must be seen by the envelope judgment (the whole
    # safety case of serving reduced precision in production).
    drill_out = None
    if drill:
        from mano_hand_tpu.obs.recorder import FlightRecorder
        from mano_hand_tpu.obs.sentinel import NumericsSentinel
        from mano_hand_tpu.runtime.chaos import ChaosPlan
        from mano_hand_tpu.runtime.supervise import DispatchPolicy

        plan = ChaosPlan()
        pol = DispatchPolicy(deadline_s=20.0, retries=0, chaos=plan)
        tr3 = Tracer()
        eng3 = ServingEngine(params, min_bucket=8, max_bucket=8,
                             max_delay_s=max_delay_s, policy=pol,
                             tracer=tr3, precision_policy=policy,
                             # The drill must corrupt the SAME family
                             # the timed engines serve — under
                             # posed_kernel="fused" an XLA-only drill
                             # engine would certify detection on a
                             # family not under test.
                             posed_kernel=posed_kernel,
                             posed_kernel_interpret=interpret)
        rec3 = FlightRecorder(tr3, eng3.counters)
        s3 = NumericsSentinel(eng3, tracer=tr3, interval_s=3600.0)
        dkeys = [eng3.specialize(b) for b in betas[:min(3, subjects)]]
        wave = [
            (rng.normal(scale=0.4,
                        size=(int(n), n_joints, 3)).astype(np.float32),
             int(s))
            for n, s in zip(rng.integers(1, 5, size=12),
                            rng.integers(0, len(dkeys), size=12))
        ]

        def submit_wave():
            import concurrent.futures as cf

            futs = [eng3.submit(p, subject=dkeys[si], priority=0)
                    for p, si in wave]
            resolved = 0
            for f in futs:
                try:
                    f.result(timeout=60.0)
                    resolved += 1
                except cf.TimeoutError:
                    pass
                except Exception:  # noqa: BLE001 — structured resolves
                    resolved += 1
            return resolved, len(futs)

        with eng3:
            eng3.warmup_posed()
            golden = s3.arm()
            ok0, n0 = submit_wave()     # clean bf16 tier-0 traffic
            clean = s3.probe()
            drill_compiles_warm = eng3.counters.compiles
            # Silent corruption: every chaos-wrapped primary — the
            # bf16 gathered family included — returns verts + 1.0
            # from here, resolving every future "ok" with floats a
            # whole envelope off. Only the sentinel can see it.
            plan.schedule("wrong:1.0@0-")
            ok1, n1 = submit_wave()
            detected = s3.probe()
            plan.clear()                # the fault clears
            recovered = s3.probe()
            drill_recompiles = (eng3.counters.compiles
                                - drill_compiles_warm)
        drill_acc = tr3.accounting()
        fam = detected["families"]
        bf16_rec = fam.get("gather_bf16") or {}
        drill_out = {
            "submitted": n0 + n1,
            "futures_resolved_fraction": (ok0 + ok1) / (n0 + n1),
            "clean_probe_drift": bool(clean["drift"]),
            "detected": bool(detected["drift"]),
            "bf16_family_detected": bool(bf16_rec.get("drift")),
            "drifted_families": detected["drifted_families"],
            "drift_max_abs_err": bf16_rec.get("max_abs_err"),
            "envelope": bf16_rec.get("envelope"),
            "golden_bf16_status": golden.get("golden_bf16_status"),
            "recovered": not recovered["drift"],
            "incidents": drill_acc["incidents"],
            "flight_capture_reasons": [c.get("reason")
                                       for c in rec3.captures],
            "faults_injected": int(eng3.counters.faults_injected),
            "steady_recompiles": int(drill_recompiles),
            "span_accounting": drill_acc,
        }
        log(f"precision sentinel drill: bf16 detected="
            f"{drill_out['bf16_family_detected']} (err "
            f"{drill_out['drift_max_abs_err']} vs envelope "
            f"{drill_out['envelope']}), recovered="
            f"{drill_out['recovered']}, "
            f"{drill_out['futures_resolved_fraction']:.0%} of "
            f"{drill_out['submitted']} futures resolved, "
            f"{drill_out['incidents']} incident(s), golden_bf16 "
            f"{drill_out['golden_bf16_status']}")

    results.update({
        "subjects": int(subjects),
        "requests": int(requests),
        "rows": [int(sizes.min()), int(sizes.max())],
        "buckets": list(eng_b.buckets),
        "platform": platform,
        "posed_kernel": posed_kernel,
        "slope_points": {"m1": m1, "m2": m2,
                         "rows_m1": rows_m1, "rows_m2": rows_m2},
        "bf16_evals_per_sec": float(f"{bf16_rate:.5g}"),
        "f32_evals_per_sec": float(f"{f32_rate:.5g}"),
        "bf16_vs_f32_ratio": float(f"{ratio:.4g}"),
        "bf16_max_abs_err": err_b,
        "bf16_err_envelope": float(envelope_m),
        "f32_control_max_abs_err": err_c,
        "steady_recompiles_bf16": int(steady_b),
        "steady_recompiles_f32": int(steady_c),
        "mixed_subject_batches": snap_b["mixed_subject_batches"],
        "coalesce_width_mean": snap_b["coalesce_width_mean"],
        "dispatches": snap_b["dispatches"],
        "flight_record": flight_record(
            tracer_b, eng_b.counters, reason="precision_complete"),
    })
    if drill_out is not None:
        results["sentinel_drill"] = drill_out
    else:
        # Self-documenting skip: judge_precision treats an ABSENT
        # drill block as a failure unless the artifact says the skip
        # was deliberate (the tiny-e2e budget pattern) — a drilled
        # run that silently dropped the block must not pass.
        results["sentinel_drill_skipped"] = True
    if trace_dir is not None:
        import os

        from mano_hand_tpu.obs import write_trace_dir

        results["trace_export"] = write_trace_dir(
            tracer_b, os.path.join(str(trace_dir), "precision"),
            counters=eng_b.counters, reason="precision_complete")
    return results


def edge_drill_run(
    params,
    *,
    # 5x offered (vs the overload drill's 4x): the wire's blocking
    # clients compress bursts when the pool saturates, so the ACHIEVED
    # multiple lands ~25-35% under the target — the headroom keeps the
    # >= 3x judging floor honest through scheduler noise on this box.
    saturation: float = 5.0,
    bursts: int = 24,
    burst_interval_s: float = 0.02,
    tier0_fraction: float = 0.125,
    # Sized against the WORKER pool, not just the service rate: the
    # wire client blocks one worker per admitted request, so overload
    # only materializes when workers > max_queued (a pool smaller than
    # the queue can never push outstanding to the shed threshold).
    max_queued: int = 16,
    tier1_quota: int = 6,
    deadline_s: float = 0.5,
    sat_latency_s: float = 0.02,
    max_bucket: int = 8,
    batch_deadline_s: float = 0.5,
    shed_probe_requests: int = 64,
    workers: int = 24,
    streams: int = 3,
    frames_per_stream: int = 3,
    drain_timeout_s: float = 10.0,
    seed: int = 0,
    tracer=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE loopback edge drill (config18, PR 15) — the PR-5 overload
    acceptance numbers reproduced THROUGH the socket, plus the wire
    protocol's own failure story. Shared by ``bench.py`` config18 and
    tests/test_edge.py (the recovery-drill pattern: one protocol, the
    artifacts cannot diverge).

    Five legs over live ``edge.EdgeServer`` processes-in-miniature
    (same-process loopback — the serialization boundary is real, the
    host is this box):

    1. **Shed probe**: the engine-side decision stays O(µs) (the
       ``max_queued=0`` probe engine — zero dispatches, dispatcher
       never started, params never transferred), and the WIRE maps
       every one of those sheds to 429 + per-tier Retry-After.
    2. **Saturation storm**: a worker pool with persistent
       connections offers ``saturation`` x the socket-calibrated
       service rate in paced bursts, tiers and TTLs riding the QoS
       headers. Criteria: every request gets an HTTP terminal
       (200/429/504 — never a hang, never a 5xx) within the budget,
       tier-0 goodput >= 95% at >= 3x achieved saturation, and the
       storm compiles nothing.
    3. **Stream parity**: PR-12 sessions through the upgrade protocol,
       frames BIT-identical (verts AND warm-start pose) to in-process
       ``submit_frame`` on the same engine.
    4. **Disconnect**: an abrupt client vanish mid-request and
       mid-frame lands the PR-13 cancellation path — terminal kind
       ``cancelled``, session closed — on a dedicated slow engine so
       the in-flight window is deterministic.
    5. **Drain**: the SIGTERM path with requests in flight — in-flight
       requests resolve, new connections are refused, the engine's
       stop() sweep runs, all inside ``drain_timeout_s`` with the
       flight recorder QUIET (drain is a lifecycle, not an incident).

    One tracer spans every engine in the drill, so the final
    closed-exactly-once accounting covers every request, frame, and
    session that crossed the wire. Everything runs on whatever backend
    is up; saturation and faults are injected in-process — no chip
    required, none harmed.
    """
    import queue as queue_mod
    import socket as socket_mod
    import threading

    import jax.numpy as jnp

    from mano_hand_tpu.edge import EdgeClient, EdgeError, EdgeServer
    from mano_hand_tpu.edge import protocol as eproto
    from mano_hand_tpu.models import anim, core
    from mano_hand_tpu.obs.recorder import FlightRecorder
    from mano_hand_tpu.runtime.chaos import ChaosPlan
    from mano_hand_tpu.runtime.supervise import DispatchPolicy
    from mano_hand_tpu.serving.engine import ServingEngine, ServingError

    if saturation <= 0:
        raise ValueError(f"saturation must be > 0, got {saturation}")
    if bursts < 1:
        raise ValueError(f"bursts must be >= 1, got {bursts}")
    if workers < 2:
        raise ValueError(f"workers must be >= 2, got {workers}")
    if streams < 1:
        raise ValueError(f"streams must be >= 1, got {streams}")
    if frames_per_stream < 2:
        raise ValueError(
            f"frames_per_stream must be >= 2 (settle + parity), got "
            f"{frames_per_stream}")
    log = _logger(log)
    if tracer is None:
        tracer = Tracer()
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    prm32 = params.astype(np.float32)
    host = "127.0.0.1"
    pose1 = rng.normal(scale=0.4, size=(1, n_joints, 3)).astype(np.float32)
    # The black box rides the WHOLE drill (the probe leg's sustained
    # shed burst is itself an incident class worth capturing); the
    # drain criterion below judges its silence across the drain window
    # only.
    recorder = FlightRecorder(tracer)

    # ---- Leg 1: the shed probe, engine-side then through the wire -----
    probe = ServingEngine(prm32, max_bucket=max_bucket, max_queued=0,
                          tracer=tracer)
    shed_us: List[float] = []
    for _ in range(max(1, shed_probe_requests)):
        t0 = time.perf_counter()
        try:
            probe.submit(pose1, deadline_s=deadline_s)
            raise RuntimeError("shed probe submit was admitted at "
                               "max_queued=0")
        except ServingError as e:
            if e.kind != "shed":
                raise
        shed_us.append((time.perf_counter() - t0) * 1e6)
    srv_probe = EdgeServer(probe, host=host, port=0).start()
    wire_429 = 0
    wire_retry_after: List[int] = []
    wire_shed_ms: List[float] = []
    cli_probe = EdgeClient(host, srv_probe.port, timeout_s=30.0)
    for i in range(max(1, shed_probe_requests)):
        t0 = time.perf_counter()
        try:
            cli_probe.forward(pose1, priority=i % 2,
                              deadline_s=deadline_s)
            raise RuntimeError("wire shed probe got a 200 at "
                               "max_queued=0")
        except EdgeError as e:
            if e.status != 429 or e.kind != "shed":
                raise
            wire_429 += 1
            if e.retry_after_s is not None:
                wire_retry_after.append(e.retry_after_s)
        wire_shed_ms.append((time.perf_counter() - t0) * 1e3)
    cli_probe.close()
    shed_probe = {
        "sheds": len(shed_us),
        "dispatches": probe.counters.dispatches,
        "engine_started": probe._thread is not None,
        "params_device_put": probe._params_dev is not None,
        "decision_p50_us": float(f"{np.percentile(shed_us, 50):.4g}"),
        "decision_p99_us": float(f"{np.percentile(shed_us, 99):.4g}"),
        "wire_429": wire_429,
        "wire_retry_after_present": len(wire_retry_after) == wire_429,
        "wire_shed_p50_ms": float(
            f"{np.percentile(wire_shed_ms, 50):.4g}"),
        "wire_shed_p99_ms": float(
            f"{np.percentile(wire_shed_ms, 99):.4g}"),
    }
    srv_probe.drain(timeout_s=5.0)
    log(f"edge: shed probe {shed_probe['sheds']} sheds "
        f"({shed_probe['dispatches']} dispatches, decision p50 "
        f"{shed_probe['decision_p50_us']:.1f} µs), wire {wire_429} x "
        f"429 (p50 {shed_probe['wire_shed_p50_ms']:.2f} ms)")

    # ---- The saturated engine + its edge -----------------------------
    plan = ChaosPlan(f"sat:{sat_latency_s}@0-")
    policy = DispatchPolicy(
        deadline_s=batch_deadline_s, retries=0, backoff_s=0.0,
        backoff_cap_s=0.0, jitter=0.0, breaker=None, chaos=plan,
        # The overload-drill rule: overload is not a fault; the
        # fallback tier would quietly raise capacity mid-drill.
        cpu_fallback=False,
    )
    eng = ServingEngine(
        prm32, max_bucket=max_bucket, max_delay_s=0.001, policy=policy,
        max_queued=max_queued, tier_quotas={1: tier1_quota},
        tracer=tracer)
    recorder.counters = eng.counters    # captures now carry the
    eng.start()                         # saturated engine's ledger
    eng.warmup()
    srv = EdgeServer(eng, host=host, port=0,
                     drain_timeout_s=drain_timeout_s).start()

    # Worker pool: one persistent connection each (the load-generator
    # fleet shape); phases tag their records.
    tasks: queue_mod.Queue = queue_mod.Queue()
    records: dict = {"calib": [], "storm": []}
    rec_lock = threading.Lock()
    _STOP = object()

    def worker():
        cli = EdgeClient(host, srv.port, timeout_s=30.0)
        while True:
            item = tasks.get()
            if item is _STOP:
                cli.close()
                return
            phase, tier, ttl = item
            t0 = time.monotonic()
            try:
                cli.forward(pose1, priority=tier, deadline_s=ttl)
                out = "ok"
            except EdgeError as e:
                out = {429: "shed", 504: "expired"}.get(
                    e.status, "error")
            except Exception:  # noqa: BLE001 — a timeout IS the bug
                out = "unresolved"
            t1 = time.monotonic()
            with rec_lock:
                records[phase].append((tier, t0, t1, out))

    pool = [threading.Thread(target=worker, daemon=True)
            for _ in range(workers)]
    for t in pool:
        t.start()

    def run_phase(phase: str, n: int, timeout_s: float) -> bool:
        dl = time.monotonic() + timeout_s
        while time.monotonic() < dl:
            with rec_lock:
                if len(records[phase]) >= n:
                    return True
            time.sleep(0.002)
        return False

    # Calibrate THIS box's wire service rate (the overload-drill
    # definition, measured through the socket): waves under the quota,
    # drained, three times.
    wave = min(max(max_bucket, min(max_queued // 2, 3 * max_bucket)),
               max_queued, workers)
    t0 = time.perf_counter()
    served = 0
    for _ in range(3):
        base = served
        for _ in range(wave):
            tasks.put(("calib", 0, None))
        if not run_phase("calib", base + wave, 60.0):
            raise RuntimeError("edge calibration wave did not drain")
        served += wave
    service_rate = served / (time.perf_counter() - t0)
    compiles_warm = eng.counters.compiles
    offered_rate = saturation * service_rate
    burst_n = max(1, int(round(offered_rate * burst_interval_s)))
    # Budget: the engine's own resolution window + one wire grace (the
    # HTTP round trip and worker scheduling on a 1-core box).
    budget_s = deadline_s + batch_deadline_s + 0.5
    log(f"edge: wire service rate {service_rate:,.0f} req/s (sat "
        f"throttle {sat_latency_s}s), offering {offered_rate:,.0f} "
        f"req/s = {burst_n}/burst x {bursts} bursts over {workers} "
        f"workers")

    # ---- Leg 2: the saturation storm ---------------------------------
    submitted = 0
    next_t = time.monotonic()
    healthz_mid = None
    load_mid = None
    for b in range(bursts):
        for _ in range(burst_n):
            tier = 0 if rng.random() < tier0_fraction else 1
            tasks.put(("storm", tier, deadline_s))
            submitted += 1
        if b == bursts // 2:
            load_mid = eng.load()
            try:
                healthz_mid = EdgeClient(
                    host, srv.port, timeout_s=5.0).healthz()
            except Exception:  # noqa: BLE001 — mid-storm info only
                healthz_mid = None
        next_t += burst_interval_s
        lag = next_t - time.monotonic()
        if lag > 0:
            time.sleep(lag)
    drained = run_phase("storm", submitted, budget_s * 2 + 30.0)
    steady_recompiles = eng.counters.compiles - compiles_warm
    snap = eng.counters.snapshot()

    outcomes = {"ok": 0, "shed": 0, "expired": 0, "error": 0,
                "unresolved": 0}
    by_tier = {0: dict(outcomes), 1: dict(outcomes)}
    in_budget = 0
    sends: List[float] = []
    wire_lat: List[float] = []
    with rec_lock:
        storm = list(records["storm"])
    for tier, t0, t1, out in storm:
        lat = t1 - t0
        sends.append(t0)
        wire_lat.append(lat)
        if out != "unresolved" and lat <= budget_s:
            in_budget += 1
        outcomes[out] += 1
        by_tier[tier][out] += 1
    missing = submitted - len(storm)
    outcomes["unresolved"] += missing
    stream_s = (max(sends) - min(sends)) if len(sends) > 1 else 1e-9
    achieved = ((len(storm) / max(stream_s, 1e-9)) / service_rate
                if service_rate else 0.0)
    t0_total = sum(by_tier[0].values())
    tier0_goodput = (by_tier[0]["ok"] / t0_total if t0_total else None)
    resolved_frac = in_budget / submitted if submitted else 0.0
    log(f"edge: {submitted} submitted at {achieved:.2f}x achieved "
        f"saturation -> {outcomes['ok']} ok / {outcomes['shed']} shed "
        f"/ {outcomes['expired']} expired / {outcomes['unresolved']} "
        f"unresolved (drained={drained}); tier-0 goodput "
        f"{tier0_goodput if tier0_goodput is None else f'{tier0_goodput:.1%}'}, "
        f"{steady_recompiles} steady recompiles")

    # ---- Scrape through the socket -----------------------------------
    scrape_cli = EdgeClient(host, srv.port, timeout_s=10.0)
    healthz = scrape_cli.healthz()
    metrics_text = scrape_cli.metrics_text()
    scrape_cli.close()
    scrape = {
        "healthz_ok": bool(healthz.get("ok")),
        "healthz_status": healthz.get("status"),
        "metrics_lines": len(metrics_text.splitlines()),
        "metrics_has_serving": "mano_serving_dispatches" in metrics_text,
        "metrics_has_slo": "mano_slo_burn_rate" in metrics_text,
    }

    # ---- Leg 3: stream parity (wire vs in-process, bit-identical) ----
    betas = [rng.normal(size=(n_shape,)).astype(np.float32)
             for _ in range(streams)]
    keys = np.zeros((streams, 3, n_joints, 3), np.float32)
    keys[:, 1] = rng.normal(scale=0.2, size=(streams, n_joints, 3))
    keys[:, 2] = keys[:, 1] + rng.normal(
        scale=0.1, size=(streams, n_joints, 3))
    tracks = np.stack([
        anim.resample_poses(keys[s], frames_per_stream)
        for s in range(streams)]).astype(np.float32)
    flat_pose = tracks.reshape(streams * frames_per_stream, n_joints, 3)
    flat_beta = np.stack([betas[s]
                          for s in range(streams)
                          for _ in range(frames_per_stream)])
    gt = core.jit_forward_batched(prm32.device_put(),
                                  jnp.asarray(flat_pose),
                                  jnp.asarray(flat_beta))
    targets = np.asarray(gt.posed_joints).reshape(
        streams, frames_per_stream, n_joints, 3)

    stream_cli = EdgeClient(host, srv.port, timeout_s=120.0)
    frames_ok = 0
    verts_err = 0.0
    pose_err = 0.0
    for s in range(streams):
        wire_frames = []
        with stream_cli.open_stream(betas=betas[s]) as ws:
            for f in range(frames_per_stream):
                wire_frames.append(ws.frame(targets[s, f]))
        sess = eng.open_stream(betas[s])
        for f in range(frames_per_stream):
            ref = sess.step(targets[s, f])
            wf = wire_frames[f]
            verts_err = max(verts_err, float(
                np.max(np.abs(wf.verts - ref.verts))))
            pose_err = max(pose_err, float(
                np.max(np.abs(wf.pose - ref.pose))))
            if wf.frame == ref.frame:
                frames_ok += 1
        sess.close()
    stream_cli.close()
    stream_leg = {
        "streams": streams,
        "frames_per_stream": frames_per_stream,
        "frames_ok": frames_ok,
        "frames_expected": streams * frames_per_stream,
        "wire_vs_inprocess_max_abs_err": verts_err,
        "wire_vs_inprocess_pose_max_abs_err": pose_err,
    }
    log(f"edge: stream parity {frames_ok}/"
        f"{streams * frames_per_stream} frames, verts err {verts_err} "
        f"pose err {pose_err} (bit-identity bar: 0.0)")

    # ---- Leg 4: disconnect -> cancel (deterministic slow engine) -----
    slow_plan = ChaosPlan("sat:0.35@0-")
    slow_policy = DispatchPolicy(
        deadline_s=2.0, retries=0, backoff_s=0.0, backoff_cap_s=0.0,
        jitter=0.0, breaker=None, chaos=slow_plan, cpu_fallback=False)
    eng_d = ServingEngine(prm32, max_bucket=2, max_delay_s=0.001,
                          policy=slow_policy, tracer=tracer)
    eng_d.start()
    eng_d.warmup([1, 2])
    srv_d = EdgeServer(eng_d, host=host, port=0).start()
    cancelled_base = eng_d.counters.cancelled
    # One-shot: a raw POST whose socket dies while the request is in
    # the 0.35s dispatch window.
    body = eproto.dumps({"pose": eproto.encode_array(pose1)})
    conn = socket_mod.create_connection((host, srv_d.port),
                                        timeout=10.0)
    conn.sendall((f"POST /v1/forward HTTP/1.1\r\nHost: {host}\r\n"
                  f"Content-Type: application/json\r\n"
                  f"Content-Length: {len(body)}\r\n\r\n"
                  ).encode("latin-1") + body)
    time.sleep(0.08)
    conn.close()
    dl = time.monotonic() + 5.0
    while (eng_d.counters.cancelled <= cancelled_base
           and time.monotonic() < dl):
        time.sleep(0.01)
    oneshot_cancelled = eng_d.counters.cancelled - cancelled_base
    # Stream: open over the wire, settle one frame, vanish mid-frame.
    d_cli = EdgeClient(host, srv_d.port, timeout_s=60.0)
    ds = d_cli.open_stream(betas=betas[0])
    ds.frame(targets[0, 0])            # settle (tracker state warm)
    aborter = threading.Timer(0.1, ds.abort)
    aborter.start()
    stream_frame_cancelled = False
    try:
        ds.frame(targets[0, 1])
    except (EdgeError, OSError, ValueError):
        stream_frame_cancelled = True
    aborter.join()
    dl = time.monotonic() + 5.0
    while (eng_d.counters.cancelled <= cancelled_base + oneshot_cancelled
           and time.monotonic() < dl):
        time.sleep(0.01)
    d_load = eng_d.load()
    disconnect = {
        "oneshot_cancelled": int(oneshot_cancelled),
        "stream_frame_aborted": stream_frame_cancelled,
        "cancelled_total": int(eng_d.counters.cancelled
                               - cancelled_base),
        "stream_closed_by_kind": d_load["streams"]["closed_by_kind"],
        "stream_frames_by_kind": d_load["streams"]["frames_by_kind"],
    }
    d_cli.close()
    srv_d.drain(timeout_s=5.0)
    log(f"edge: disconnect leg cancelled "
        f"{disconnect['cancelled_total']} (one-shot "
        f"{disconnect['oneshot_cancelled']}, stream frames by kind "
        f"{disconnect['stream_frames_by_kind']})")

    # ---- Leg 5: drain with requests in flight ------------------------
    inflight_results: List[str] = []
    inflight_lock = threading.Lock()
    inflight_n = min(6, workers)
    # Barrier: every client establishes its persistent connection
    # (healthz) BEFORE any forward is sent, so the drain below races
    # the REQUESTS (the thing under test), never the TCP connects.
    inflight_ready = threading.Barrier(inflight_n + 1)

    def inflight_request():
        cli = EdgeClient(host, srv.port, timeout_s=30.0)
        try:
            cli.healthz()
            inflight_ready.wait(timeout=10.0)
            cli.forward(pose1, priority=0, deadline_s=5.0)
            out = "ok"
        except EdgeError as e:
            out = f"http_{e.status}"
        except Exception as e:  # noqa: BLE001
            out = f"exc_{type(e).__name__}"
        finally:
            cli.close()
        with inflight_lock:
            inflight_results.append(out)

    inflight_threads = [threading.Thread(target=inflight_request,
                                         daemon=True)
                       for _ in range(inflight_n)]
    for t in inflight_threads:
        t.start()
    inflight_ready.wait(timeout=10.0)
    # Drain only once the server holds every request (or the window
    # closed because fast ones already resolved — both are fine; the
    # criterion is that none is refused or stranded).
    spin_dl = time.monotonic() + 1.0
    while (srv._active_requests < inflight_n
           and time.monotonic() < spin_dl):
        time.sleep(0.0005)
    captures_before_drain = len(recorder.captures)
    t_drain0 = time.monotonic()
    drain_report = srv.drain(timeout_s=drain_timeout_s)
    drain_wall = time.monotonic() - t_drain0
    for t in inflight_threads:
        t.join(timeout=10.0)
    refused = False
    try:
        probe_conn = socket_mod.create_connection((host, srv.port),
                                                  timeout=2.0)
        probe_conn.close()
    except OSError:
        refused = True
    recorder_quiet = len(recorder.captures) == captures_before_drain
    with inflight_lock:
        inflight_ok = (len(inflight_results) == inflight_n
                       and all(r == "ok" for r in inflight_results))
    drain_leg = {
        "inflight_requests": inflight_n,
        "inflight_all_ok": inflight_ok,
        "inflight_results": sorted(inflight_results),
        "new_connection_refused": refused,
        "drain_wall_s": float(f"{drain_wall:.4g}"),
        "within_timeout": bool(drain_report.get("within_timeout"))
                          and drain_wall <= drain_timeout_s,
        "engine_stopped": eng._thread is None,
        "recorder_quiet_during_drain": recorder_quiet,
        "report": {k: v for k, v in drain_report.items()
                   if k != "inflight_resolved"},
    }
    log(f"edge: drain {drain_wall:.2f}s (timeout {drain_timeout_s}s), "
        f"in-flight {inflight_results}, new conn refused={refused}, "
        f"recorder quiet={recorder_quiet}")

    # Workers down (their engine is stopped; sheds/errors past this
    # point would be drain artifacts, not drill data).
    for _ in pool:
        tasks.put(_STOP)
    for t in pool:
        t.join(timeout=5.0)

    acc = tracer.accounting()
    return {
        "edge_drill_schema": 1,
        "saturation_target": float(saturation),
        "saturation_achieved": float(f"{achieved:.4g}"),
        "service_rate_req_per_s": float(f"{service_rate:.5g}"),
        "offered_rate_req_per_s": float(f"{offered_rate:.5g}"),
        "bursts": int(bursts),
        "burst_requests": int(burst_n),
        "burst_interval_s": burst_interval_s,
        "deadline_s": deadline_s,
        "budget_s": float(f"{budget_s:.4g}"),
        "tier0_fraction": tier0_fraction,
        "max_queued": int(max_queued),
        "tier1_quota": int(tier1_quota),
        "sat_latency_s": sat_latency_s,
        "workers": int(workers),
        "submitted": int(submitted),
        "outcomes": outcomes,
        "by_tier": {str(t): c for t, c in by_tier.items()},
        "tier0_goodput": (None if tier0_goodput is None
                          else float(f"{tier0_goodput:.6g}")),
        "wire_resolved_within_budget_fraction": float(
            f"{resolved_frac:.6g}"),
        "wire_p50_ms": (float(f"{np.percentile(wire_lat, 50) * 1e3:.4g}")
                        if wire_lat else None),
        "wire_p99_ms": (float(f"{np.percentile(wire_lat, 99) * 1e3:.4g}")
                        if wire_lat else None),
        "shed_probe": shed_probe,
        "steady_recompiles": int(steady_recompiles),
        "backlog_peak": snap["backlog_peak"],
        "shed": snap["shed"],
        "expired": snap["expired"],
        "dispatches": snap["dispatches"],
        "coalesce_width_mean": snap["coalesce_width_mean"],
        "load_mid_drill": load_mid,
        "healthz_mid_drill": healthz_mid,
        "scrape": scrape,
        "stream": stream_leg,
        "disconnect": disconnect,
        "drain": drain_leg,
        "incident_captures": len(recorder.captures),
        "incident_captures_pre_drain": captures_before_drain,
        "span_accounting": acc,
        "flight_record": flight_record(
            tracer, eng.counters, reason="edge_drill_complete"),
    }


def subject_store_drill_run(
    params,
    *,
    subjects: int = 100_000,
    requests_per_leg: int = 120,
    lanes: int = 2,
    max_subjects: int = 32,
    warm_capacity: int = 64,
    max_rows: int = 2,
    max_bucket: int = 8,
    zipf_a: float = 1.2,
    max_delay_s: float = 0.003,
    workers: int = 8,
    pair_slice: int = 20,
    seed: int = 0,
    cold_dir: Optional[str] = None,
    backend: Optional[str] = None,
    tracer=None,
    log: Callable[[str], None] = None,
) -> dict:
    """THE tiered-subject-store capacity drill (PR 16 tentpole; bench
    config19).

    ``subjects`` synthetic identities (default 100k) are REGISTERED —
    betas only, ~40 bytes each, never bulk-baked — on two lane-fleet
    engines driven through the capacity ladder under Zipf traffic:

    * **hot_only** — working set <= ``max_subjects``: every request
      resolves from the device table (the warmup pre-fills it to full
      capacity, so the leg is recompile- and promotion-free);
    * **warm_spill** — working set > hot but <= hot + warm: evictions
      demote rows to host RAM and later dispatches PROMOTE them back
      (async ``device_put`` started at coalesce admit), never
      re-running the shape stage;
    * **cold_spill** — Zipf over the whole universe: warm-LRU overflow
      pages rows to disk (orbax row pages) and deep-tail requests page
      them back (or re-bake on a true miss — counted, never an error).
      A DAMAGE PROBE then corrupts one cold page in place and requests
      that subject: the load must degrade to a counted re-bake
      (``subject_store_cold_damage``) with a bit-correct result.

    The INTERLEAVED PAIRED protocol (the slope-time discipline applied
    to A/B serving): each leg's request stream is cut into slices run
    alternately on the SHARDED engine (N lanes holding N disjoint
    shard tables through the store) and a REPLICATED twin (same lanes,
    no store) — same requests, same load, so the throughput ratio and
    the per-lane device-rows comparison are paired, not sequential.
    ``scripts/bench_report.py:judge_subject_store`` reads: hot-tier
    hit rate, promotion-stall p99 inside the coalesce window, ZERO
    steady recompiles across the whole ladder, per-lane device rows
    strictly below the replicated baseline, every future resolved
    (misses counted, never errored), spans closed exactly once. All
    CPU-lane-provable; no chip required.
    """
    import concurrent.futures as cf
    import tempfile
    import threading

    import jax

    from mano_hand_tpu.serving.engine import ServingEngine, ServingError
    from mano_hand_tpu.serving.subject_store import (SubjectStore,
                                                     SubjectStoreConfig)

    log = _logger(log)
    if tracer is None:
        tracer = Tracer(capacity=65536)
    n_joints, n_shape = params.n_joints, params.n_shape
    prm32 = params.astype(np.float32)
    rng = np.random.default_rng(seed)
    universe = rng.normal(size=(subjects, n_shape)).astype(np.float32)

    tmp = None
    if cold_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="mano_subject_store_")
        cold_dir = tmp.name

    # The ladder's working sets (universe index ranges / samplers).
    hot_n = max_subjects
    warm_n = min(subjects, max_subjects + max(8, warm_capacity // 2))

    def make_stream(n, leg_universe, pass_seed):
        r = np.random.default_rng(pass_seed)
        idx = (r.zipf(zipf_a, size=n).astype(np.int64) - 1) % leg_universe
        sizes = r.integers(1, max_rows + 1, size=n)
        return [(r.normal(scale=0.4,
                          size=(int(s), n_joints, 3)).astype(np.float32),
                 int(i))
                for s, i in zip(sizes, idx)]

    legs = ("hot_only", "warm_spill", "cold_spill")
    streams = {
        "hot_only": make_stream(requests_per_leg, hot_n, seed + 101),
        "warm_spill": make_stream(requests_per_leg, warm_n, seed + 102),
        "cold_spill": make_stream(requests_per_leg, subjects, seed + 103),
    }

    # Reference pass FIRST: the single-device engine, subjects baked on
    # demand — the bit-identity bar for every tiered/sharded result.
    reference = {}
    ref_eng = ServingEngine(prm32, max_bucket=max_bucket,
                            max_delay_s=0.001)
    with ref_eng:
        ref_keys = {}

        def ref_forward(pose, si):
            if si not in ref_keys:
                ref_keys[si] = ref_eng.specialize(universe[si])
            return ref_eng.forward(pose, subject=ref_keys[si])

        for name in legs:
            reference[name] = [ref_forward(p, si)
                               for p, si in streams[name]]

    store = SubjectStore(SubjectStoreConfig(
        warm_capacity=warm_capacity, cold_dir=cold_dir,
        sharded=True, backend=backend))
    eng_s = ServingEngine(
        prm32, max_bucket=max_bucket, max_subjects=max_subjects,
        max_delay_s=max_delay_s, lanes=lanes, tracer=tracer,
        subject_store=store)
    eng_r = ServingEngine(
        prm32, max_bucket=max_bucket, max_subjects=max_subjects,
        max_delay_s=max_delay_s, lanes=lanes)
    resolve_timeout = 120.0

    def run_slice(eng, keys, stream, outcomes, results, base):
        lock = threading.Lock()

        def submit_one(j):
            p, si = stream[j]
            fut = eng.submit(p, subject=keys[si])
            try:
                results[base + j] = fut.result(timeout=resolve_timeout)
                k = "ok"
            except ServingError as e:
                k = "expired" if e.kind == "expired" else "error"
            except Exception:   # noqa: BLE001 — a timeout IS the bug
                k = "stranded"
            with lock:
                outcomes[k] += 1

        t0 = time.perf_counter()
        with cf.ThreadPoolExecutor(max_workers=workers) as pool:
            list(pool.map(submit_one, range(len(stream))))
        return time.perf_counter() - t0

    def max_err(results, refs):
        worst = 0.0
        for got, want in zip(results, refs):
            if got is None:
                return None          # an unresolved result: no bar
            worst = max(worst, float(np.abs(got - want).max()))
        return worst

    leg_out = {}
    damage = {}
    try:
        with eng_s, eng_r:
            keys_s = eng_s.register_subjects(universe)
            keys_r = eng_r.register_subjects(universe)
            assert keys_s == keys_r     # content-addressed, same bytes
            # Pre-fill the hot tier to FULL capacity, then warm: the
            # table (and every shard table) reaches its final shape
            # before any executable builds, so the whole ladder runs
            # with zero steady recompiles — growth is a warmup event.
            for i in range(hot_n):
                eng_s.specialize(universe[i])
                eng_r.specialize(universe[i])
            buckets = [b for b in eng_s.buckets if b <= max_bucket]
            for e in (eng_s, eng_r):
                e.warmup(buckets)
                e.warmup_posed(buckets)
            warm_compiles_s = eng_s.counters.compiles
            warm_compiles_r = eng_r.counters.compiles
            log(f"subject-store drill: {subjects} registered subjects, "
                f"hot={max_subjects} warm={warm_capacity} "
                f"lanes={lanes} sharded vs replicated, "
                f"{warm_compiles_s} warm-up compiles (sharded)")

            dt_s_total = dt_r_total = 0.0
            oc_s = {"ok": 0, "error": 0, "expired": 0, "stranded": 0}
            oc_r = dict(oc_s)
            for name in legs:
                stream = streams[name]
                res_s = [None] * len(stream)
                res_r = [None] * len(stream)
                dt_s = dt_r = 0.0
                store_before = eng_s.counters.snapshot()
                for base in range(0, len(stream), pair_slice):
                    sl = stream[base:base + pair_slice]
                    dt_s += run_slice(eng_s, keys_s, sl, oc_s,
                                      res_s, base)
                    dt_r += run_slice(eng_r, keys_r, sl, oc_r,
                                      res_r, base)
                dt_s_total += dt_s
                dt_r_total += dt_r
                after = eng_s.counters.snapshot()
                leg_out[name] = {
                    "requests": len(stream),
                    "distinct_subjects": len({si for _, si in stream}),
                    "sharded_vs_reference_max_abs_err": max_err(
                        res_s, reference[name]),
                    "replicated_vs_reference_max_abs_err": max_err(
                        res_r, reference[name]),
                    "throughput_sharded_per_sec": float(
                        f"{len(stream) / dt_s:.5g}") if dt_s else None,
                    "throughput_replicated_per_sec": float(
                        f"{len(stream) / dt_r:.5g}") if dt_r else None,
                    "store_deltas": {
                        k: after[k] - store_before[k]
                        for k in ("subject_store_hot_hits",
                                  "subject_store_warm_hits",
                                  "subject_store_cold_hits",
                                  "subject_store_misses",
                                  "subject_store_prefetches",
                                  "subject_store_demotions_warm",
                                  "subject_store_demotions_cold")},
                }
                log(f"  leg {name}: "
                    f"{leg_out[name]['distinct_subjects']} subjects, "
                    f"err_s={leg_out[name]['sharded_vs_reference_max_abs_err']}")

            # -- cold-revisit mini-leg: force organic cold hits -------
            # A small universe can resolve every paired leg out of
            # hot+warm (the inclusive tiers keep recently-paged rows
            # warm), leaving the cold READ path untested by real
            # traffic.  Pull a handful of evicted-everywhere digests
            # back through the live engine so the cold tier serves
            # organic requests, with bit-parity against a fresh
            # reference engine.
            from mano_hand_tpu.io import orbax_ckpt

            with eng_s._exe_lock:
                hot_now = set(eng_s._subject_slots)
            warm_now = set(store.warm_digests())
            cold_only = [d for d in store.cold_digests()
                         if d not in hot_now and d not in warm_now]
            revisit = cold_only[:max(1, requests_per_leg // 4)]
            # A stopped engine never restarts: parity for the revisit
            # leg and the damage probe comes from ONE fresh
            # single-device engine.
            ref2 = ServingEngine(prm32, max_bucket=max_bucket,
                                 max_delay_s=0.001)
            with ref2:
                if revisit:
                    rv_before = eng_s.counters.snapshot()
                    pose_rv = rng.normal(
                        scale=0.4,
                        size=(1, n_joints, 3)).astype(np.float32)
                    rv_err = 0.0
                    t0_rv = time.perf_counter()
                    for d in revisit:
                        got = eng_s.submit(
                            pose_rv,
                            subject=d).result(timeout=resolve_timeout)
                        oc_s["ok"] += 1
                        want = ref2.forward(
                            pose_rv, subject=ref2.specialize(
                                universe[keys_s.index(d)]))
                        rv_err = max(rv_err, float(
                            np.abs(np.asarray(got) - want).max()))
                    dt_rv = time.perf_counter() - t0_rv
                    rv_after = eng_s.counters.snapshot()
                    leg_out["cold_revisit"] = {
                        "requests": len(revisit),
                        "distinct_subjects": len(revisit),
                        "sharded_vs_reference_max_abs_err": rv_err,
                        "throughput_sharded_per_sec": float(
                            f"{len(revisit) / dt_rv:.5g}")
                        if dt_rv else None,
                        "store_deltas": {
                            k: rv_after[k] - rv_before[k]
                            for k in (
                                "subject_store_hot_hits",
                                "subject_store_warm_hits",
                                "subject_store_cold_hits",
                                "subject_store_misses",
                                "subject_store_prefetches",
                                "subject_store_demotions_warm",
                                "subject_store_demotions_cold")},
                    }
                    log(f"  leg cold_revisit: {len(revisit)} subjects, "
                        f"err_s={rv_err}, cold_hits="
                        f"{leg_out['cold_revisit']['store_deltas']['subject_store_cold_hits']}")

                # -- damage probe: corrupt one cold page IN PLACE -----
                # The victim comes from the NON-revisited cold
                # remainder: revisited digests were just promoted back
                # to hot/warm and would be served without touching
                # their (corrupted) page.
                with eng_s._exe_lock:
                    hot_now = set(eng_s._subject_slots)
                warm_now = set(store.warm_digests())
                rv_set = set(revisit)
                victims = [d for d in store.cold_digests()
                           if d not in hot_now and d not in warm_now
                           and d not in rv_set]
                if victims:
                    vd = victims[0]
                    vi = keys_s.index(vd)
                    meta, arrays = orbax_ckpt.load_row_page(vd, cold_dir)
                    # A self-CONSISTENT page for the WRONG subject: the
                    # per-array hashes verify, the digest preimage does
                    # not — exactly the silent-corruption case the
                    # content check exists for.
                    arrays["shape"] = np.asarray(arrays["shape"]) + 1.0
                    orbax_ckpt.save_row_page(vd, arrays, cold_dir,
                                             backend=backend)
                    dmg_before = eng_s.counters.snapshot()[
                        "subject_store_cold_damage"]
                    pose = rng.normal(
                        scale=0.4,
                        size=(1, n_joints, 3)).astype(np.float32)
                    want = ref2.forward(
                        pose, subject=ref2.specialize(universe[vi]))
                    got = eng_s.submit(
                        pose, subject=vd).result(timeout=resolve_timeout)
                    oc_s["ok"] += 1
                    dmg_after = eng_s.counters.snapshot()[
                        "subject_store_cold_damage"]
                    damage = {
                        "injected": True,
                        "damage_counted": int(dmg_after - dmg_before),
                        "request_max_abs_err": float(
                            np.abs(np.asarray(got) - want).max()),
                    }
                else:
                    damage = {"injected": False}

            steady_recompiles_s = (eng_s.counters.compiles
                                   - warm_compiles_s)
            steady_recompiles_r = (eng_r.counters.compiles
                                   - warm_compiles_r)
            counters_snap = eng_s.counters.snapshot()
            load_s = eng_s.load()
            load_r = eng_r.load()
    finally:
        if tmp is not None:
            tmp.cleanup()

    lookups = sum(counters_snap[k] for k in (
        "subject_store_hot_hits", "subject_store_warm_hits",
        "subject_store_cold_hits", "subject_store_misses"))
    hot_rate = (counters_snap["subject_store_hot_hits"] / lookups
                if lookups else None)
    prom = counters_snap["subject_store_promotion_ms"]
    per_s = load_s["lanes"]["per_lane"]
    per_r = load_r["lanes"]["per_lane"]
    rows_s = [p["table_capacity"] for p in per_s]
    rows_r = [p["table_capacity"] for p in per_r]
    n_paired = len(legs) * requests_per_leg
    n_total = n_paired + len(revisit) + (
        1 if damage.get("injected") else 0)
    resolved = n_total - oc_s["stranded"]
    acc = tracer.accounting()
    return {
        "subjects_registered": int(subjects),
        "lanes": int(lanes),
        "hot_capacity": int(max_subjects),
        "warm_capacity": int(warm_capacity),
        "zipf_a": float(zipf_a),
        "coalesce_window_ms": float(max_delay_s * 1e3),
        "requests_total": int(n_total),
        "futures_resolved_fraction": float(f"{resolved / n_total:.6g}"),
        "outcomes": oc_s,
        "outcomes_replicated": oc_r,
        "legs": leg_out,
        "damage_probe": damage,
        "hot_tier_hit_rate": (None if hot_rate is None
                              else float(f"{hot_rate:.6g}")),
        "store_counters": {
            k: counters_snap[k] for k in (
                "subject_store_hot_hits", "subject_store_warm_hits",
                "subject_store_cold_hits", "subject_store_misses",
                "subject_store_prefetches",
                "subject_store_promotions",
                "subject_store_demotions_warm",
                "subject_store_demotions_cold",
                "subject_store_cold_damage")},
        "promotion_stall_ms": prom,
        "promotion_p99_within_window": bool(
            prom["n"] == 0 or prom["p99_ms"] <= max_delay_s * 1e3),
        "steady_recompiles": int(steady_recompiles_s),
        "steady_recompiles_replicated": int(steady_recompiles_r),
        "per_lane_device_rows_sharded": rows_s,
        "per_lane_device_rows_replicated": rows_r,
        "device_rows_ratio": (
            float(f"{max(rows_s) / max(rows_r):.4g}")
            if rows_r and max(rows_r) else None),
        "throughput_sharded_per_sec": float(
            f"{n_paired / dt_s_total:.5g}") if dt_s_total else None,
        "throughput_replicated_per_sec": float(
            f"{n_paired / dt_r_total:.5g}") if dt_r_total else None,
        "paired_throughput_ratio": (
            float(f"{dt_r_total / dt_s_total:.4g}")
            if dt_s_total and dt_r_total else None),
        "subject_store": load_s["subject_store"],
        "lanes_sharded": bool(load_s["lanes"].get("sharded")),
        "platform": jax.default_backend(),
        "spans": {
            "started": acc["spans_started"],
            "closed": acc["spans_closed"],
            "open": acc["spans_open"],
            "closed_by_kind": acc["closed_by_kind"],
        },
        "flight_record": flight_record(
            tracer, eng_s.counters, reason="subject_store_drill_complete"),
    }


def _prom_value(text: str, name: str):
    """First value of a plain (label-free) Prometheus sample, or None."""
    for ln in text.splitlines():
        if ln.startswith(name + " "):
            try:
                return float(ln.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return None


def fleet_drill_run(
    params,
    *,
    workers: int = 3,
    lanes: int = 2,
    streams: int = 208,
    frames_per_stream: int = 4,
    stream_workers: int = 16,
    unique_tracks: int = 8,
    max_bucket: int = 8,
    max_subjects: int = 32,
    store_warm_capacity: int = 16,
    drain_budget_s: float = 10.0,
    ready_timeout_s: float = 420.0,
    frame_deadline_s: float = 120.0,
    client_timeout_s: float = 180.0,
    work_dir=None,
    seed: int = 0,
    log: Callable[[str], None] = None,
) -> dict:
    """THE fleet chaos drill (config21, PR 18): a rolling deploy that
    never drops a frame, measured end to end across real process
    boundaries. Shared by ``bench.py`` config21 and tests/test_fleet.py
    (the recovery-drill pattern: one protocol, the artifacts cannot
    diverge).

    The substrate is the PR-18 front tier at full depth: N ``mano
    serve`` worker PROCESSES (``edge.fleet``) cold-booting from a
    per-lane executable lattice baked in THIS process, fronted by one
    ``edge.EdgeProxy`` doing health-aware routing and live stream
    migration. Phases:

    1. **Bake + boot**: bake the lattice (per-lane tier included — the
       shard capacity rides the default ladder), boot every worker with
       ``--lanes`` + ``--aot-dir``, and scrape each worker's /metrics:
       the cold-boot criterion is compiles == 0 AND aot_loads > 0 PER
       WORKER at lanes=N (PR-6's zero-retrace boot, per-worker).
    2. **Warm + baseline**: one direct stream per worker compiles the
       fit-stage programs (warm-up-class, counted as warm), then the
       drill's stream fleet opens through the proxy and settles one
       frame wave; per-worker compile baselines are scraped HERE —
       everything after is steady state.
    3. **Chaos**: SIGKILL one worker while the next frame wave is in
       flight (relays fail over mid-frame: the resend-on-dead-backend
       exception, siblings re-derive identical frames from the last
       confirmed pose), then DRAIN a second worker under the remaining
       live streams (polite migration: close on the old worker, warm
       re-open on a sibling) against ``drain_budget_s``.
    4. **Judgment inputs**: every frame of every stream must reach an
       HTTP terminal; every stream's POSE chain must be bit-equal to
       its track's in-process reference and to every fleet sibling on
       the same track, migrated streams included (the warm-start
       handoff contract — verts get f32 anchor tolerance, see the
       parity comment below); steady recompiles must be 0
       fleet-wide (exit-line counters minus the baselines; the
       SIGKILLed worker is excluded by construction — its counters
       died with it); spans must close exactly once on every worker
       that reported (exit-line accounting — the cross-process half).

    All CPU-defined: workers pin ``--platform cpu`` and the sockets are
    loopback — no chip required, none harmed.
    """
    import os
    import shutil
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.edge import (
        EdgeClient,
        EdgeError,
        Fleet,
        WorkerSpec,
    )
    from mano_hand_tpu.models import anim, core
    from mano_hand_tpu.serving.engine import ServingEngine
    from mano_hand_tpu.serving.subject_store import (
        SubjectStore,
        SubjectStoreConfig,
    )

    if workers < 3:
        raise ValueError(f"workers must be >= 3 (kill one, drain one, "
                         f"serve on the rest), got {workers}")
    if streams < workers:
        raise ValueError(f"streams must be >= workers, got {streams}")
    if frames_per_stream < 3:
        raise ValueError(f"frames_per_stream must be >= 3 (settle + "
                         f"kill + drain waves), got {frames_per_stream}")
    log = _logger(log)
    host = "127.0.0.1"
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    prm32 = params.astype(np.float32)
    tracks = min(max(1, unique_tracks), streams)

    own_work_dir = work_dir is None
    if own_work_dir:
        work_dir = tempfile.mkdtemp(prefix="mano_fleet_drill_")
    aot_dir = os.path.join(work_dir, "aot")
    log_dir = os.path.join(work_dir, "logs")
    os.makedirs(aot_dir, exist_ok=True)
    os.makedirs(log_dir, exist_ok=True)

    # ---- Phase 1: bake the per-lane lattice, boot the fleet ----------
    t_bake0 = time.monotonic()
    bake_eng = ServingEngine(
        prm32, max_bucket=max_bucket, aot_dir=aot_dir, lanes=lanes,
        max_subjects=max_subjects,
        subject_store=SubjectStore(SubjectStoreConfig(
            warm_capacity=store_warm_capacity, sharded=True)))
    manifest = bake_eng.bake_lattice(platforms=("cpu",),
                                     include_cpu_fallback=False)
    bake_wall = time.monotonic() - t_bake0
    log(f"fleet: baked {len(manifest['entries'])} lattice entries in "
        f"{bake_wall:.1f}s (capacities "
        f"{sorted({e.get('capacity') for e in manifest['entries'].values() if 'capacity' in e})})")

    # Worker CPUs need `lanes` host devices; append, never clobber,
    # the site's XLA_FLAGS.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " "
                 f"--xla_force_host_platform_device_count={lanes}").strip()
    # One spec PER worker: each gets its own compile-cache dir via
    # MANO_TEST_CACHE_DIR. Workers inherit the parent env, so under a
    # pytest lane they would otherwise all share the lane's cache dir
    # with the live pytest process — the XLA executable-deserialization
    # crash class (CLAUDE.md: never two processes on one cache dir).
    specs = [WorkerSpec(platform="cpu", lanes=lanes,
                        max_bucket=max_bucket,
                        max_delay_ms=1.0, max_subjects=max_subjects,
                        aot_dir=aot_dir,
                        store_warm_capacity=store_warm_capacity,
                        drain_timeout_s=max(15.0, drain_budget_s),
                        extra_env={"MANO_TEST_CACHE_DIR": os.path.join(
                            work_dir, f"jax_cache_w{i}")})
             for i in range(workers)]
    fleet = Fleet(specs, env={"XLA_FLAGS": flags},
                  stderr_dir=log_dir,
                  proxy_kwargs=dict(connect_timeout_s=5.0,
                                    probe_timeout_s=2.0,
                                    upstream_timeout_s=client_timeout_s),
                  log=lambda m: log(f"fleet: {m}"))
    t_boot0 = time.monotonic()
    fleet.start(ready_timeout_s=ready_timeout_s)
    boot_wall = time.monotonic() - t_boot0
    ports = {name: w.port for name, w in fleet.workers.items()}
    log(f"fleet: {workers} workers up in {boot_wall:.1f}s "
        f"(lanes={lanes} each), proxy on :{fleet.proxy.port}")

    def scrape(name: str) -> dict:
        cli = EdgeClient(host, ports[name], timeout_s=30.0)
        try:
            text = cli.metrics_text()
        finally:
            cli.close()
        return {k: int(_prom_value(text, f"mano_serving_{k}") or 0)
                for k in ("compiles", "aot_loads", "aot_load_failures")}

    try:
        # Cold-boot criterion: per-worker lattice boot, zero re-traces.
        cold_boot = {name: scrape(name) for name in fleet.workers}
        log(f"fleet: cold boot counters {cold_boot}")

        # ---- Reference tracks + targets (deterministic fits) ---------
        betas = [rng.normal(size=(n_shape,)).astype(np.float32)
                 for _ in range(tracks)]
        keys = np.zeros((tracks, 3, n_joints, 3), np.float32)
        keys[:, 1] = rng.normal(scale=0.2, size=(tracks, n_joints, 3))
        keys[:, 2] = keys[:, 1] + rng.normal(
            scale=0.1, size=(tracks, n_joints, 3))
        track_poses = np.stack([
            anim.resample_poses(keys[t], frames_per_stream)
            for t in range(tracks)]).astype(np.float32)
        flat_pose = track_poses.reshape(
            tracks * frames_per_stream, n_joints, 3)
        flat_beta = np.stack([betas[t] for t in range(tracks)
                              for _ in range(frames_per_stream)])
        gt = core.jit_forward_batched(prm32.device_put(),
                                      jnp.asarray(flat_pose),
                                      jnp.asarray(flat_beta))
        targets = np.asarray(gt.posed_joints).reshape(
            tracks, frames_per_stream, n_joints, 3)

        ref_eng = ServingEngine(prm32, max_bucket=max_bucket,
                                max_delay_s=0.001,
                                max_subjects=max_subjects)
        ref_eng.start()
        ref_frames = []
        for t in range(tracks):
            sess = ref_eng.open_stream(betas[t])
            ref_frames.append([sess.step(targets[t, f])
                               for f in range(frames_per_stream)])
            sess.close()
        ref_eng.stop()

        # ---- Phase 2: warm the fit stage on EVERY worker -------------
        for name in fleet.workers:
            wcli = EdgeClient(host, ports[name], timeout_s=60.0)
            with wcli.open_stream(betas=betas[0],
                                  frame_deadline_s=frame_deadline_s) as ws:
                ws.frame(targets[0, 0])
            wcli.close()

        # The drill's stream fleet, all through the proxy.
        clients = []
        stream_clis = []
        for s in range(streams):
            cli = EdgeClient(host, fleet.proxy.port,
                             timeout_s=client_timeout_s)
            st = cli.open_stream(betas=betas[s % tracks],
                                 frame_deadline_s=frame_deadline_s)
            clients.append(cli)
            stream_clis.append(st)
        log(f"fleet: {streams} live streams open through the proxy "
            f"({tracks} distinct tracks)")

        outcomes = {"ok": 0, "http_error": 0, "exception": 0}
        got = [[None] * frames_per_stream for _ in range(streams)]
        rec_lock = threading.Lock()

        def step(s: int, f: int):
            try:
                fr = stream_clis[s].frame(targets[s % tracks, f])
                with rec_lock:
                    outcomes["ok"] += 1
                    got[s][f] = fr
            except EdgeError as e:
                with rec_lock:
                    outcomes["http_error"] += 1
                    got[s][f] = ("http", e.status, e.kind)
            except Exception as e:  # noqa: BLE001 — NOT a terminal
                with rec_lock:
                    outcomes["exception"] += 1
                    got[s][f] = ("exc", type(e).__name__, str(e)[:120])

        pool = ThreadPoolExecutor(max_workers=stream_workers)

        def wave(f: int):
            list(pool.map(lambda s: step(s, f), range(streams)))

        # Settle wave 0, then everything after is steady state.
        t_w0 = time.monotonic()
        wave(0)
        wave0_wall = time.monotonic() - t_w0
        baseline = {name: scrape(name) for name in fleet.workers}

        # ---- Phase 3: chaos. SIGKILL mid-wave, then drain. -----------
        load = {be.name: len(be.streams)
                for be in fleet.proxy.backends().values()}
        kill_victim = max(load, key=lambda n: load[n])
        t_w1 = time.monotonic()
        killer_fired = threading.Event()

        def killer():
            # Mid-wave: frames are on the wire when the SIGKILL lands.
            time.sleep(min(0.05, wave0_wall / 4))
            fleet.kill_worker(kill_victim)
            killer_fired.set()

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        wave(1)
        kt.join(timeout=30.0)
        kill_wave_wall = time.monotonic() - t_w1
        log(f"fleet: killed {kill_victim} (hosted "
            f"{load[kill_victim]} streams) mid-wave; wave 1 resolved "
            f"in {kill_wave_wall:.1f}s, migrations so far "
            f"{fleet.proxy.migrations}")

        load2 = {be.name: len(be.streams)
                 for be in fleet.proxy.backends().values()
                 if be.name != kill_victim}
        drain_victim = max(load2, key=lambda n: load2[n])
        t_dr = time.monotonic()
        drain_report = fleet.drain_worker(
            drain_victim, migrate_timeout_s=drain_budget_s,
            term_timeout_s=max(30.0, drain_budget_s * 3))
        drain_wall = time.monotonic() - t_dr
        log(f"fleet: drained {drain_victim} (hosted "
            f"{load2[drain_victim]} streams): migrated "
            f"{drain_report.get('streams_migrated')} in "
            f"{drain_report.get('wall_s')}s (budget {drain_budget_s}s, "
            f"clean={drain_report.get('clean')})")

        for f in range(2, frames_per_stream):
            wave(f)
        pool.shutdown(wait=True)

        closes_ok = 0
        close_errors = []
        for s in range(streams):
            try:
                stream_clis[s].close()
                closes_ok += 1
            except Exception as e:  # noqa: BLE001
                close_errors.append(f"{type(e).__name__}: {e}"[:120])
            clients[s].close()

        proxy_counters = {
            "migrations": fleet.proxy.migrations,
            "migrated_frames": fleet.proxy.migrated_frames,
            "frames_relayed": fleet.proxy.frames_relayed,
            "reroutes": fleet.proxy.reroutes,
            "upstream_failures": fleet.proxy.upstream_failures,
            "streams_opened": fleet.proxy.streams_opened,
        }

        # ---- Phase 4: teardown + cross-process aggregation -----------
        reports = fleet.stop(timeout_s=max(30.0, drain_budget_s * 3))
    finally:
        try:
            fleet.stop(timeout_s=30.0)
        except Exception:  # noqa: BLE001 — teardown must finish
            pass

    # Parity, two tiers. (1) POSE bit-equality — intra-fleet AND
    # against the in-process reference: the pose chain IS the fit
    # state the migration handoff transfers (resume_pose), the fits
    # are deterministic and run per-stream, so a migrated stream's
    # poses must be IDENTICAL to an unmigrated sibling's and to the
    # reference — exact zero, across process boundaries (this is the
    # "migrated warm starts bit-equal" judgment). (2) VERTS at f32
    # tolerance: verts are a pure function of (pose, betas) but ride
    # the coalesced batch, and WHICH bucket executable serves a batch
    # varies run to run (b1 vs b2 differ by ~1 ulp on CPU) — that
    # jitter exists on one worker with no chaos at all, so demanding
    # bit-zero here would be judging the batcher, not the handoff.
    frames_expected = streams * frames_per_stream
    parity_err = 0.0
    pose_err = 0.0
    intra_err = 0.0
    intra_pose_err = 0.0
    numbering_ok = 0
    compared = 0
    canon = {}
    for s in range(streams):
        for f in range(frames_per_stream):
            fr = got[s][f]
            if not hasattr(fr, "verts"):
                continue
            compared += 1
            ref = ref_frames[s % tracks][f]
            parity_err = max(parity_err, float(
                np.max(np.abs(fr.verts - ref.verts))))
            pose_err = max(pose_err, float(
                np.max(np.abs(fr.pose - ref.pose))))
            first = canon.setdefault((s % tracks, f), fr)
            if first is not fr:
                intra_err = max(intra_err, float(
                    np.max(np.abs(fr.verts - first.verts))))
                intra_pose_err = max(intra_pose_err, float(
                    np.max(np.abs(fr.pose - first.pose))))
            if fr.frame == f:
                numbering_ok += 1

    # Steady recompiles: exit-line counters minus the post-warm
    # baselines. The SIGKILLed worker is excluded by construction (no
    # exit line — its counters and spans died with it).
    steady_by_worker = {}
    spans_by_worker = {}
    aot_failures = 0
    for name, rep in reports.items():
        if rep is None:
            steady_by_worker[name] = None
            spans_by_worker[name] = None
            continue
        cnt = rep.get("counters") or {}
        steady_by_worker[name] = (
            int(cnt.get("compiles", 0))
            - baseline.get(name, {}).get("compiles", 0))
        aot_failures += int(cnt.get("aot_load_failures", 0))
        acc = rep.get("accounting") or {}
        spans_by_worker[name] = {
            "started": acc.get("spans_started"),
            "closed": acc.get("spans_closed"),
            "open": acc.get("spans_open"),
            "double_closed": acc.get("spans_double_closed"),
        }
    steady_total = sum(v for v in steady_by_worker.values()
                       if v is not None)
    spans_balanced = all(
        v is None or (v["started"] == v["closed"] and v["open"] == 0
                      and not v["double_closed"])
        for v in spans_by_worker.values())

    if own_work_dir:
        shutil.rmtree(work_dir, ignore_errors=True)

    terminals = outcomes["ok"] + outcomes["http_error"]
    return {
        "fleet_drill_schema": 1,
        # Workers are ALWAYS cpu subprocesses; the in-process reference
        # rides the parent's backend. The judge applies the exact-zero
        # in-process pose anchor only when this is "cpu" (intra-fleet
        # bit-equality is platform-independent and judged always).
        "reference_platform": jax.default_backend(),
        "workers": int(workers),
        "lanes": int(lanes),
        "streams": int(streams),
        "frames_per_stream": int(frames_per_stream),
        "unique_tracks": int(tracks),
        "max_bucket": int(max_bucket),
        "max_subjects": int(max_subjects),
        "store_warm_capacity": int(store_warm_capacity),
        "lattice_entries": len(manifest["entries"]),
        "bake_wall_s": float(f"{bake_wall:.4g}"),
        "boot_wall_s": float(f"{boot_wall:.4g}"),
        "cold_boot": cold_boot,
        "cold_boot_zero_compiles": all(
            c["compiles"] == 0 and c["aot_loads"] > 0
            and c["aot_load_failures"] == 0
            for c in cold_boot.values()),
        "frames_expected": int(frames_expected),
        "outcomes": outcomes,
        "terminal_fraction": float(
            f"{terminals / frames_expected:.6g}") if frames_expected
            else None,
        "closes_ok": int(closes_ok),
        "close_errors": close_errors[:5],
        "frames_compared": int(compared),
        "frame_numbering_ok": int(numbering_ok),
        "intra_fleet_max_abs_err": intra_err,
        "intra_fleet_pose_max_abs_err": intra_pose_err,
        "wire_vs_inprocess_max_abs_err": parity_err,
        "wire_vs_inprocess_pose_max_abs_err": pose_err,
        "kill": {
            "victim": kill_victim,
            "streams_hosted": int(load[kill_victim]),
            "fired_mid_wave": bool(killer_fired.is_set()),
            "wave_wall_s": float(f"{kill_wave_wall:.4g}"),
        },
        "drain": {
            "victim": drain_victim,
            "streams_hosted": int(load2[drain_victim]),
            "budget_s": float(drain_budget_s),
            "wall_s": drain_report.get("wall_s"),
            "clean": bool(drain_report.get("clean")),
            "streams_migrated": drain_report.get("streams_migrated"),
            "total_wall_s": float(f"{drain_wall:.4g}"),
        },
        "proxy": proxy_counters,
        "steady_recompiles_by_worker": steady_by_worker,
        "steady_recompiles_total": int(steady_total),
        "aot_load_failures_total": int(aot_failures),
        "spans_by_worker": spans_by_worker,
        "spans_closed_exactly_once": bool(spans_balanced),
        "worker_exit_reports": {
            name: (None if rep is None else {
                k: rep.get(k) for k in
                ("drained", "incident_captures")})
            for name, rep in reports.items()},
    }


def selfheal_drill_run(
    params,
    *,
    workers: int = 3,
    lanes: int = 2,
    streams: int = 12,
    frames_per_stream: int = 7,
    stream_workers: int = 8,
    unique_tracks: int = 4,
    max_bucket: int = 8,
    max_subjects: int = 32,
    store_warm_capacity: int = 16,
    campaign: str = "kill_worker@0.2s, kill_proxy@1.5s, partition:25@3s",
    store_campaign: str = "damage_page@0s",
    mttr_budget_ms: float = 300000.0,
    restart_budget: int = 6,
    budget_window_s: float = 900.0,
    probe_interval_s: float = 0.25,
    probe_timeout_s: float = 2.0,
    failure_threshold: int = 3,
    heal_timeout_s: float = 300.0,
    ready_timeout_s: float = 420.0,
    frame_deadline_s: float = 120.0,
    client_timeout_s: float = 60.0,
    storm_leg: bool = True,
    work_dir=None,
    seed: int = 0,
    log: Callable[[str], None] = None,
) -> dict:
    """THE self-healing chaos campaign (config23, PR 20): every PR-20
    recovery tier drilled end to end, with ZERO human invocations —
    detection and repair belong to the supervisor/standby/overlay, the
    drill only schedules faults and measures. Shared by ``bench.py``
    config23 and tests/test_selfheal.py (one protocol, the artifacts
    cannot diverge).

    **Leg A — process campaign.** The full PR-20 fleet: ``workers``
    fixed-port ``mano serve`` processes (``--warm-streams``, per-lane
    AOT lattice, one compile-cache dir EACH) supervised by a
    ``FleetSupervisor``; an active/standby ``mano proxy``
    :class:`~mano_hand_tpu.edge.fleet.ProxyPair` behind one
    flock-arbitered service port; ``streams``
    :class:`~mano_hand_tpu.edge.client.ResilientStream` clients. A
    seeded :class:`~mano_hand_tpu.runtime.chaos.ChaosCampaign`
    (``KIND[:PARAM]@Ts`` grammar) then fires ``kill_worker`` (SIGKILL
    a worker — the supervisor's exit-line channel), ``kill_proxy``
    (SIGKILL the ACTIVE proxy — flock takeover, clients
    reconnect-and-resume), and ``partition`` (SIGSTOP a worker: the
    process lives, ``/healthz`` stops — the supervisor's breaker
    channel; a SIGCONT backstop fires at ``:PARAM`` seconds in case
    the supervisor is the thing that broke). Judgment inputs: every
    frame reaches an HTTP terminal with CONTINUOUS numbering, pose
    chains stay bit-equal to the in-process reference (healed workers
    and resumed streams included), heals == scheduled deaths with the
    post-heal steady wave compiling NOTHING, per-heal MTTR within
    ``mttr_budget_ms``, spans closed exactly once on every worker that
    reported an exit line.

    **Leg C — restart storm** (rides the same fleet, after the steady
    check): a fresh supervisor with ``restart_budget=1`` takes one
    kill (heals) and then a second (budget exhausted) — the drill
    passes only if the second death DEGRADES (worker abandoned,
    incident recorded, surviving workers still serve a fresh stream)
    instead of flapping.

    **Leg B — in-process store/lane tier.** A sharded ``lanes``-lane
    engine over a warm+cold ``SubjectStore``: force one lane's breaker
    DOWN — the next dead-shard placement AUTO-kicks the PR-20 shard
    rebalance (store overlay + engine-hot row adoption), after which
    the dead lane's subjects serve bit-identical with 0 recompiles
    (the ``(bucket, capacity)`` keying is untouched). Then a second
    seeded campaign fires ``damage_page`` against one COLD row page:
    the next access is a COUNTED re-bake (never an error) and the
    result stays bit-identical.

    All CPU-defined: workers pin ``--platform cpu``, sockets are
    loopback — no chip required, none harmed.
    """
    import os
    import shutil
    import signal as signal_mod
    import socket
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.edge import (
        EdgeClient,
        EdgeError,
        Fleet,
        FleetSupervisor,
        ProxyPair,
        ProxySpec,
        ResilientStream,
        WorkerSpec,
    )
    from mano_hand_tpu.models import anim, core
    from mano_hand_tpu.runtime import health as health_mod
    from mano_hand_tpu.runtime.chaos import ChaosCampaign
    from mano_hand_tpu.runtime.health import CircuitBreaker
    from mano_hand_tpu.runtime.supervise import DispatchPolicy
    from mano_hand_tpu.serving.engine import ServingEngine
    from mano_hand_tpu.serving.subject_store import (
        SubjectStore,
        SubjectStoreConfig,
    )

    if workers < 3:
        raise ValueError(f"workers must be >= 3 (kill one, partition "
                         f"one, serve on the rest), got {workers}")
    if frames_per_stream < 6:
        raise ValueError(
            f"frames_per_stream must be >= 6 (settle + >=2 chaos + "
            f"post-heal settle + steady waves), got {frames_per_stream}")
    # Parse up front: a bad campaign spec must fail before any process
    # boots. The process leg takes exactly the three process kinds.
    proc_campaign = ChaosCampaign(campaign, seed=seed)
    bad = sorted({e.kind for e in proc_campaign.events}
                 - {"kill_worker", "kill_proxy", "partition"})
    if bad:
        raise ValueError(f"process campaign kinds {bad} not drillable "
                         "here (damage_page is the store campaign's)")
    expected_heals = sum(1 for e in proc_campaign.events
                         if e.kind in ("kill_worker", "partition"))
    expected_takeovers = sum(1 for e in proc_campaign.events
                             if e.kind == "kill_proxy")
    if restart_budget < expected_heals + 2:
        raise ValueError(
            f"restart_budget {restart_budget} cannot absorb "
            f"{expected_heals} scheduled deaths plus boot-failure "
            "retries")
    log = _logger(log)
    host = "127.0.0.1"
    n_joints, n_shape = params.n_joints, params.n_shape
    rng = np.random.default_rng(seed)
    prm32 = params.astype(np.float32)
    tracks = min(max(1, unique_tracks), streams)

    own_work_dir = work_dir is None
    if own_work_dir:
        work_dir = tempfile.mkdtemp(prefix="mano_selfheal_drill_")
    aot_dir = os.path.join(work_dir, "aot")
    log_dir = os.path.join(work_dir, "logs")
    os.makedirs(aot_dir, exist_ok=True)
    os.makedirs(log_dir, exist_ok=True)

    def free_ports(n: int) -> list:
        # Bind all n simultaneously so the kernel guarantees they are
        # distinct, then release: the just-released ports are free to
        # re-bind (the fixed-port heal contract needs them STABLE, so
        # they are chosen once, here).
        socks = [socket.socket() for _ in range(n)]
        try:
            for s in socks:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind((host, 0))
            return [s.getsockname()[1] for s in socks]
        finally:
            for s in socks:
                s.close()

    ports = free_ports(workers + 1)
    service_port = ports[-1]
    worker_ports = {f"w{i}": ports[i] for i in range(workers)}

    # ---- Phase 1: bake the per-lane lattice ---------------------------
    t_bake0 = time.monotonic()
    bake_eng = ServingEngine(
        prm32, max_bucket=max_bucket, aot_dir=aot_dir, lanes=lanes,
        max_subjects=max_subjects,
        subject_store=SubjectStore(SubjectStoreConfig(
            warm_capacity=store_warm_capacity, sharded=True)))
    manifest = bake_eng.bake_lattice(platforms=("cpu",),
                                     include_cpu_fallback=False)
    bake_wall = time.monotonic() - t_bake0
    log(f"selfheal: baked {len(manifest['entries'])} lattice entries "
        f"in {bake_wall:.1f}s")

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " "
                 f"--xla_force_host_platform_device_count={lanes}").strip()
    # FIXED ports + --warm-streams: the replacement a heal boots binds
    # the dead worker's own port after a full warm pass (fit-stage
    # programs are not in the lattice), so it re-enters the standby
    # pair's STATIC routing with zero wiring calls and zero steady
    # compiles. One compile-cache dir per worker (CLAUDE.md: never two
    # processes on one cache dir).
    specs = [WorkerSpec(platform="cpu", lanes=lanes,
                        max_bucket=max_bucket,
                        max_delay_ms=1.0, max_subjects=max_subjects,
                        aot_dir=aot_dir,
                        store_warm_capacity=store_warm_capacity,
                        warm_streams=True,
                        drain_timeout_s=15.0,
                        port=worker_ports[f"w{i}"],
                        extra_env={"MANO_TEST_CACHE_DIR": os.path.join(
                            work_dir, f"jax_cache_w{i}")})
             for i in range(workers)]
    fleet = Fleet(specs, env={"XLA_FLAGS": flags},
                  stderr_dir=log_dir, external_proxy=True,
                  log=lambda m: log(f"selfheal: {m}"))
    pair = ProxyPair(
        ProxySpec(port=service_port,
                  lock_path=os.path.join(work_dir, "proxy.lock"),
                  backends=[(n, host, p)
                            for n, p in worker_ports.items()],
                  drain_timeout_s=10.0,
                  upstream_timeout_s=client_timeout_s * 4),
        stderr_dir=log_dir, log=lambda m: log(f"selfheal: {m}"))
    sup = FleetSupervisor(
        fleet, poll_interval_s=0.05,
        probe_interval_s=probe_interval_s,
        probe_timeout_s=probe_timeout_s,
        failure_threshold=failure_threshold,
        restart_budget=restart_budget,
        budget_window_s=budget_window_s,
        ready_timeout_s=ready_timeout_s,
        log=lambda m: log(f"selfheal: {m}"))
    sup2 = None

    def scrape(name: str):
        cli = EdgeClient(host, worker_ports[name], timeout_s=30.0)
        try:
            text = cli.metrics_text()
        except Exception:  # noqa: BLE001 — a dead worker scrapes None
            return None
        finally:
            cli.close()
        return {k: int(_prom_value(text, f"mano_serving_{k}") or 0)
                for k in ("compiles", "aot_loads", "aot_load_failures")}

    t_boot0 = time.monotonic()
    fleet.start(ready_timeout_s=ready_timeout_s)
    try:
        pair.start(timeout_s=60.0)
        boot_wall = time.monotonic() - t_boot0
        log(f"selfheal: {workers} fixed-port workers + proxy pair up "
            f"in {boot_wall:.1f}s (service :{service_port})")

        boot_counters = {name: scrape(name) for name in fleet.workers}
        lattice_boot_ok = all(
            c is not None and c["aot_loads"] > 0
            and c["aot_load_failures"] == 0
            for c in boot_counters.values())

        # ---- Reference tracks (deterministic fits) -------------------
        betas = [rng.normal(size=(n_shape,)).astype(np.float32)
                 for _ in range(tracks)]
        keys = np.zeros((tracks, 3, n_joints, 3), np.float32)
        keys[:, 1] = rng.normal(scale=0.2, size=(tracks, n_joints, 3))
        keys[:, 2] = keys[:, 1] + rng.normal(
            scale=0.1, size=(tracks, n_joints, 3))
        track_poses = np.stack([
            anim.resample_poses(keys[t], frames_per_stream)
            for t in range(tracks)]).astype(np.float32)
        flat_pose = track_poses.reshape(
            tracks * frames_per_stream, n_joints, 3)
        flat_beta = np.stack([betas[t] for t in range(tracks)
                              for _ in range(frames_per_stream)])
        gt = core.jit_forward_batched(prm32.device_put(),
                                      jnp.asarray(flat_pose),
                                      jnp.asarray(flat_beta))
        targets = np.asarray(gt.posed_joints).reshape(
            tracks, frames_per_stream, n_joints, 3)

        ref_eng = ServingEngine(prm32, max_bucket=max_bucket,
                                max_delay_s=0.001,
                                max_subjects=max_subjects)
        ref_eng.start()
        ref_frames = []
        for t in range(tracks):
            sess = ref_eng.open_stream(betas[t])
            ref_frames.append([sess.step(targets[t, f])
                               for f in range(frames_per_stream)])
            sess.close()
        ref_eng.stop()

        # ---- Streams: reconnect-and-resume clients -------------------
        stream_clis = [
            ResilientStream(host, service_port,
                            timeout_s=client_timeout_s,
                            betas=betas[s % tracks],
                            max_reconnects=12,
                            reconnect_backoff_s=0.1,
                            reconnect_timeout_s=60.0,
                            frame_deadline_s=frame_deadline_s)
            for s in range(streams)]
        log(f"selfheal: {streams} resilient streams open through the "
            f"pair ({tracks} distinct tracks)")

        outcomes = {"ok": 0, "http_error": 0, "exception": 0}
        got = [[None] * frames_per_stream for _ in range(streams)]
        rec_lock = threading.Lock()

        def step(s: int, f: int):
            try:
                fr = stream_clis[s].frame(targets[s % tracks, f])
                with rec_lock:
                    outcomes["ok"] += 1
                    got[s][f] = fr
            except EdgeError as e:
                with rec_lock:
                    outcomes["http_error"] += 1
                    got[s][f] = ("http", e.status, e.kind)
            except Exception as e:  # noqa: BLE001 — NOT a terminal
                with rec_lock:
                    outcomes["exception"] += 1
                    got[s][f] = ("exc", type(e).__name__, str(e)[:120])

        pool = ThreadPoolExecutor(max_workers=stream_workers)

        def wave(f: int):
            list(pool.map(lambda s: step(s, f), range(streams)))

        wave(0)                                         # settle
        baseline = {name: scrape(name) for name in fleet.workers}
        sup.start()

        # ---- Leg A: the campaign fires under live waves --------------
        takeover_walls = []

        def on_kill_worker(ev):
            alive = [n for n, w in fleet.workers.items() if w.alive()]
            victim = proc_campaign.pick(alive)
            if victim is None:
                raise RuntimeError("no live worker to kill")
            fleet.kill_worker(victim)
            return victim

        def on_kill_proxy(ev):
            t0 = time.monotonic()
            victim = pair.kill_active()
            pair.wait_active(timeout_s=60.0)
            dt_ms = (time.monotonic() - t0) * 1e3
            takeover_walls.append(round(dt_ms, 1))
            return {"victim": victim,
                    "takeover_ms": round(dt_ms, 1)}

        def on_partition(ev):
            alive = [n for n, w in fleet.workers.items() if w.alive()]
            victim = proc_campaign.pick(alive)
            if victim is None:
                raise RuntimeError("no live worker to partition")
            pid = fleet.workers[victim].pid
            os.kill(pid, signal_mod.SIGSTOP)

            def backstop():
                # Only matters if the supervisor ITSELF failed: its
                # heal SIGKILLs the stopped remains long before this.
                try:
                    os.kill(pid, signal_mod.SIGCONT)
                except OSError:
                    pass

            t = threading.Timer(ev.param, backstop)
            t.daemon = True
            t.start()
            return {"victim": victim, "stopped_pid": pid,
                    "sigcont_backstop_s": ev.param}

        proc_campaign.on("kill_worker", on_kill_worker)
        proc_campaign.on("kill_proxy", on_kill_proxy)
        proc_campaign.on("partition", on_partition)
        proc_campaign.start()

        t_chaos0 = time.monotonic()
        for f in range(1, frames_per_stream - 2):        # chaos waves
            wave(f)
        last_event_s = (proc_campaign.events[-1].at_s
                        if proc_campaign.events else 0.0)
        campaign_done = proc_campaign.join(
            timeout_s=last_event_s + 120.0)

        # Wait until the supervisor healed every scheduled death
        # (bounded — a heal that never lands is the drill's failure,
        # not its hang).
        t_heal0 = time.monotonic()
        heal_deadline = t_heal0 + heal_timeout_s
        while time.monotonic() < heal_deadline:
            if sup.load()["fleet"]["restarts"] >= expected_heals:
                break
            time.sleep(0.1)
        heal_wait_wall = time.monotonic() - t_heal0
        chaos_wall = time.monotonic() - t_chaos0

        wave(frames_per_stream - 2)                      # post-heal settle
        baseline2 = {name: scrape(name) for name in fleet.workers}
        wave(frames_per_stream - 1)                      # steady
        final_counters = {name: scrape(name) for name in fleet.workers}
        pool.shutdown(wait=True)

        # Post-heal steady recompiles: scraped live over the fixed
        # ports (exit lines would miss the healed workers' baselines).
        steady_by_worker = {}
        for name in fleet.workers:
            b2, fc = baseline2.get(name), final_counters.get(name)
            steady_by_worker[name] = (
                None if b2 is None or fc is None
                else fc["compiles"] - b2["compiles"])
        steady_total = sum(v for v in steady_by_worker.values()
                           if v is not None)

        closes_ok = 0
        close_errors = []
        reconnects_total = 0
        for s in range(streams):
            reconnects_total += stream_clis[s].reconnects
            try:
                stream_clis[s].close()
                closes_ok += 1
            except Exception as e:  # noqa: BLE001
                close_errors.append(f"{type(e).__name__}: {e}"[:120])

        sup_ledger = sup.load()["fleet"]
        sup.stop()

        # ---- Leg C: restart storm -> degraded + incident -------------
        storm = None
        if storm_leg:
            sup2 = FleetSupervisor(
                fleet, poll_interval_s=0.05,
                probe_interval_s=probe_interval_s,
                probe_timeout_s=probe_timeout_s,
                failure_threshold=failure_threshold,
                restart_budget=1, budget_window_s=3600.0,
                ready_timeout_s=ready_timeout_s,
                log=lambda m: log(f"selfheal-storm: {m}"))
            sup2.start()
            victim = sorted(n for n, w in fleet.workers.items()
                            if w.alive())[0]
            fleet.kill_worker(victim)
            d1 = time.monotonic() + heal_timeout_s
            while (time.monotonic() < d1
                   and sup2.load()["fleet"]["restarts"] < 1):
                time.sleep(0.1)
            fleet.kill_worker(victim)            # budget now exhausted
            d2 = time.monotonic() + 60.0
            while time.monotonic() < d2:
                led = sup2.load()["fleet"]
                if led["incidents"] >= 1 and victim in led["abandoned"]:
                    break
                time.sleep(0.1)
            storm_ledger = sup2.load()["fleet"]
            sup2.stop()
            sup2 = None
            # Degraded-but-serving: a FRESH stream through the pair
            # must still produce bit-exact frames off the survivors.
            deg_err = None
            deg_frames = 0
            try:
                rs = ResilientStream(host, service_port,
                                     timeout_s=client_timeout_s,
                                     betas=betas[0], max_reconnects=12,
                                     reconnect_timeout_s=60.0,
                                     frame_deadline_s=frame_deadline_s)
                try:
                    deg_err = 0.0
                    for f in range(2):
                        fr = rs.frame(targets[0, f])
                        deg_err = max(deg_err, float(np.max(np.abs(
                            fr.pose - ref_frames[0][f].pose))))
                        deg_frames += 1
                finally:
                    rs.abort()
            except Exception as e:  # noqa: BLE001 — recorded, judged
                close_errors.append(
                    f"storm-degraded: {type(e).__name__}: {e}"[:120])
            storm = {
                "victim": victim,
                "restarts": storm_ledger["restarts"],
                "deaths_detected": storm_ledger["deaths_detected"],
                "incidents": storm_ledger["incidents"],
                "incident_log": storm_ledger["incident_log"],
                "abandoned": storm_ledger["abandoned"],
                "budget_left": storm_ledger["budget"]["left"],
                "degraded_frames_ok": deg_frames,
                "degraded_pose_max_abs_err": deg_err,
                "degraded_without_flap": bool(
                    storm_ledger["restarts"] == 1
                    and storm_ledger["incidents"] == 1
                    and victim in storm_ledger["abandoned"]),
            }
            log(f"selfheal: storm leg — {storm['restarts']} heal, "
                f"{storm['incidents']} incident, abandoned "
                f"{storm['abandoned']}, degraded serve err={deg_err}")

        # Takeover facts from the surviving active proxy itself.
        proxy_health = None
        try:
            hcli = EdgeClient(host, service_port, timeout_s=10.0)
            h = hcli.healthz()
            hcli.close()
            proxy_health = {"proxy_role": h.get("proxy_role"),
                            "takeovers": h.get("takeovers")}
        except Exception as e:  # noqa: BLE001 — recorded, judged
            close_errors.append(
                f"proxy-healthz: {type(e).__name__}: {e}"[:120])

        proxy_reports = pair.stop(timeout_s=30.0)
        reports = fleet.stop(timeout_s=60.0)
    finally:
        try:
            if sup2 is not None:
                sup2.stop()
            sup.stop()
        except Exception:  # noqa: BLE001 — teardown must finish
            pass
        try:
            proc_campaign.stop()
        except Exception:  # noqa: BLE001 — teardown must finish
            pass
        try:
            pair.stop(timeout_s=10.0)
        except Exception:  # noqa: BLE001 — teardown must finish
            pass
        try:
            fleet.stop(timeout_s=30.0)
        except Exception:  # noqa: BLE001 — teardown must finish
            pass

    # ---- Leg A parity + spans (same bars as the fleet drill) ---------
    frames_expected = streams * frames_per_stream
    pose_err = 0.0
    verts_err = 0.0
    numbering_ok = 0
    compared = 0
    for s in range(streams):
        for f in range(frames_per_stream):
            fr = got[s][f]
            if not hasattr(fr, "verts"):
                continue
            compared += 1
            ref = ref_frames[s % tracks][f]
            pose_err = max(pose_err, float(
                np.max(np.abs(fr.pose - ref.pose))))
            verts_err = max(verts_err, float(
                np.max(np.abs(fr.verts - ref.verts))))
            if fr.frame == f:
                numbering_ok += 1

    spans_by_worker = {}
    for name, rep in reports.items():
        if rep is None:
            spans_by_worker[name] = None
            continue
        acc = rep.get("accounting") or {}
        spans_by_worker[name] = {
            "started": acc.get("spans_started"),
            "closed": acc.get("spans_closed"),
            "open": acc.get("spans_open"),
            "double_closed": acc.get("spans_double_closed"),
        }
    spans_balanced = all(
        v is None or (v["started"] == v["closed"] and v["open"] == 0
                      and not v["double_closed"])
        for v in spans_by_worker.values())

    mttr_ms = list(sup_ledger["mttr_ms"])
    mttr_p99 = (float(np.percentile(mttr_ms, 99)) if mttr_ms else None)

    # ---- Leg B: shard rebalance + cold-page damage (in-process) ------
    log("selfheal: leg B — in-process shard rebalance + damage_page")
    n_b = 6
    betas_b = [rng.normal(size=(n_shape,)).astype(np.float32)
               for _ in range(n_b)]
    poses_b = [rng.normal(scale=0.4,
                          size=(2, n_joints, 3)).astype(np.float32)
               for _ in range(n_b)]
    with ServingEngine(prm32, max_bucket=max_bucket,
                       max_delay_s=0.001) as ref_b:
        keys_r = [ref_b.specialize(b) for b in betas_b]
        want_b = [ref_b.forward(poses_b[i], subject=keys_r[i])
                  for i in range(n_b)]

    cold_dir = os.path.join(work_dir, "cold")
    store_b = SubjectStore(SubjectStoreConfig(
        warm_capacity=2, cold_dir=cold_dir, sharded=True,
        backend="pickle"))
    lane_ok = [True] * lanes
    policy_b = DispatchPolicy(
        deadline_s=30.0, retries=1, backoff_s=0.005,
        backoff_cap_s=0.01, jitter=0.0,
        breaker=CircuitBreaker(failure_threshold=2,
                               probe_interval_s=0.001,
                               respect_priority_claim=False),
        cpu_fallback=True)
    rebalance = {}
    damage = {}
    store_campaign_fired = []
    with ServingEngine(prm32, max_bucket=max_bucket, max_delay_s=0.002,
                       policy=policy_b, lanes=lanes,
                       lane_probe=lambda i: lane_ok[i],
                       max_subjects=4,
                       subject_store=store_b) as eng_b:
        keys_b = [eng_b.specialize(b) for b in betas_b]
        pre_err = 0.0
        for i in range(n_b):                     # warm every program
            got_b = eng_b.forward(poses_b[i], subject=keys_b[i])
            pre_err = max(pre_err, float(
                np.abs(got_b - want_b[i]).max()))
        shards_pop = sorted({store_b.shard_for(k) for k in keys_b})
        dead = store_b.shard_for(keys_b[0])
        owned = [i for i in range(n_b)
                 if store_b.shard_for(keys_b[i]) == dead]
        base_b = eng_b.counters.snapshot()
        # Lane loss: probe pinned false + breaker driven DOWN through
        # its public API (the tests' idiom — never a raw state poke).
        lane_ok[dead] = False
        lane_set = eng_b._get_lanes()
        br = lane_set.lanes[dead].breaker
        for _ in range(64):
            if br is None or br.record_failure() == health_mod.DOWN:
                break
        # The next dead-shard placement AUTO-kicks the rebalance; the
        # drill never calls it (0 human invocations).
        trigger = eng_b.forward(poses_b[owned[0]],
                                subject=keys_b[owned[0]])
        reb_deadline = time.monotonic() + 60.0
        while (eng_b.counters.snapshot()["shard_rebalances"] < 1
               and time.monotonic() < reb_deadline):
            time.sleep(0.02)
        reb_err = float(np.abs(trigger - want_b[owned[0]]).max())
        for i in owned:                          # adopted-shard serving
            got_b = eng_b.forward(poses_b[i], subject=keys_b[i])
            reb_err = max(reb_err, float(
                np.abs(got_b - want_b[i]).max()))
        after_b = eng_b.counters.snapshot()
        rebalance = {
            "dead_shard": int(dead),
            "shards_populated": shards_pop,
            "owned_subjects": len(owned),
            "pre_loss_max_abs_err": pre_err,
            "shard_rebalances": int(after_b["shard_rebalances"]),
            "rebalance_rows": int(after_b["shard_rebalance_rows"]),
            "steady_recompiles": int(after_b["compiles"]
                                     - base_b["compiles"]),
            "max_abs_err": reb_err,
            "reassigned": store_b.snapshot().get("reassigned_shards"),
        }
        log(f"selfheal: rebalanced shard {dead} "
            f"({rebalance['shard_rebalances']} rebalance, "
            f"{rebalance['rebalance_rows']} rows adopted, "
            f"{rebalance['steady_recompiles']} recompiles, "
            f"err={reb_err})")

        # -- damage_page: seeded store campaign vs the cold tier ------
        camp2 = ChaosCampaign(store_campaign, seed=seed + 1,
                              log=lambda m: log(f"selfheal: {m}"))
        dmg_digest = {}

        def on_damage(ev):
            from mano_hand_tpu.io import orbax_ckpt

            victim_d = camp2.pick(store_b.cold_digests())
            if victim_d is None:
                raise RuntimeError("no cold page to damage")
            # The test idiom (tests/test_subject_store.py): a page
            # whose per-array hashes verify but whose digest preimage
            # does not — self-consistent, for the WRONG subject.
            meta, arrays = orbax_ckpt.load_row_page(victim_d, cold_dir)
            arrays["shape"] = np.asarray(arrays["shape"]) + 1.0
            orbax_ckpt.save_row_page(victim_d, arrays, cold_dir,
                                     backend="pickle")
            dmg_digest["digest"] = victim_d
            return victim_d

        camp2.on("damage_page", on_damage).start()
        camp2.join(timeout_s=30.0)
        store_campaign_fired = list(camp2.events_fired)
        dig = dmg_digest.get("digest")
        req_err = None
        dmg_counted = 0
        if dig is not None and dig in keys_b:
            # Push the damaged digest out of the hot table AND the
            # 2-row warm tier so the verification request must read
            # the (damaged) cold page.
            for i in range(n_b):
                if keys_b[i] != dig:
                    eng_b.forward(poses_b[i], subject=keys_b[i])
            dmg_base = eng_b.counters.snapshot()[
                "subject_store_cold_damage"]
            i = keys_b.index(dig)
            got_b = eng_b.forward(poses_b[i], subject=keys_b[i])
            req_err = float(np.abs(got_b - want_b[i]).max())
            dmg_counted = (eng_b.counters.snapshot()[
                "subject_store_cold_damage"] - dmg_base)
        damage = {
            "injected": dig is not None,
            "digest": (dig or "")[:12],
            "damage_counted": int(dmg_counted),
            "request_max_abs_err": req_err,
        }
        log(f"selfheal: damage_page — counted {dmg_counted} re-bake, "
            f"err={req_err}")

    if own_work_dir:
        shutil.rmtree(work_dir, ignore_errors=True)

    terminals = outcomes["ok"] + outcomes["http_error"]
    return {
        "selfheal_drill_schema": 1,
        # Workers are ALWAYS cpu subprocesses; the in-process
        # references ride the parent's backend — the judge applies the
        # exact-zero pose anchors only when this is "cpu".
        "reference_platform": jax.default_backend(),
        "workers": int(workers),
        "lanes": int(lanes),
        "streams": int(streams),
        "frames_per_stream": int(frames_per_stream),
        "unique_tracks": int(tracks),
        "max_bucket": int(max_bucket),
        "max_subjects": int(max_subjects),
        "campaign": campaign,
        "store_campaign": store_campaign,
        "campaign_done": bool(campaign_done),
        "campaign_fired": proc_campaign.events_fired,
        "store_campaign_fired": store_campaign_fired,
        "lattice_entries": len(manifest["entries"]),
        "bake_wall_s": float(f"{bake_wall:.4g}"),
        "boot_wall_s": float(f"{boot_wall:.4g}"),
        "boot_counters": boot_counters,
        "lattice_boot_ok": bool(lattice_boot_ok),
        "chaos_wall_s": float(f"{chaos_wall:.4g}"),
        "frames_expected": int(frames_expected),
        "outcomes": outcomes,
        "terminal_fraction": float(
            f"{terminals / frames_expected:.6g}") if frames_expected
            else None,
        "frames_compared": int(compared),
        "frame_numbering_ok": int(numbering_ok),
        "pose_max_abs_err": pose_err,
        "verts_max_abs_err": verts_err,
        "closes_ok": int(closes_ok),
        "close_errors": close_errors[:8],
        "reconnects_total": int(reconnects_total),
        "takeovers_expected": int(expected_takeovers),
        "takeover_walls_ms": takeover_walls,
        "proxy_health": proxy_health,
        "proxy_exit_reports": {
            name: (None if rep is None else
                   {k: rep.get(k) for k in ("role", "takeovers")})
            for name, rep in proxy_reports.items()},
        "expected_heals": int(expected_heals),
        "heal_wait_wall_s": float(f"{heal_wait_wall:.4g}"),
        "supervisor": sup_ledger,
        "supervisor_restarts": int(sup_ledger["restarts"]),
        "all_deaths_auto_healed": bool(
            sup_ledger["restarts"] >= expected_heals
            and not sup_ledger["abandoned"]),
        "heal_mttr_ms": mttr_ms,
        "heal_p99_mttr_ms": (None if mttr_p99 is None
                             else float(f"{mttr_p99:.5g}")),
        "heal_max_mttr_ms": (max(mttr_ms) if mttr_ms else None),
        "mttr_budget_ms": float(mttr_budget_ms),
        "mttr_within_budget": bool(
            mttr_ms and max(mttr_ms) <= mttr_budget_ms),
        "steady_recompiles_by_worker": steady_by_worker,
        "steady_recompiles_total": int(steady_total),
        "spans_by_worker": spans_by_worker,
        "spans_closed_exactly_once": bool(spans_balanced),
        "storm": storm,
        "storm_restarts": (None if storm is None
                           else int(storm["restarts"])),
        "rebalance": rebalance,
        "damage": damage,
        "worker_exit_reports": {
            name: (None if rep is None else {
                k: rep.get(k) for k in
                ("drained", "incident_captures")})
            for name, rep in reports.items()},
    }


def control_drill_run(
    params,
    *,
    # Trace shape: a flash crowd whose peak offers peak_multiple x the
    # socket-calibrated service rate while the pre-crowd base leaves
    # slack — the controller's cold window. Tier 0 is deliberately a
    # MINORITY share so its offered load stays under capacity even at
    # peak (priority scheduling then keeps its goodput ~flat in both
    # legs and tier-1 served becomes the discriminator).
    trace_kind: str = "flash_crowd",
    trace_seed: int = 7,
    trace_duration_s: float = 2.5,
    base_fraction: float = 0.5,
    peak_multiple: float = 4.0,
    tier0_fraction: float = 0.15,
    crowd_at_fraction: float = 0.35,
    pairs: int = 2,
    # Engine envelope (the edge-drill shape: pool > queue or overload
    # never materializes through blocking clients).
    max_queued: int = 16,
    tier1_quota: int = 4,
    deadline_s: float = 0.6,
    sat_latency_s: float = 0.02,
    max_bucket: int = 8,
    batch_deadline_s: float = 0.5,
    coalesce_base_s: float = 0.004,
    workers: int = 24,
    # Controller cadence for a seconds-long trace: ticks must land
    # INSIDE the pre-crowd window or the grow leg never happens.
    cadence_s: float = 0.05,
    crash_at_fraction: float = 0.5,
    drain_timeout_s: float = 10.0,
    seed: int = 0,
    log: Callable[[str], None] = None,
) -> dict:
    """THE closed-loop control drill (config22, PR 19): the adaptive
    controller versus its own static defaults on the SAME seeded flash
    crowd, through the real socket. Shared by ``bench.py`` config22 and
    tests/test_control.py (the recovery-drill pattern: one protocol,
    the artifacts cannot diverge).

    Protocol:

    1. **Calibrate**: measure this box's wire service rate (edge-drill
       waves under quota, through the socket) and scale ONE seeded
       ``traffic.make_trace`` flash crowd off it. The trace is
       generated once; its ``serialize()`` digest rides the artifact as
       the determinism receipt. Every leg replays the same arrivals.
    2. **Paired legs, interleaved**: ``pairs`` x (static, controlled),
       alternating, each on a FRESH engine + EdgeServer (per-leg
       tracers: the closed-once accounting is judged per leg). The
       static leg is today's behavior: fixed ``tier1_quota`` of
       ``max_queued``. The controlled leg starts from the SAME statics
       and lets ``serving.control.Controller`` steer quotas, coalesce,
       bucket bias, and per-tier Retry-After off live burn rates.
       Interleaving is the edge-drill noise defense: box-load drift
       costs both arms, not whichever arm it lands on.
    3. **Crash leg**: one controlled replay where the control thread is
       killed mid-crowd (``crash_at_fraction`` into the trace). The
       criterion is the PR-19 safety contract: the controller reverts
       every actuator to the static defaults, the engine keeps serving,
       and 100% of requests still reach an HTTP terminal — a dead
       controller degrades to today's behavior, never wedges admission.

    Judgment inputs (``scripts/bench_report.py`` owns the verdict):
    controlled tier-0 goodput >= static tier-0 goodput on the pooled
    pairs AND controlled tier-1 served STRICTLY greater; 0 steady
    recompiles every leg; every actuation evented (runtime-event count
    == the counter ledger, per controlled leg); spans closed exactly
    once per leg; crash leg reverted + fully terminal. Burn rates are
    computed by the REGISTRY's own ``slo_report`` math on each leg's
    exit counters — the controller is judged against the bookkeeping it
    steered by. All CPU-defined: saturation is a chaos throttle, the
    sockets are loopback — no chip required, none harmed.
    """
    import hashlib
    import queue as queue_mod
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from mano_hand_tpu.edge import EdgeClient, EdgeError, EdgeServer
    from mano_hand_tpu.obs.metrics import slo_report
    from mano_hand_tpu.runtime.chaos import ChaosPlan
    from mano_hand_tpu.runtime.supervise import DispatchPolicy
    from mano_hand_tpu.serving import traffic
    from mano_hand_tpu.serving.control import ControlConfig, Controller
    from mano_hand_tpu.serving.engine import ServingEngine

    if pairs < 1:
        raise ValueError(f"pairs must be >= 1, got {pairs}")
    if workers < 2:
        raise ValueError(f"workers must be >= 2, got {workers}")
    if trace_duration_s <= 0:
        raise ValueError(
            f"trace_duration_s must be > 0, got {trace_duration_s}")
    if not 0.0 < crash_at_fraction < 1.0:
        raise ValueError(
            f"crash_at_fraction must be in (0, 1), got "
            f"{crash_at_fraction}")
    log = _logger(log)
    n_joints = params.n_joints
    rng = np.random.default_rng(seed)
    prm32 = params.astype(np.float32)
    host = "127.0.0.1"
    pose1 = rng.normal(scale=0.4, size=(1, n_joints, 3)).astype(
        np.float32)
    plan_spec = f"sat:{sat_latency_s}@0-"
    static_quotas = {1: int(tier1_quota)}

    def fresh_engine(tracer):
        policy = DispatchPolicy(
            deadline_s=batch_deadline_s, retries=0, backoff_s=0.0,
            backoff_cap_s=0.0, jitter=0.0, breaker=None,
            chaos=ChaosPlan(plan_spec),
            # The overload-drill rule: overload is not a fault; the
            # fallback tier would quietly raise capacity mid-leg.
            cpu_fallback=False,
        )
        eng = ServingEngine(
            prm32, max_bucket=max_bucket, max_delay_s=coalesce_base_s,
            policy=policy, max_queued=max_queued,
            tier_quotas=dict(static_quotas), tracer=tracer)
        eng.start()
        eng.warmup()
        return eng

    # ---- Calibrate the wire service rate (edge-drill definition) -----
    cal_tracer = Tracer(capacity=32768)
    cal_eng = fresh_engine(cal_tracer)
    cal_srv = EdgeServer(cal_eng, host=host, port=0,
                         drain_timeout_s=drain_timeout_s).start()
    wave = min(max_bucket, max_queued)

    def _cal_one():
        # One client per request: EdgeClient owns one socket and is
        # not safe to share across the wave's threads.
        cli = EdgeClient(host, cal_srv.port, timeout_s=30.0)
        try:
            cli.forward(pose1, priority=0)
        finally:
            cli.close()

    t0 = time.perf_counter()
    served = 0
    for _ in range(3):
        with ThreadPoolExecutor(min(wave, workers)) as px:
            futs = [px.submit(_cal_one) for _ in range(wave)]
            for f in futs:
                f.result(timeout=60.0)
        served += wave
    service_rate = served / (time.perf_counter() - t0)
    cal_srv.drain(timeout_s=drain_timeout_s)

    base_hz = base_fraction * service_rate
    peak_hz = peak_multiple * service_rate
    trace = traffic.make_trace(
        trace_kind, seed=trace_seed, duration_s=trace_duration_s,
        base_hz=base_hz, peak_hz=peak_hz,
        tier0_fraction=tier0_fraction,
        crowd_at_fraction=crowd_at_fraction)
    trace_bytes = traffic.serialize(trace)
    stats = traffic.trace_stats(trace)
    log(f"control: wire service rate {service_rate:,.0f} req/s, trace "
        f"{trace_kind} seed={trace_seed} -> {stats['arrivals']} "
        f"arrivals ({stats['tier0']} tier-0), peak "
        f"{stats['peak_rate_hz']:,.0f} req/s over {trace_duration_s}s")

    # Budget: engine resolution window + one wire grace (the edge-drill
    # bound on this 1-core box).
    budget_s = deadline_s + batch_deadline_s + 0.5

    def leg_run(name: str, controlled: bool,
                crash_at_s: Optional[float] = None) -> dict:
        tr = Tracer(capacity=32768)
        eng = fresh_engine(tr)
        ctl = None
        if controlled:
            ctl = Controller(eng, config=ControlConfig(
                cadence_s=cadence_s,
                min_actuation_interval_s=2.0 * cadence_s,
                coalesce_max_s=max(coalesce_base_s, 0.004),
                tier1_quota_max_fraction=0.75,
            ), log=log)
            ctl.start()
        srv = EdgeServer(
            eng, host=host, port=0, drain_timeout_s=drain_timeout_s,
            retry_after_source=(None if ctl is None
                                else ctl.retry_after_for)).start()
        compiles_warm = eng.counters.compiles

        tasks: queue_mod.Queue = queue_mod.Queue()
        records: list = []
        rec_lock = threading.Lock()
        _STOP = object()

        def worker():
            cli = EdgeClient(host, srv.port, timeout_s=30.0)
            while True:
                item = tasks.get()
                if item is _STOP:
                    cli.close()
                    return
                tier = item
                t0 = time.monotonic()
                retry_after = None
                try:
                    cli.forward(pose1, priority=tier,
                                deadline_s=deadline_s)
                    out = "ok"
                except EdgeError as e:
                    out = {429: "shed", 504: "expired"}.get(
                        e.status, "error")
                    retry_after = e.retry_after_s
                except Exception:  # noqa: BLE001 — a timeout IS the bug
                    out = "unresolved"
                t1 = time.monotonic()
                with rec_lock:
                    records.append((tier, t0, t1, out, retry_after))

        pool = [threading.Thread(target=worker, daemon=True)
                for _ in range(workers)]
        for t in pool:
            t.start()

        crash_timer = None
        crash_fired = threading.Event()
        if crash_at_s is not None:
            def _inject():
                crash_fired.set()
                # The drill reaches into the controller on purpose:
                # _crash IS the crash path every BaseException in the
                # control loop takes — injecting here exercises the
                # revert contract without faking an exception class.
                ctl._crash(RuntimeError(
                    "control_drill: injected controller crash"))
            crash_timer = threading.Timer(crash_at_s, _inject)
            crash_timer.daemon = True
            crash_timer.start()

        # ---- Replay the ONE trace, paced to its offsets --------------
        t_start = time.monotonic()
        for (t_off, tier) in trace:
            lag = (t_start + t_off) - time.monotonic()
            if lag > 0:
                time.sleep(lag)
            tasks.put(tier)
        submitted = len(trace)
        dl = time.monotonic() + trace_duration_s + 2 * budget_s + 30.0
        drained = False
        while time.monotonic() < dl:
            with rec_lock:
                if len(records) >= submitted:
                    drained = True
                    break
            time.sleep(0.005)
        wall = time.monotonic() - t_start
        if crash_timer is not None:
            crash_timer.cancel()

        # Exit-line bookkeeping BEFORE teardown: the control block and
        # slo_report ride the same load() the controller steered by.
        load_end = eng.load()
        snapc = eng.counters.snapshot()
        slo = slo_report(snapc, None, load_end["latency_by_tier"])
        ctl_block = load_end["control"]
        # The crash contract: every live actuator back at its static
        # default (read the engine, not the controller's claim).
        reverted = (
            eng.max_delay_s == coalesce_base_s
            and eng.max_queued == max_queued
            and eng._tier_quotas == static_quotas
            and eng.bucket_bias == 0)
        if ctl is not None:
            ctl.stop()
        for _ in pool:
            tasks.put(_STOP)
        for t in pool:
            t.join(timeout=10.0)
        srv.drain(timeout_s=drain_timeout_s)

        events = tr.snapshot()["events"]
        n_ctl_events = sum(1 for e in events if e[2] == "control")
        n_revert_events = sum(
            1 for e in events if e[2] == "control_revert")
        acc = tr.accounting()

        by_tier = {0: {}, 1: {}}
        retry_after_seen = {0: set(), 1: set()}
        with rec_lock:
            for (tier, _, _, out, ra) in records:
                k = 0 if tier <= 0 else 1
                by_tier[k][out] = by_tier[k].get(out, 0) + 1
                if ra is not None:
                    retry_after_seen[k].add(int(ra))
        t0_total = sum(by_tier[0].values())
        t0_ok = by_tier[0].get("ok", 0)
        unresolved = sum(t.get("unresolved", 0)
                         for t in by_tier.values())
        leg = {
            "name": name,
            "controlled": bool(controlled),
            "submitted": int(submitted),
            "resolved": int(len(records)),
            "drained": bool(drained),
            "unresolved": int(unresolved),
            "by_tier": {str(k): dict(sorted(v.items()))
                        for k, v in by_tier.items()},
            "tier0_goodput": float(
                f"{(t0_ok / t0_total) if t0_total else 1.0:.4g}"),
            "tier0_ok": int(t0_ok),
            "tier0_total": int(t0_total),
            "tier1_ok": int(by_tier[1].get("ok", 0)),
            "tier1_total": int(sum(by_tier[1].values())),
            "retry_after_seen": {
                str(k): sorted(v) for k, v in
                retry_after_seen.items()},
            "steady_recompiles": int(
                eng.counters.compiles - compiles_warm),
            "wall_s": float(f"{wall:.4g}"),
            "control": {
                "ticks": int(ctl_block["ticks"]),
                "actuations": int(ctl_block["actuations"]),
                "reverts": int(ctl_block["reverts"]),
                "crashed": bool(ctl_block["crashed"]),
            },
            "control_events": int(n_ctl_events),
            "control_revert_events": int(n_revert_events),
            "actuations_evented": bool(
                n_ctl_events == ctl_block["actuations"]),
            "reverted_to_static": bool(reverted),
            "slo_burn_rates": {
                t: rep.get("burn_rates", {})
                for t, rep in slo.get("tiers", {}).items()},
            "span_accounting": acc,
            "spans_closed_exactly_once": bool(
                acc["spans_started"] == acc["spans_closed"]
                and acc["spans_open"] == 0),
        }
        if crash_at_s is not None:
            leg["crash_injected"] = bool(crash_fired.is_set())
        log(f"control: leg {name}: tier0 goodput "
            f"{leg['tier0_goodput']:.3f} ({t0_ok}/{t0_total}), tier1 "
            f"served {leg['tier1_ok']}/{leg['tier1_total']}, "
            f"{ctl_block['actuations']} actuations "
            f"({n_ctl_events} evented), steady recompiles "
            f"{leg['steady_recompiles']}, unresolved {unresolved}")
        return leg

    # ---- Paired legs, interleaved ------------------------------------
    legs = []
    for p in range(pairs):
        legs.append(leg_run(f"static_{p}", controlled=False))
        legs.append(leg_run(f"controlled_{p}", controlled=True))
    crash_leg = leg_run(
        "crash", controlled=True,
        crash_at_s=crash_at_fraction * trace_duration_s)

    stat = [l for l in legs if not l["controlled"]]
    ctrl = [l for l in legs if l["controlled"]]

    def pooled_goodput(ls):
        ok = sum(l["tier0_ok"] for l in ls)
        total = sum(l["tier0_total"] for l in ls)
        return float(f"{(ok / total) if total else 1.0:.4g}")

    out = {
        "control_drill_schema": 1,
        "trace": {
            "kind": trace_kind,
            "seed": int(trace_seed),
            "duration_s": float(trace_duration_s),
            "base_hz": float(f"{base_hz:.4g}"),
            "peak_hz": float(f"{peak_hz:.4g}"),
            "tier0_fraction": float(tier0_fraction),
            "sha256": hashlib.sha256(trace_bytes).hexdigest(),
            "stats": stats,
        },
        "service_rate_per_sec": float(f"{service_rate:.4g}"),
        "pairs": int(pairs),
        "legs": legs,
        "crash_leg": crash_leg,
        "static_tier0_goodput": pooled_goodput(stat),
        "controlled_tier0_goodput": pooled_goodput(ctrl),
        "static_tier1_served": int(sum(l["tier1_ok"] for l in stat)),
        "controlled_tier1_served": int(
            sum(l["tier1_ok"] for l in ctrl)),
        "static_tier1_served_per_sec": float(f"""{(
            sum(l["tier1_ok"] for l in stat)
            / max(1e-9, sum(l["wall_s"] for l in stat))):.4g}"""),
        "controlled_tier1_served_per_sec": float(f"""{(
            sum(l["tier1_ok"] for l in ctrl)
            / max(1e-9, sum(l["wall_s"] for l in ctrl))):.4g}"""),
        "steady_recompiles_total": int(
            sum(l["steady_recompiles"] for l in legs + [crash_leg])),
        "unresolved_total": int(
            sum(l["unresolved"] for l in legs + [crash_leg])),
        "actuations_total": int(
            sum(l["control"]["actuations"] for l in ctrl)),
        "actuations_evented": bool(
            all(l["actuations_evented"] for l in ctrl + [crash_leg])),
        "spans_closed_exactly_once": bool(
            all(l["spans_closed_exactly_once"]
                for l in legs + [crash_leg])),
    }
    return out
