"""Tiered subject store: page O(100k) subjects through device/host/disk.

The engine's device-resident ``SubjectTable`` (PR 4) is the HOT tier and
stays the single source of truth for what a dispatch gathers from; this
module adds the two tiers underneath it plus the shard map that turns
PR-13's per-lane replicas into disjoint shards:

* **warm** — evicted rows land as host ``numpy`` copies in a bounded
  LRU (``warm_capacity``); a later dispatch PROMOTES the row back with
  ``jax.device_put`` instead of re-running the shape stage.  Promotion
  is started asynchronously at coalesce-admit / ``open_stream`` time
  (``prefetch``), so the transfer hides inside the coalesce window and
  the install path only pays the residual ``block_until_ready`` stall —
  which is exactly what ``subject_store_promotion_ms`` measures.
* **cold** — warm-LRU overflow pages rows to disk through
  ``io/orbax_ckpt.py`` row pages (one directory per subject digest,
  content-hashed).  A damaged page NEVER errors a request: the load
  degrades to a counted re-bake (``subject_store_cold_damage``), the
  PR-6 damage contract applied to paging.
* **shards** — ``shard_of(digest, n)`` is the pure content-based
  subject→lane placement used when ``sharded=True``: lane *k* keeps
  rows only for shard *k* in a shard-local table (lanes.py), so N lanes
  hold N DISJOINT slices instead of N full replicas — the per-lane
  device footprint drops by ~N at equal subject count.

Locking: the store's ``_lock`` is LEAF-LEVEL — it is acquired with no
engine lock held by the store itself, never acquires any other lock
inside, and no device work runs under it (transfers are staged outside,
like every device op on the engine's install path).  Counters live on
the engine's ``ServingCounters`` (bound at attach), which has its own
leaf lock.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

import numpy as np

# The baked row's arrays, exactly the checkpoint schema of
# engine.checkpoint_subjects: "shape" IS the digest preimage (the
# dtype-normalized betas specialize hashed), so a cold page is
# self-verifying without a sidecar.
ROW_KEYS = ("v_shaped", "joints", "shape")


def subject_digest(betas: np.ndarray) -> str:
    """The engine's subject key for a NORMALIZED betas array (must stay
    in lockstep with ``ServingEngine.specialize``'s hashing)."""
    return hashlib.sha256(
        np.ascontiguousarray(betas).tobytes()).hexdigest()[:16]


def shard_of(digest: str, n_shards: int) -> int:
    """Content-based subject→shard placement: stable across restarts,
    independent of registration order, uniform over sha256 prefixes."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    return int(digest[:8], 16) % n_shards


@dataclass
class SubjectStoreConfig:
    """Tier sizing for one :class:`SubjectStore`.

    ``warm_capacity``: max rows held as host copies (LRU beyond it
    pages to ``cold_dir`` when set, else the row is dropped and the
    next access re-bakes).  ``cold_dir``: row-page directory (None =
    no cold tier).  ``sharded``: lanes hold disjoint shard tables
    instead of full replicas.  ``backend``: cold-page serialization
    override, forwarded to ``io.orbax_ckpt`` ("orbax" | "pickle" |
    None = auto)."""

    warm_capacity: int = 1024
    cold_dir: Optional[str] = None
    sharded: bool = False
    backend: Optional[str] = None

    def __post_init__(self):
        if self.warm_capacity < 1:
            raise ValueError(
                f"warm_capacity must be >= 1, got {self.warm_capacity}")


class SubjectStore:
    """The warm/cold tiers + shard map under one serving engine.

    One store binds to ONE engine (``ServingEngine(subject_store=...)``
    calls :meth:`bind`); all mutation happens on engine threads
    (dispatcher / installers / stream opens), under the store's own
    leaf lock.
    """

    def __init__(self, config: Optional[SubjectStoreConfig] = None, **kw):
        self.config = config if config is not None else SubjectStoreConfig(
            **kw)
        self._lock = threading.Lock()
        self._warm: "OrderedDict[str, dict]" = OrderedDict()
        # digest -> (handles dict, t_started) for an in-flight async
        # promotion; consumed (popped) by fetch_row on the install path.
        self._promotions: dict = {}
        self._cold_index: set = set()
        self._counters = None
        self._n_shards: Optional[int] = None
        # Shard-rebalance overlay (PR 20): dead shard -> tuple of
        # surviving shard indices adopting its subjects.  ``shard_for``
        # remaps through it, so the ENTIRE pipeline (admit grouping,
        # dispatcher shard tags, lane placement, sharded resolve) agrees
        # on the new owner the instant the overlay lands — no per-call
        # coordination.  Values are immutable tuples swapped whole;
        # readers take no lock (the hot-path placement lookup).
        self._reassigned: dict = {}
        if self.config.cold_dir is not None:
            # Adopt pages a previous process left behind: paging is a
            # persistence layer, not per-process scratch.
            from mano_hand_tpu.io import orbax_ckpt

            self._cold_index.update(
                orbax_ckpt.list_row_pages(self.config.cold_dir))

    # ------------------------------------------------------------- attach
    def bind(self, counters, n_shards: Optional[int] = None) -> None:
        """Attach to an engine's counters (and lane count when sharded).
        Binding twice to different engines is a wiring bug."""
        with self._lock:
            if self._counters is not None and self._counters is not counters:
                raise RuntimeError(
                    "SubjectStore is already bound to another engine")
            self._counters = counters
            if n_shards is not None:
                self._n_shards = int(n_shards)

    @property
    def sharded(self) -> bool:
        return self.config.sharded

    @property
    def n_shards(self) -> Optional[int]:
        return self._n_shards

    def shard_for(self, digest: str) -> Optional[int]:
        """The EFFECTIVE owning shard of one subject digest, or None
        when the store is unsharded / not yet bound to a lane count.
        A shard reassigned on lane loss (:meth:`reassign_shard`) maps
        its subjects onto the survivors by a second content hash, so
        the dead shard's load spreads deterministically instead of
        piling onto one adopter."""
        n = self._n_shards
        if not self.config.sharded or not n:
            return None
        s = shard_of(digest, n)
        survivors = self._reassigned.get(s)
        if survivors is None:
            return s
        return survivors[int(digest[:8], 16) % len(survivors)]

    def reassign_shard(self, dead: int, survivors) -> bool:
        """Route a dead shard's subjects onto ``survivors`` (PR 20 lane
        loss).  Idempotent: a shard already reassigned is left alone
        (False).  Survivors must be live shard indices — in range, not
        the dead shard, and not themselves reassigned; a reassignment
        chain would make ownership depend on overlay-install order."""
        n = self._n_shards
        if not self.config.sharded or not n:
            raise RuntimeError("reassign_shard on an unsharded store")
        if not 0 <= dead < n:
            raise ValueError(f"dead shard {dead} out of range [0, {n})")
        surv = tuple(sorted(set(int(s) for s in survivors)))
        if not surv:
            raise ValueError("reassign_shard needs >= 1 survivor")
        with self._lock:
            if dead in self._reassigned:
                return False
            for s in surv:
                if not 0 <= s < n or s == dead or s in self._reassigned:
                    raise ValueError(
                        f"survivor shard {s} is not live (range [0, {n}), "
                        f"dead={dead}, reassigned="
                        f"{sorted(self._reassigned)})")
            self._reassigned[dead] = surv
        return True

    def restore_shard(self, dead: int) -> bool:
        """Undo :meth:`reassign_shard` once the lane is back (the
        failback mirror); returns whether an overlay was removed."""
        with self._lock:
            return self._reassigned.pop(dead, None) is not None

    # ------------------------------------------------------------ prefetch
    def prefetch(self, digest: str) -> bool:
        """Start an ASYNC host→device promotion for a warm row; returns
        whether a transfer was started.  Called at coalesce-admit and
        ``open_stream`` — the points where a dispatch is known to be
        coming — so the copy overlaps the coalesce window.  A digest
        that is hot, cold-only, or unknown is a cheap no-op (the install
        path handles those tiers itself)."""
        with self._lock:
            if digest in self._promotions:
                return False
            row = self._warm.get(digest)
        if row is None:
            return False
        import jax

        # Device work OUTSIDE the lock; jax.device_put returns with the
        # transfer in flight — that asynchrony IS the prefetch.
        handles = {k: jax.device_put(v) for k, v in row.items()}
        with self._lock:
            # A racing prefetch of the same digest put the same bytes;
            # last writer wins harmlessly.
            self._promotions[digest] = (handles, time.perf_counter())
        if self._counters is not None:
            self._counters.count_store_prefetch()
        return True

    # --------------------------------------------------------------- fetch
    def fetch_row(self, digest: str):
        """Resolve one digest from the warm or cold tier for an install;
        returns ``(row_arrays, tier)`` with the arrays device-resident
        and ready, or None on a miss (caller re-bakes, counting the
        miss).  The measured stall — everything this call waited on —
        lands in the promotion-latency reservoir; a prefetched row's
        stall is only the residual ``block_until_ready``, which is the
        whole point."""
        import jax

        t0 = time.perf_counter()
        with self._lock:
            prom = self._promotions.pop(digest, None)
            row = self._warm.get(digest)
            if row is not None:
                self._warm.move_to_end(digest)
        if prom is not None:
            handles, _t_started = prom
            jax.block_until_ready(list(handles.values()))
            self._record(t0, "warm")
            return handles, "warm"
        if row is not None:
            # Warm hit without a prefetch: the stall is the full
            # synchronous transfer — honestly measured as such.
            handles = {k: jax.device_put(v) for k, v in row.items()}
            jax.block_until_ready(list(handles.values()))
            self._record(t0, "warm")
            return handles, "warm"
        row = self._load_cold(digest)
        if row is None:
            return None
        victims = []
        with self._lock:
            # Cold rows promote THROUGH warm (inclusive tiers): the next
            # eviction of this subject demotes for free.
            self._warm[digest] = row
            self._warm.move_to_end(digest)
            while len(self._warm) > self.config.warm_capacity:
                victims.append(self._warm.popitem(last=False))
        self._page_out(victims)
        handles = {k: jax.device_put(v) for k, v in row.items()}
        jax.block_until_ready(list(handles.values()))
        self._record(t0, "cold")
        return handles, "cold"

    def _record(self, t0: float, tier: str) -> None:
        c = self._counters
        if c is None:
            return
        if tier == "warm":
            # The promotion-latency quantile measures the WARM
            # host->device stall only — the thing prefetch exists to
            # hide inside the coalesce window (the drill's p99
            # criterion). Cold paging is disk-bound by design and
            # observable through its own hit counter; folding it in
            # would drown the signal the quantile judges.
            c.record_promotion_stall(time.perf_counter() - t0)
            c.count_store_warm()
        else:
            c.count_store_cold()
        c.count_store_promotion()

    # -------------------------------------------------------------- demote
    def demote(self, digest: str, row) -> None:
        """Insert one evicted subject's row into the warm tier.  The
        caller passes the row's arrays (device or host); the D2H copy
        happens HERE, outside every lock — callers must not hold engine
        locks (the engine calls this after releasing ``_install_lock``).
        Warm overflow pages the LRU victim to the cold tier."""
        host = {k: np.asarray(row[k]) for k in ROW_KEYS}
        victims = []
        with self._lock:
            self._warm[digest] = host
            self._warm.move_to_end(digest)
            while len(self._warm) > self.config.warm_capacity:
                victims.append(self._warm.popitem(last=False))
            self._promotions.pop(digest, None)
        if self._counters is not None:
            self._counters.count_store_demotion_warm()
        self._page_out(victims)

    # -------------------------------------------------------------- resize
    def resize_warm(self, new_capacity: int) -> dict:
        """Retarget the warm tier's row budget at RUNTIME (PR 18).

        One lock hold flips the capacity and stages out the LRU-first
        victims a shrink strands; paging (disk work) runs after release,
        exactly like ``demote``'s overflow path. Evictions are COUNTED
        (``subject_store_resize_evictions``), never an error — a paged
        victim re-enters through the cold tier, an unpaged one re-bakes
        on next use, both existing degradation contracts. A grow evicts
        nothing; rows refill on demand."""
        new_capacity = int(new_capacity)
        if new_capacity < 1:
            raise ValueError(
                f"warm_capacity must be >= 1, got {new_capacity}")
        victims = []
        with self._lock:
            old = self.config.warm_capacity
            self.config.warm_capacity = new_capacity
            while len(self._warm) > new_capacity:
                victims.append(self._warm.popitem(last=False))
        if victims and self._counters is not None:
            self._counters.count_store_resize_eviction(len(victims))
        self._page_out(victims)
        return {"warm_capacity": new_capacity, "previous": old,
                "evicted": len(victims)}

    # ------------------------------------------------------------ cold tier
    def _page_out(self, victims) -> None:
        for digest, row in victims:
            if self.config.cold_dir is None:
                continue    # no cold tier: the row is gone; next
                # access is a counted miss → re-bake.
            with self._lock:
                present = digest in self._cold_index
            if present:
                # Content-addressed: a verified page for this digest
                # IS this row — re-writing identical bytes buys
                # nothing (rows promoted THROUGH warm cycle often).
                continue
            from mano_hand_tpu.io import orbax_ckpt

            orbax_ckpt.save_row_page(digest, row, self.config.cold_dir,
                                     backend=self.config.backend)
            with self._lock:
                self._cold_index.add(digest)
            if self._counters is not None:
                self._counters.count_store_demotion_cold()

    def _load_cold(self, digest: str):
        """Load + verify one cold page; None on miss OR damage (damage
        is counted and degrades to a re-bake, never an error)."""
        if self.config.cold_dir is None:
            return None
        with self._lock:
            known = digest in self._cold_index
        if not known:
            return None
        from mano_hand_tpu.io import orbax_ckpt

        try:
            meta, arrays = orbax_ckpt.load_row_page(
                digest, self.config.cold_dir)
            row = {k: np.asarray(arrays[k]) for k in ROW_KEYS}
            # Self-verification: "shape" is the digest preimage, and
            # every array must match the hash recorded at save time.
            if subject_digest(row["shape"]) != digest:
                raise ValueError("betas digest mismatch")
            want = meta.get("row_sha256") or {}
            for k in ROW_KEYS:
                got = hashlib.sha256(
                    np.ascontiguousarray(row[k]).tobytes()).hexdigest()
                if want.get(k) != got:
                    raise ValueError(f"row hash mismatch on {k!r}")
        except Exception:
            # Drop the damaged page from the index so one bad file
            # costs one re-bake, not one per access.
            with self._lock:
                self._cold_index.discard(digest)
            if self._counters is not None:
                self._counters.count_store_cold_damage()
            return None
        return row

    def cold_page_path(self, digest: str) -> Optional[Path]:
        """Where one digest's cold page lives (for drills/tests that
        inject damage); None when no cold tier is configured."""
        if self.config.cold_dir is None:
            return None
        from mano_hand_tpu.io import orbax_ckpt

        return orbax_ckpt.row_page_path(digest, self.config.cold_dir)

    def cold_digests(self) -> list:
        with self._lock:
            return sorted(self._cold_index)

    def warm_digests(self) -> list:
        with self._lock:
            return list(self._warm)

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> dict:
        """One-lock-hold tier occupancy (the torn-telemetry rule): every
        field read under a single hold of the store lock."""
        with self._lock:
            return {
                "warm_rows": len(self._warm),
                "warm_capacity": self.config.warm_capacity,
                "promotions_pending": len(self._promotions),
                "cold_pages": len(self._cold_index),
                "cold_dir": (None if self.config.cold_dir is None
                             else str(self.config.cold_dir)),
                "sharded": self.config.sharded,
                "shards": self._n_shards,
                "reassigned_shards": {
                    str(d): list(s)
                    for d, s in sorted(self._reassigned.items())},
            }
