"""Dynamic micro-batching engine over the compiled MANO forward.

The ROADMAP's serving story made concrete: many independent small
forward requests (per-frame trackers, per-user inference calls) arrive
with ragged batch sizes; dispatching each as-is retraces/recompiles per
novel shape — minutes of dead time per shape on the tunneled chip — and
under-fills the device. This engine:

* **coalesces** pending requests into one batch per dispatch, padding to
  the nearest power-of-two bucket (serving/buckets.py) and masking the
  pad rows back out, so the whole request universe compiles into
  ``log2(max_bucket)`` programs;
* **caches executables per bucket** — an in-memory table backed by an
  optional persistent AOT artifact directory (io/export_aot.py): a cold
  process re-loads a warm bucket's serialized StableHLO instead of
  re-tracing it (the XLA backend compile of the artifact is further
  absorbed by jax's persistent compilation cache when enabled);
* **overlaps host and device** with a pipelined dispatch path (PR 17):
  at ``inflight_depth > 1`` the dispatcher hands each launched batch to
  a bounded FIFO COMPLETION STAGE (``_CompletionStage``: dispatch/
  readback, deadline re-check, future resolution, span close on its own
  worker) and assembles batch N+1 while batch N executes — supervised
  and unsupervised alike; batch inputs are written into pre-allocated
  staging slabs at coalesce-admit time so launch stops re-stacking
  arrays on the critical path; and the coalesce window adapts (shrinks
  as backlog rises) so waiting for stragglers only pays when the device
  would otherwise idle. ``inflight_depth=1`` is the serial
  assemble->launch->block->resolve cycle, byte-for-byte in telemetry
  shape — the drill's baseline (serving/measure.py:
  dispatch_pipeline_drill_run proves pipelined results bit-identical);
* **donates** the steady-state input buffers (``donate_argnums`` on the
  per-bucket jit) so XLA may reuse them for outputs — meaningful on
  device backends; auto-disabled on CPU, where donation is unimplemented
  and only warns.

Everything except absolute throughput is verifiable on the CPU backend:
recompile counts, padding waste, pad-mask bit-exactness, and the AOT
round-trip are all pinned in tests/test_serving.py.

Tunnel caveat (CLAUDE.md): a tunnel drop mid-dispatch hangs the
dispatcher thread inside a C-level PJRT RPC that neither signals nor
``stop()``'s join can interrupt — SIGTERM handlers need the main
thread between bytecodes, so only SIGKILL (from OUTSIDE the process)
truly clears one. PR 3's answer is layered: pass a
``runtime.DispatchPolicy`` and every device call runs SUPERVISED — a
per-batch deadline on a disposable worker thread (the wedged RPC is
abandoned, the batch retried or failed over), bounded classified
retries with backoff + jitter, a circuit breaker
(``runtime.health.CircuitBreaker``: healthy -> degraded -> down, with
killable-subprocess re-probe) gating **graceful degradation to
CPU-bucketed executables** and recompile-free failback; and
``stop(timeout_s=...)`` resolves EVERY in-flight and queued future
with a structured ``ServingError`` even when the dispatcher itself is
wedged. Fault modes are reproducible on CPU via
``runtime.chaos.ChaosPlan`` (the policy's ``chaos`` field wraps the
PRIMARY executables only). Process-level escalation (the true
``kill -9``) still belongs to an external supervisor — the
`serve-bench` CLI arms the unified ``runtime.supervise.Watchdog``;
bench.py rides under its own instance of the same class.

* **specializes per subject** (the shape-split cache, PR 2): dominant
  production streams hold betas fixed per subject for thousands of
  calls, so ``specialize(betas)`` bakes the shape stage ONCE
  (models/core.py:specialize) and ``submit(pose, subject=key)`` runs a
  pose-only program thereafter — steady-state per-subject traffic
  composes both caches with zero recompiles (counted, not hoped:
  ``ServingCounters``);

* **coalesces ACROSS subjects** (PR 4): every baked subject lives in a
  device-resident ``models.core.SubjectTable`` row, and the pose-only
  per-bucket executables are GATHERED programs
  (core.forward_posed_gather) taking the table plus an int32 [B]
  subject index as runtime arguments — the subject is a per-row index,
  not a per-batch executable constant, so a realistic multi-tenant
  stream (many users, each their own betas) merges into one dispatch
  per bucket instead of degenerating into single-request batches.
  Results stay bit-identical to the per-subject posed program at the
  same bucket size (the shared basis leaves stay unbatched inside the
  gather — see core.forward_posed_gather). Table capacity grows by
  DOUBLING (gathered programs recompile ``O(log subjects)`` times,
  counted), and above ``max_subjects`` the least-recently-used subject
  is EVICTED — a row rewrite, never a recompile (the table is a
  runtime argument; ``specializations_evicted`` counts it), with the
  raw betas retained so an evicted subject re-bakes transparently on
  its next dispatch. Full-path and pose-only requests still never
  share a batch; ``_pending`` parks requests for a genuine bucket
  overflow (``coalesce_overflows``), that kind split, or — rarely —
  when one batch would otherwise span more distinct subjects than
  ``max_subjects`` table rows (which ``_resolve_batch`` could never
  pin at once).

* **survives too much traffic** (PR 5): serving millions of users means
  the arrival rate WILL exceed device throughput sometimes, and an
  unbounded queue turns that into unbounded backlog and unbounded
  latency for everyone. The overload layer is three rules, all enforced
  before chip time is spent: **bounded admission** (``max_queued`` +
  per-tier quotas) sheds at ``submit`` with a structured
  ``ServingError(kind="shed")`` in O(µs); **per-request deadlines**
  (``submit(deadline_s=...)``) ride the request through coalescing,
  parking, eviction re-bake, and failover, and are swept BEFORE
  dispatch at every boundary (queue head, coalesce, launch, failover) —
  chip time is never spent on a result nobody will read, and a result
  that arrives late resolves as ``kind="expired"`` rather than
  pretending to be fresh; **priority classes**
  (``submit(priority=...)``) shed batch tiers first (tier quotas
  reserve headroom for tier 0) and parked tier-0 requests lead every
  next batch, so interactive traffic cannot starve. ``load()`` is the
  backpressure signal callers poll to back off BEFORE the hard shed.
  The guarantee is measured, not asserted:
  serving/measure.py:overload_drill_run drives 4x sustained saturation
  and bench_report judges resolution-within-budget, tier-0 goodput,
  and zero steady-state recompiles.

* **narrates itself** (PR 8): pass an ``obs.Tracer`` and every request
  carries a SPAN — stamped at each boundary the engine already sweeps
  deadlines at (submit -> coalesce/park -> launch -> dispatched ->
  readback -> resolve) and closed EXACTLY ONCE at the future's terminal
  kind (ok/shed/expired/error/shutdown), at the same sites that resolve
  the future, so "every future resolves" and "every span closes" are
  the same guarantee. Runtime events (chaos faults, breaker
  transitions, deadline kills, failovers, evictions, lattice loads,
  compiles) land on the same timeline; incidents trigger the flight
  recorder (obs/recorder.py). ``load()`` grows per-tier latency
  quantiles + backlog age from the tracer. The disabled path
  (``tracer=None``, the default) adds zero calls; the enabled path
  costs <= 3% end-to-end, measured by bench config12's paired
  interleaved criterion — tracing must never change WHAT it measures.

* **dispatches across a device fleet** (PR 13): pass ``lanes=N`` and
  the coalesced batches fan out over N per-device dispatch lanes
  (serving/lanes.py) — least-backlogged healthy lane wins, the
  SubjectTable is replicated per lane with recompile-free row-write
  broadcasts, and the PR-3 circuit breaker generalizes into a failover
  LADDER: device -> least-loaded healthy sibling lane -> CPU tier,
  with recompile-free failback when a lane's breaker re-probes
  healthy (outage-length-aware exponential backoff, runtime/health.py)
  — one bad chip degrades capacity instead of the service.
  ``load()["lanes"]`` is the per-lane telemetry block; the lane-loss
  chaos drill (bench config16) proves 100% of futures resolve through
  a lane killed mid-stream. A caller can also WITHDRAW a request:
  ``future.cancel()`` frees the admission slot and closes the span as
  terminal kind ``cancelled`` before any deadline sweep would
  (counted per tier).

* **survives its own death** (PR 6): restart is just another fault
  class. ``bake_lattice()`` pre-bakes EVERY reachable program —
  (bucket x kind {full, gathered pose-only} x table capacity x
  platform, plus the PR-3 CPU-failover tier) — as a versioned,
  checksummed artifact lattice keyed by ``params_digest``
  (io/export_aot.py), so a cold process boots with ZERO re-traces
  (``warmup``/``warmup_posed`` report "aot"; ``aot_loads`` proves it);
  ``checkpoint_subjects``/``restore_subjects`` persist the warm
  SubjectTable (rows + betas + LRU order, orbax with pickle fallback),
  so restored subjects serve BIT-identical pose-only results without
  one shape-stage re-bake. Every damage class — truncated, corrupted,
  checksum- or digest-mismatched artifacts, wrong schema version,
  half-written checkpoints — degrades to a counted recompile or
  re-specialize (``aot_load_failures``/structured telemetry), never a
  crash and never a silently-wrong executable. The cold-start drill
  (serving/measure.py:cold_start_drill_run, bench config11) measures
  process-start -> first-served-result and -> p99-stable and enforces
  the zero-compile criterion.

Typical use::

    eng = ServingEngine(params, max_bucket=256, aot_dir="serve_cache/")
    with eng:
        fut = eng.submit(pose_n16x3, shape_n10)   # async
        verts = fut.result()                      # [n, 778, 3]
        verts = eng.forward(pose, shape)          # sync convenience
        subj = eng.specialize(betas)              # bake the shape stage
        verts = eng.forward(pose, subject=subj)   # pose-only fast path
        # Different subjects' submits coalesce into ONE gathered
        # dispatch per bucket (the multi-tenant steady state):
        futs = [eng.submit(p, subject=eng.specialize(b))
                for p, b in zip(user_poses, user_betas)]
    print(eng.counters.snapshot())
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Callable, Optional, Sequence

import numpy as np

from mano_hand_tpu.obs import log as obs_log
from mano_hand_tpu.serving import buckets as bucket_mod
from mano_hand_tpu.utils.profiling import ServingCounters

_SENTINEL = object()

#: Degradation messages route through the obs logger's warning channel
#: (a real ``warnings.warn`` — catchable/assertable, stderr, never
#: stdout; see obs/log.py for the channel split).
_LOG = obs_log.get_logger("serving.engine")


class ServingError(RuntimeError):
    """Structured terminal failure of one serving request.

    The engine's future-resolution guarantee is "a result or a
    ServingError, within the configured deadline" — never a hang. The
    fields tell the caller WHICH guarantee fired:

    * ``kind`` is the overload-aware discriminator (PR 5):
      ``"shed"`` — refused at admission (bounded queue / tier quota;
      an O(µs) bookkeeping decision, no device involved — retry later,
      see ``ServingEngine.load``); ``"expired"`` — the request's own
      ``deadline_s`` passed before a result could be delivered (swept
      without spending chip time wherever possible); ``"error"`` — the
      dispatch itself failed after supervision was exhausted;
      ``"shutdown"`` — ``stop()`` found the request outstanding.
    * ``phase`` names where in the pipeline it fired (``"admission"``,
      ``"coalesce"``, ``"dispatch"``, ``"failover"``, ``"readback"``,
      ``"shutdown"``); ``attempts`` counts primary tries; ``cause`` is
      the last underlying exception, if any.
    """

    def __init__(self, message: str, *, phase: str = "dispatch",
                 kind: Optional[str] = None, attempts: int = 0, cause=None):
        super().__init__(message)
        self.phase = phase
        self.kind = kind if kind is not None else (
            "shutdown" if phase == "shutdown" else "error")
        self.attempts = attempts
        self.cause = cause


def default_donate() -> bool:
    """Donation default: on for device backends, off on CPU (where jax
    leaves donation unimplemented and each call would only warn)."""
    import jax

    return jax.default_backend() != "cpu"


def build_bucket_executable(params_dev, bucket: int, n_joints: int,
                            n_shape: int, dtype, donate: bool):
    """THE per-bucket forward executable — shared by the engine and
    ``MANOModel.forward_bucketed`` so the two paths cannot drift.

    A jax.jit callable (keeps XLA's C++ fast dispatch path — measured
    ~1 ms/batch faster than a ``lowered().compile()`` object driven from
    Python), params as runtime ARGUMENTS (constant-baking changes float
    folding and the results stop being bit-identical to the direct
    path), eagerly warmed with a dummy batch so the compile lands at
    build time, never inside a latency-sensitive dispatch. The caller
    counts the compile.
    """
    import jax

    from mano_hand_tpu.models import core

    jitted = jax.jit(
        lambda q, p, s: core.forward_batched(q, p, s).verts,
        donate_argnums=(1, 2) if donate else (),
    )
    jax.block_until_ready(jitted(
        params_dev,
        np.zeros((bucket, n_joints, 3), dtype),
        np.zeros((bucket, n_shape), dtype),
    ))
    return lambda p, s: jitted(params_dev, p, s)


def build_posed_gather_executable(table_dev, bucket: int, n_joints: int,
                                  dtype, donate: bool):
    """The per-bucket POSE-ONLY executable (gathered, PR 4).

    The SubjectTable and the int32 [B] subject index ride as runtime
    ARGUMENTS — same reasoning as the params above (constant-baking
    changes float folding), with the coalescing payoff on top: ONE
    compiled program per (bucket, table capacity) serves EVERY mixture
    of subjects, so a new subject costs one specialization (a data
    computation) and zero compiles, and requests for DIFFERENT subjects
    share a dispatch. Only the pose buffer is donated; the table is
    reused across the whole steady-state stream (donating it would
    invalidate the buffers other in-flight snapshots read). Eagerly
    warmed with a dummy batch; the caller counts the compile.
    """
    import jax

    from mano_hand_tpu.models import core

    jitted = jax.jit(
        lambda tab, idx, p: core.forward_posed_gather(tab, idx, p).verts,
        donate_argnums=(2,) if donate else (),
    )
    jax.block_until_ready(jitted(
        table_dev, np.zeros((bucket,), np.int32),
        np.zeros((bucket, n_joints, 3), dtype)))
    return jitted


def default_posed_interpret() -> bool:
    """Fused-posed-kernel interpret default: the Pallas TPU kernel
    needs Mosaic (a real chip); every other backend runs it through the
    Pallas interpreter — compiled XLA emulation, slower than the chip
    kernel but numerically the same program (the interpret lane the
    whole PR-10 tier was proven in)."""
    import jax

    return jax.default_backend() not in ("tpu", "axon")


def build_posed_gather_fused_executable(table_dev, bucket: int,
                                        n_joints: int, dtype, donate: bool,
                                        interpret: bool):
    """The per-bucket FUSED gathered pose-only executable (PR 10).

    Same calling convention and runtime-argument contract as
    ``build_posed_gather_executable`` — the SubjectTable and the int32
    [B] index are runtime ARGUMENTS, one compiled kernel per
    (bucket, capacity) serves every subject mixture, only the pose
    buffer is donated — but the program body is the single Pallas
    launch ``core.forward_posed_gather_fused`` (gather + pose blend +
    FK + skin in VMEM, ops/pallas_posed.py). Numerics are within ~1e-5
    of the XLA gathered program, NOT bit-identical, which is why this
    tier never loads from (or bakes into) the PR-6 AOT lattice: the
    lattice's contract is bit-identity with the live jit of the XLA
    family, and a silent family swap across a restart would break it.
    Eagerly warmed; the caller counts the compile.
    """
    import jax

    from mano_hand_tpu.models import core

    jitted = jax.jit(
        lambda tab, idx, p: core.forward_posed_gather_fused(
            tab, idx, p, interpret=interpret),
        donate_argnums=(2,) if donate else (),
    )
    jax.block_until_ready(jitted(
        table_dev, np.zeros((bucket,), np.int32),
        np.zeros((bucket, n_joints, 3), dtype)))
    return jitted


def build_posed_gather_bf16_executable(table_dev, bucket: int,
                                       n_joints: int, dtype, donate: bool,
                                       fused: bool = False,
                                       interpret: bool = False):
    """The per-bucket bf16-TIER gathered pose-only executable (PR 14).

    Same calling convention and runtime-argument contract as
    ``build_posed_gather_executable`` — table + int32 [B] index as
    runtime ARGUMENTS, one compiled program per (bucket, capacity) for
    every subject mixture, only the pose buffer donated — but the
    program body is the bf16-compute/f32-accumulate pose stage
    (``core.forward_posed_gather(compute_dtype=bf16)``, or the fused
    kernel's single-pass bf16 MXU form when ``fused``). Inputs and
    outputs stay f32 (callers never see bf16 arrays — the CPU-failover
    rung and delivery slicing are dtype-oblivious by construction).
    NOT bit-identical to the f32 family (~4e-4 m measured), which is
    why this tier never loads from (or bakes into) the PR-6 AOT
    lattice and is judged by the sentinel against its PrecisionPolicy
    ENVELOPE, never by f32-digest equality. Eagerly warmed; the caller
    counts the compile.
    """
    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.models import core

    if fused:
        fn = lambda tab, idx, p: core.forward_posed_gather_fused(  # noqa: E731
            tab, idx, p, interpret=interpret,
            compute_dtype=jnp.bfloat16)
    else:
        fn = lambda tab, idx, p: core.forward_posed_gather(  # noqa: E731
            tab, idx, p, compute_dtype=jnp.bfloat16).verts
    jitted = jax.jit(fn, donate_argnums=(2,) if donate else ())
    jax.block_until_ready(jitted(
        table_dev, np.zeros((bucket,), np.int32),
        np.zeros((bucket, n_joints, 3), dtype)))
    return jitted


def build_cpu_fallback_executable(params_host, bucket: int, n_joints: int,
                                  n_shape: int, dtype):
    """The graceful-degradation executable: the SAME program family as
    ``build_bucket_executable`` (params as runtime ARGUMENTS — the
    bit-identity policy, so failover results match a direct CPU
    bucketed call exactly), pinned to the host CPU backend via
    committed inputs. Never donated (CPU donation is unimplemented)
    and never chaos-wrapped (the fallback is the clean path failover
    is measured against). Eagerly warmed like its siblings.
    """
    import jax

    from mano_hand_tpu.models import core

    cpu = jax.devices("cpu")[0]
    params_cpu = jax.device_put(params_host, cpu)
    jitted = jax.jit(lambda q, p, s: core.forward_batched(q, p, s).verts)

    def put(x):
        return jax.device_put(np.asarray(x), cpu)

    jax.block_until_ready(jitted(
        params_cpu,
        put(np.zeros((bucket, n_joints, 3), dtype)),
        put(np.zeros((bucket, n_shape), dtype)),
    ))
    return lambda p, s: jitted(params_cpu, put(p), put(s))


class _CancellableFuture(Future):
    """The Future ``submit`` hands out, with caller-initiated
    cancellation wired back into the engine (PR 13).

    The engine never calls ``set_running_or_notify_cancel``, so a
    request's future stays PENDING until its terminal resolution — a
    ``cancel()`` before that succeeds, flips the future to CANCELLED
    (``result()`` raises ``CancelledError``), and fires the engine
    hook EXACTLY once: the admission slot frees immediately and the
    span closes as terminal kind ``cancelled``, before the deadline
    sweep would have fired. A queued/parked cancelled request is
    skipped by every dispatch boundary (never batched, never costing
    a device row); one already in flight completes on device but its
    result is discarded at delivery — the same late-result discipline
    as an expired readback. ``cancel()`` after any resolution returns
    False, exactly the stdlib contract.
    """

    def __init__(self, on_cancel: Callable[[], None]):
        super().__init__()
        self._on_cancel = on_cancel
        self._cancel_notified = False

    def cancel(self) -> bool:
        if not super().cancel():
            return False
        hook = None
        # Future's own condition doubles as the once-guard: stdlib
        # cancel() returns True again on an already-cancelled future,
        # but the engine-side bookkeeping must fire exactly once.
        with self._condition:
            if not self._cancel_notified:
                self._cancel_notified = True
                hook = self._on_cancel
        if hook is not None:
            hook()
        return True


class _Request:
    __slots__ = ("pose", "shape", "rows", "squeeze", "subject", "future",
                 "t_submit", "deadline", "tier", "span")

    def __init__(self, pose, shape, rows, squeeze, subject=None,
                 deadline=None, tier=0):
        self.pose = pose
        self.shape = shape          # None on the pose-only (subject) path
        self.rows = rows
        self.squeeze = squeeze
        self.subject = subject      # specialization digest or None (full)
        # A plain Future until ``ServingEngine.submit`` swaps in a
        # _CancellableFuture wired to the engine's cancel bookkeeping —
        # a _Request cannot know its engine at construction, and a
        # hookless cancellable future would silently drop the
        # slot-free/span-close/counter work a cancel() must do.
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline    # absolute time.monotonic() or None
        self.tier = tier            # priority class (0 = interactive)
        self.span = None            # obs.Tracer span id (PR 8) or None


class _Staging:
    """One pre-allocated batch-assembly slab pair (PR 17).

    ``pose``/``shape`` are max-bucket-row arrays the coalesce loop
    writes INCREMENTALLY as each request is admitted, so the launch
    path hands the executable a contiguous ``slab[:bucket]`` view
    instead of re-stacking every member array on the critical path.
    ``finish`` fills the pad region by broadcasting row 0 — the exact
    ``buckets.pad_rows`` rule ("pad rows replay live traffic's
    regime"), so staged batches stay bit-identical to the legacy
    concatenate+pad assembly. A slab is owned by its batch until the
    dispatch has consumed it (under the completion stage: until
    readback), then returns to the engine's pool.
    """

    __slots__ = ("pose", "shape", "rows", "full")

    def __init__(self, pose, shape):
        self.pose = pose            # [max_bucket, J, 3] engine dtype
        self.shape = shape          # [max_bucket, S] engine dtype
        self.rows = 0               # write cursor (== batch rows)
        self.full = False           # full path: shape rows staged too

    def append(self, req: _Request) -> None:
        n = req.rows
        self.pose[self.rows:self.rows + n] = req.pose
        if self.full:
            self.shape[self.rows:self.rows + n] = req.shape
        self.rows += n

    def finish(self, bucket: int):
        """Pad to ``bucket`` (repeat row 0, the pad_rows contract) and
        return the batch's ``(pose, shape)`` views — ``shape`` is None
        on the pose-only path."""
        if self.rows < bucket:
            self.pose[self.rows:bucket] = self.pose[:1]
            if self.full:
                self.shape[self.rows:bucket] = self.shape[:1]
        return (self.pose[:bucket],
                self.shape[:bucket] if self.full else None)


class _CompletionStage:
    """The bounded completion stage of the dispatch pipeline (PR 17):
    a pool of ``depth`` daemon workers that finish launched batches —
    dispatch-or-readback, deadline re-check, future resolution, span
    close — while the dispatcher assembles the next batch.

    ``depth`` bounds launched-but-unresolved batches: ``submit`` blocks
    once ``depth`` batches are in flight, which is the pipeline's
    backpressure — and because the pool holds one worker per in-flight
    slot, up to ``depth`` device round-trips overlap each other (the
    actual pipelining win on the tunnel: concurrent outstanding RPCs
    hide each other's RTT; a single worker was tried first and
    serialized them — docs/roadmap.md PR-17 dead-ends). Resolution
    order is still STRICT FIFO: every batch takes a launch-order
    sequence number at submit and ``_finish_in_order`` holds its
    completed result at a reorder barrier until every predecessor has
    resolved, so delivery order matches launch order exactly as the
    serial loop's did (and per-lane FIFO in lane mode is untouched:
    lanes bypass this stage entirely — each lane worker is already its
    own completion stage).

    Failure contract (mirrors ``_launch``): a ``ServingError`` poisons
    ONLY its batch and the stage keeps completing (a failed batch is
    traffic); any other ``BaseException`` is engine-fatal — the
    failing worker poisons its batch plus everything still queued,
    records the failure, and retires; ``submit``/``drain`` re-raise it
    on the DISPATCHER thread so the normal crash path (poison parked,
    drain cancelled, ``_failure``) owns the shutdown. Workers holding
    a completed batch at the reorder barrier when a peer fails still
    resolve their own batch (its predecessors were poisoned by the
    failing worker, so FIFO over resolved batches holds).

    ``_completion_lock`` is a Condition and the stage's ONE lock — a
    LEAF in the engine's lock order (nothing else is ever taken under
    it), held only around deque/sequence bookkeeping. Device work (the
    dispatch closure, ``np.asarray`` readback) and future resolution
    run OUTSIDE it: the ``device-under-completion-lock`` analysis rule
    (mano_hand_tpu/analysis/policy.py) pins that, the same way the
    ``_exe_lock``/``_install_lock`` rules pin the executable caches.
    """

    def __init__(self, eng: "ServingEngine", depth: int):
        self._eng = eng
        self.depth = int(depth)
        self._completion_lock = threading.Condition()
        self._items: collections.deque = collections.deque()
        self._inflight = 0          # submitted, not yet delivered
        self._next_seq = 0          # launch order, assigned at submit
        self._deliver_seq = 0       # next seq allowed to resolve
        self._closed = False
        self._failure: Optional[BaseException] = None
        self._threads = [
            threading.Thread(
                target=self._worker,
                name=f"mano-serving-completion-{i}", daemon=True)
            for i in range(max(1, self.depth))]
        for t in self._threads:
            t.start()

    def inflight(self) -> int:
        with self._completion_lock:
            return self._inflight

    def submit(self, fn, reqs, rows: int, bucket: int, n_subjects: int,
               staging) -> int:
        """Hand one launched batch to the stage; blocks at ``depth``
        (backpressure). Returns the post-enqueue in-flight count.
        Re-raises a worker engine-fatal failure on the caller (the
        dispatcher), whose crash handler owns it; the caller's batch
        is NOT enqueued then (its ``_launch`` except poisons it)."""
        with self._completion_lock:
            while (self._failure is None and not self._closed
                   and self._inflight >= self.depth):
                self._completion_lock.wait()
            if self._failure is not None:
                raise self._failure
            if self._closed:
                raise ServingError(
                    "completion stage closed during submit (engine "
                    "stopping)", phase="shutdown")
            seq = self._next_seq
            self._next_seq += 1
            self._inflight += 1
            self._items.append((seq, fn, reqs, rows, bucket,
                                n_subjects, staging))
            self._completion_lock.notify_all()
            return self._inflight

    def drain(self) -> None:
        """Block until every submitted batch has resolved (the
        dispatcher's clean-exit barrier). Re-raises a worker
        engine-fatal failure; returns immediately once closed (the
        stop() wedged path abandoned us — ``_sweep_live`` resolves
        whatever the stuck workers still hold)."""
        with self._completion_lock:
            while (self._failure is None and not self._closed
                   and self._inflight > 0):
                self._completion_lock.wait()
            if self._failure is not None:
                raise self._failure

    def close(self, exc: Optional[BaseException] = None) -> None:
        """Retire the pool (idempotent). Queued never-dispatched
        batches are poisoned — with ``exc`` on a dispatcher crash,
        else with the shutdown ServingError — so no future strands
        however the stage ends. A worker wedged INSIDE a batch stays
        abandoned (daemon; only kill -9 clears a hung device RPC), and
        that batch's futures — plus any batch parked behind it at the
        reorder barrier — fall to stop()'s ``_sweep_live``."""
        with self._completion_lock:
            self._closed = True
            leftovers = list(self._items)
            self._items.clear()
            self._completion_lock.notify_all()
        err = exc if exc is not None else ServingError(
            "serving engine stopped before this launched batch "
            "completed", phase="shutdown")
        for it in leftovers:
            self._eng._poison(it[2], err)
            self._eng._staging_release(it[6])

    # Worker side ------------------------------------------------------
    def _worker(self) -> None:
        eng = self._eng
        item = None
        try:
            while True:
                with self._completion_lock:
                    while (not self._items and not self._closed
                           and self._failure is None):
                        self._completion_lock.wait()
                    if self._failure is not None or not self._items:
                        return      # failed, or closed + drained
                    item = self._items.popleft()
                seq, fn, reqs, rows, bucket, n_subjects, staging = item
                try:
                    outcome = self._run_call(fn, reqs, rows, bucket,
                                             n_subjects)
                finally:
                    eng._staging_release(staging)
                self._finish_in_order(seq, outcome, reqs, bucket)
                item = None
        except BaseException as e:  # noqa: BLE001 — engine-fatal class
            # The _launch contract, stage-shaped: poison the batch
            # whose resolution failed AND everything still queued
            # (nothing will ever run it), record the failure for the
            # dispatcher to re-raise, and retire.
            if item is not None:
                eng._poison(item[2], e)
            with self._completion_lock:
                if self._failure is None:
                    self._failure = e
                leftovers = list(self._items)
                self._items.clear()
                self._completion_lock.notify_all()
            for it in leftovers:
                eng._poison(it[2], e)
                eng._staging_release(it[6])

    def _run_call(self, fn, reqs, rows: int, bucket: int,
                  n_subjects: int):
        """The parallel phase: everything about finishing ONE batch
        that does not touch another batch's state — deadline re-check,
        the dispatch closure, the blocking readback. Runs concurrently
        across workers; returns an outcome tag for the in-order
        delivery phase."""
        eng = self._eng
        tr = eng._tracer
        # Deadline re-check across the launch/completion split (PR 5
        # composed with PR 17): the batch waited in the stage queue
        # AFTER its launch-boundary sweep, so re-check NOW — the last
        # instant a sweep still costs zero device time. Only a WHOLLY
        # expired/cancelled batch skips its dispatch (the staged slab
        # cannot drop single rows without re-assembly); a live member
        # keeps the batch, and stragglers expire individually at
        # readback (_deliver). give_up_by needs no re-arming here: it
        # is an absolute monotonic bound (supervise.batch_give_up_by),
        # so stage queue time already counted against it.
        if any(r.deadline is not None or r.future.cancelled()
               for r in reqs):
            now = time.monotonic()
            if all(r.future.cancelled() or eng._is_expired(r, now)
                   for r in reqs):
                return ("presweep", None)
        try:
            out = fn()   # supervised: host array; unsupervised: async
        except ServingError as e:
            # Supervision exhausted for THIS batch — same contract as
            # the serial path: its futures get the structured error
            # and the stage keeps completing (a failed batch is
            # traffic, not an engine invariant breach).
            return ("poison", e)
        eng.counters.count_dispatch(bucket, rows, requests=len(reqs),
                                    subjects=n_subjects)
        if tr is not None:
            for r in reqs:
                tr.event(r.span, "dispatched")
        verts = np.asarray(out)  # blocks until the device batch is done
        return ("ok", verts)

    def _finish_in_order(self, seq: int, outcome, reqs,
                         bucket: int) -> None:
        """The serial phase: hold this completed batch at the reorder
        barrier until every earlier launch has resolved, then resolve
        its futures / close its spans. This is what keeps resolution
        strictly FIFO while the ``_run_call`` phases overlap."""
        eng = self._eng
        with self._completion_lock:
            while self._deliver_seq != seq and self._failure is None:
                self._completion_lock.wait()
        # Resolution runs OUTSIDE the lock (leaf contract). On the
        # failure path the predecessors were poisoned by the failing
        # worker before it set _failure, so resolving this batch now
        # still observes FIFO over resolved batches.
        kind, payload = outcome
        if kind == "presweep":
            for r in reqs:
                if not eng._skip_cancelled(r):
                    eng._expire(r, "dispatch")
            eng.counters.count_pipeline_presweep()
        elif kind == "poison":
            eng._poison(reqs, payload)
        else:
            eng.counters.count_pipeline_completion()
            eng._deliver(reqs, payload, bucket)
        with self._completion_lock:
            self._deliver_seq = seq + 1
            self._inflight -= 1
            self._completion_lock.notify_all()


class ServingEngine:
    """Micro-batching forward server over one parameter set.

    Parameters
    ----------
    params: ManoParams (any float dtype; cast to ``dtype``).
    min_bucket/max_bucket: power-of-two bucket range; requests larger
        than ``max_bucket`` are rejected at ``submit`` (chunk upstream).
    max_delay_s: how long the dispatcher waits to coalesce more requests
        once it holds at least one (the latency/throughput knob).
    adaptive_coalesce: shrink the coalesce window as backlog depth and
        head-of-line age rise (PR 17, ``_coalesce_window``): with a
        backlog that can already fill a batch the wait buys nothing and
        only adds latency, so it collapses toward zero; sparse traffic
        still gets the full ``max_delay_s``. False pins the legacy
        fixed window. Never changes WHICH requests may share a batch —
        results are bit-identical either way.
    aot_dir: directory of persistent AOT artifacts. When it holds a
        baked executable LATTICE (``bake_lattice()``; PR 6) every
        reachable program — full, gathered pose-only per capacity, CPU
        failover — loads at boot with zero re-traces, bit-identical to
        the live jit path (params/table as runtime args), and a
        damaged or digest-mismatched entry degrades to a counted
        recompile (``aot_load_failures``). Otherwise the legacy
        per-bucket full-forward artifacts apply: missing buckets are
        compiled AND exported there; present ones load without
        re-tracing. None = in-memory cache only.
    donate: donate pose/shape buffers to XLA (None = auto: on for
        device backends, off on CPU where donation is unimplemented).
    inflight_depth: the dispatch pipeline's in-flight depth (PR 17):
        how many launched-but-unresolved batches the bounded completion
        stage may hold, and therefore how many device round-trips may
        overlap each other (2 = classic double buffering, the default —
        batch N+1 assembles and dispatches while batch N executes; the
        dispatcher blocks on stage backpressure past the depth;
        resolution stays strict launch-order FIFO via the stage's
        reorder barrier). 1 disables the stage entirely and keeps the
        serial assemble->launch->block->resolve cycle, byte-for-byte in
        telemetry shape — the pipelined-vs-serial drill's baseline.
        Ignored in lane mode (lanes ARE the overlap).
    counters: a shared ServingCounters (e.g. process-global); default a
        private one, exposed as ``self.counters``.
    max_subjects: capacity ceiling of the device-resident subject table.
        Within it, capacity grows by doubling (each growth retraces the
        warm gathered executables once — ``O(log subjects)`` compiles,
        counted); above it, the least-recently-used subject's table row
        is evicted and reused (``specializations_evicted``) — never a
        recompile, because the table is a runtime argument. Evicted
        subjects keep their betas registered and re-bake transparently
        on their next dispatch.
    policy: a ``runtime.DispatchPolicy`` enabling supervised dispatch
        (per-batch deadline, classified retries with backoff, circuit-
        breaker-gated CPU failover, optional chaos injection). None
        (default) keeps the unsupervised fast path: zero supervision
        threads, zero overhead per dispatch — right for directly-
        attached devices. Each supervised batch still resolves to a
        host array inside its own deadline envelope; since PR 17 that
        envelope runs ON the completion stage at ``inflight_depth > 1``,
        so supervision no longer forfeits the host/device overlap —
        batch N+1 assembles while batch N's supervised call runs
        (depth 1 restores the strictly serial pre-PR-17 behavior).
    max_queued: bounded admission (PR 5). None (default) keeps the
        historical unbounded queue; an int caps OUTSTANDING requests
        (submitted, not yet resolved — queued, parked, and in flight),
        and a ``submit`` that would exceed the cap raises a structured
        ``ServingError(kind="shed")`` in O(µs), without touching the
        device or even starting the dispatcher. Shedding at the door is
        the whole defense: a sustained arrival rate above device
        throughput otherwise grows the backlog — and every caller's
        latency — without bound, and a stale interactive pose is
        worthless (PAPER.md §0).
    tier_quotas: per-priority admission thresholds over the SHARED
        outstanding count, e.g. ``{1: 16}``: a tier-``t`` submit is
        shed once outstanding >= its quota. Defaults (requires
        ``max_queued``): tier 0 may fill the whole queue
        (``max_queued``), tiers >= 1 only half — so overload sheds low
        tiers FIRST and the headroom above a low tier's quota is
        reserved for tier-0 (interactive) traffic by construction.
        Quotas are clamped to ``max_queued``.
    busy_fraction: the soft backpressure threshold: ``load()`` reports
        a tier "busy" (try later) once outstanding crosses this
        fraction of its quota, before hard shedding begins.
    posed_kernel: which program family serves the gathered pose-only
        path (PR 10). ``"xla"`` (default) keeps the PR-4 XLA gathered
        program; ``"fused"`` selects the single-launch Pallas kernel
        (``core.forward_posed_gather_fused``: SubjectTable row gather +
        pose blend + FK + skinning in VMEM, ops/pallas_posed.py) — same
        runtime-argument contract (zero per-subject recompiles, one
        program per bucket x capacity), numerics within ~1e-5 of the
        XLA family rather than bit-identical. The fused tier composes
        with supervised dispatch/chaos/failover unchanged (the CPU
        fallback stays the clean bit-identity tier) and is exported to
        the numerics sentinel, but is gated by table capacity: above
        ``pallas_posed.POSED_FUSED_MAX_CAPACITY`` (VMEM residency) the
        engine silently serves the XLA family instead, and it never
        enters the PR-6 AOT lattice (the lattice contract is
        bit-identity with the live XLA jit).
    posed_kernel_interpret: run the fused tier through the Pallas
        interpreter (None = auto: real TPU backends use Mosaic,
        everything else interprets — the CPU lanes/tests/bench-interpret
        path). Ignored under ``posed_kernel="xla"``.
    lanes: per-device dispatch lanes (PR 13, serving/lanes.py). None
        (default) keeps the single-device dispatch path unchanged —
        zero new threads, zero new calls. An int N builds N lanes over
        ``parallel.mesh.lane_devices`` (one per addressable device;
        round-robin oversubscription when N exceeds the device count):
        the dispatcher still coalesces exactly as before, then places
        each assembled batch on the least-backlogged healthy lane;
        the SubjectTable is replicated per lane (row writes broadcast,
        recompile-free); and under a ``policy`` each lane carries its
        OWN circuit breaker with the failover LADDER — device ->
        least-loaded healthy sibling lane -> CPU tier — so one bad
        chip degrades capacity instead of the service, and failback
        after a re-probe is recompile-free (warm per-lane caches).
        ``load()`` gains a one-lock-hold ``"lanes"`` block. Lane
        executables are the same params/table-as-runtime-args program
        families, so lane results stay bit-identical to the
        single-device path on the same platform.
    lane_probe: per-lane breaker probe override — called as
        ``lane_probe(lane_index) -> bool`` (the lane-loss drill's hand
        on each simulated tunnel). Default: the policy breaker's probe
        (a killable-subprocess device probe).
    tracer: an ``obs.Tracer`` (PR 8). None (default) disables tracing
        entirely — zero calls on every path. With a tracer, every
        request carries a span (see the module docstring), runtime
        events ride the same timeline, incidents (deadline kill,
        failover, shed burst) notify the flight recorder, and
        ``load()`` gains per-tier latency quantiles + backlog age.
        When the policy carries a ``CircuitBreaker`` without an
        ``on_transition`` hook, the engine wires breaker state changes
        onto the timeline too.
    precision_policy: a ``serving.precision.PrecisionPolicy`` (PR 14).
        None (default) = every tier f32, byte-for-byte the pre-PR-14
        engine. With a policy, pose-only (subject) requests on the
        named tiers serve a SECOND gathered program family — bf16
        compute with f32 accumulation on the MXU-bound pose-stage
        contractions (``core.forward_posed_gather(compute_dtype=bf16)``
        or the fused kernel's single-pass bf16 form, per the same
        ``posed_kernel``/capacity gate) — under the policy's stated
        vertex-error envelope. Batches are single-precision (a
        mixed-precision coalesce parks the odd request out, the "kind"
        rule's sibling); full-path requests, fitting/batch tiers, the
        CPU-failover rung, and the AOT lattice all stay f32; the bf16
        family warms beside the f32 one (zero steady recompiles on
        both) and is exported to the numerics sentinel, which judges
        it against the ENVELOPE vs the f32 truth — never by f32-digest
        equality.
    """

    def __init__(
        self,
        params,
        *,
        min_bucket: int = 1,
        max_bucket: int = 1024,
        max_delay_s: float = 0.002,
        adaptive_coalesce: bool = True,
        aot_dir=None,
        donate: Optional[bool] = None,
        inflight_depth: int = 2,
        dtype=np.float32,
        counters: Optional[ServingCounters] = None,
        policy=None,
        max_subjects: int = 4096,
        max_queued: Optional[int] = None,
        tier_quotas: Optional[dict] = None,
        busy_fraction: float = 0.75,
        tracer=None,
        posed_kernel: str = "xla",
        posed_kernel_interpret: Optional[bool] = None,
        lanes: Optional[int] = None,
        lane_probe: Optional[Callable[[int], bool]] = None,
        precision_policy=None,
        subject_store=None,
        store_warm_capacity: Optional[int] = None,
    ):
        self._params = params.astype(dtype)
        self._dtype = np.dtype(dtype)
        self.buckets = bucket_mod.bucket_sizes(min_bucket, max_bucket)
        self.max_delay_s = float(max_delay_s)
        self.adaptive_coalesce = bool(adaptive_coalesce)
        self.aot_dir = aot_dir
        if inflight_depth < 1:
            raise ValueError(
                f"inflight_depth must be >= 1, got {inflight_depth}")
        self.inflight_depth = int(inflight_depth)
        if donate is None:
            donate = default_donate()
        self.donate = bool(donate)
        self.counters = counters if counters is not None else ServingCounters()
        self._n_joints = params.n_joints
        self._n_shape = params.n_shape
        self._policy = policy
        if max_subjects < 1:
            raise ValueError(
                f"max_subjects must be >= 1, got {max_subjects}")
        self.max_subjects = int(max_subjects)
        if max_queued is not None and max_queued < 0:
            raise ValueError(
                f"max_queued must be >= 0 (0 sheds everything), got "
                f"{max_queued}")
        self.max_queued = None if max_queued is None else int(max_queued)
        if tier_quotas is not None and self.max_queued is None:
            raise ValueError(
                "tier_quotas require max_queued (quotas are thresholds "
                "over the bounded outstanding count)")
        for t, q in (tier_quotas or {}).items():
            if t < 0 or q < 0:
                raise ValueError(
                    f"tier_quotas entries must be non-negative, got "
                    f"{{{t}: {q}}}")
        self._tier_quotas = dict(tier_quotas or {})
        if not 0.0 < busy_fraction <= 1.0:
            raise ValueError(
                f"busy_fraction must be in (0, 1], got {busy_fraction}")
        self.busy_fraction = float(busy_fraction)
        # Closed-loop control (PR 19): the bucket-ladder selection bias
        # (0 = the classic smallest-fitting rung; N rounds N rungs up,
        # trading pad waste for fewer distinct executables exercised —
        # see set_bucket_bias) and the attached controller's snapshot
        # source (None = no controller; load()["control"] stays a
        # shape-stable empty block, exactly like streams).
        self.bucket_bias = 0
        self._control_source = None
        if posed_kernel not in ("xla", "fused"):
            raise ValueError(
                f"posed_kernel must be 'xla' or 'fused', got "
                f"{posed_kernel!r}")
        self._posed_kernel = posed_kernel
        if precision_policy is not None:
            from mano_hand_tpu.serving.precision import PrecisionPolicy

            if not isinstance(precision_policy, PrecisionPolicy):
                raise TypeError(
                    f"precision_policy must be a "
                    f"serving.precision.PrecisionPolicy, got "
                    f"{type(precision_policy).__name__}")
        self._precision_policy = precision_policy
        # None = resolve lazily at first build (a jax backend query —
        # the engine's constructor touches no backend by design).
        self._posed_interpret = posed_kernel_interpret
        self._tracer = tracer
        if tracer is not None and policy is not None:
            breaker = getattr(policy, "breaker", None)
            if (breaker is not None
                    and getattr(breaker, "on_transition", None) is None):
                # Breaker state changes belong on the request timeline;
                # only an unclaimed hook is taken (a caller-wired hook
                # — e.g. a drill's own — wins).
                breaker.on_transition = (
                    lambda old, new: tracer.runtime_event(
                        "breaker", old=old, new=new))
        self._params_dev = None        # device-resident params (jit path)
        # The executable lattice (PR 6): loaded lazily from aot_dir's
        # manifest (one boot-time JSON read; entries deserialize on
        # first use). None = no lattice (never baked, or degraded at
        # load — counted in aot_load_failures, never a crash).
        self._lattice = None
        self._lattice_loaded = False
        self._lattice_lock = threading.Lock()   # single-flight loader
        self._digest: Optional[str] = None   # params_digest, cached
        self._lat_leaves = None        # device params leaves (lattice call)
        self._lat_leaves_cpu = None    # CPU-pinned leaves (failover tier)
        self._exes: dict = {}          # bucket -> compiled callable
        self._subject_betas: dict = {}  # betas digest -> host [S] array
        #   Never evicted (40 bytes/subject): the CPU fallback re-runs
        #   the FULL forward from raw betas, and an evicted subject
        #   re-bakes its table row from here on its next dispatch.
        # The device-resident subject table (PR 4). Updated ONLY
        # functionally (core.table_set_row/table_grow return new
        # pytrees), so the snapshot a dispatch captures under
        # ``_exe_lock`` stays valid however specialize/evict mutate the
        # live reference afterwards.
        self._table = None             # core.SubjectTable or None
        # Monotonic install counter, bumped under _exe_lock at every
        # table swap (PR 13): lane replicas carry the version of the
        # engine table they derive from, so a lane worker can PROVE its
        # replica agrees with the slots it resolved (an eviction reuses
        # slots — serving a newer replica against older slots would be
        # silently wrong; see lanes.py:_resolve_for_lane).
        self._table_version = 0
        self._subject_slots: dict = {}  # betas digest -> table row
        self._subject_lru = collections.OrderedDict()  # digest -> None
        self._next_slot = 0            # first never-used row
        #   (an eviction reuses the victim's row directly, so the only
        #   allocation states are next-fresh-row, grow, or evict)
        self._gather_exes: dict = {}   # bucket -> (capacity, executable)
        #   (subject-agnostic AND mix-agnostic: table + index are
        #   runtime args; invalidated only by a capacity growth)
        self._gather_exes_bf16: dict = {}  # bucket -> (capacity, exe)
        #   The bf16-TIER gathered family (PR 14): same keying and
        #   invalidation rules as _gather_exes, populated only under a
        #   precision_policy with bf16 tiers. Never lattice-served
        #   (the lattice contract is f32 bit-identity).
        self._cpu_exes: dict = {}      # bucket -> CPU fallback executable
        self._exe_lock = threading.Lock()
        # Serializes _install_subject's bake-and-swap so table mutation
        # device work can stage OUTSIDE _exe_lock (see _install_subject;
        # lock order: _install_lock -> _exe_lock, never the reverse).
        self._install_lock = threading.Lock()
        self._queue: queue.Queue = queue.Queue()
        # Requests parked by _coalesce (bucket overflow, a full-vs-
        # pose-only kind split, or a batch already spanning max_subjects
        # distinct subjects — see _admit): they LEAD the next batches,
        # so a parked request can never starve behind the live queue.
        # Owned by the dispatcher thread; the crash handler sweeps it.
        self._pending: collections.deque = collections.deque()
        # The pipelined completion stage (PR 17): built by the
        # dispatcher loop at entry when ``inflight_depth > 1`` on the
        # single-device path (lanes ARE the overlap in lane mode, and
        # depth 1 keeps the serial assemble->launch->block->resolve
        # cycle byte-for-byte). stop()'s wedged branch reads it to
        # abandon a stuck stage.
        self._completion = None
        # Staged-assembly slab pool (PR 17): pre-allocated max-bucket
        # pose/shape slabs, written incrementally at coalesce-admit
        # time so _launch stops re-stacking request arrays on the
        # critical path. Recycled when the owning batch fully resolves
        # (a slab is live from assembly until its dispatch consumed
        # it, which under the completion stage is after readback).
        self._slab_pool: collections.deque = collections.deque()
        self._slab_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._failure: Optional[BaseException] = None
        # EVERY unresolved request, from submit to future resolution:
        # the shutdown sweep resolves these even when the dispatcher is
        # wedged inside a C-level RPC it will never return from.
        self._live: dict = {}
        self._live_lock = threading.Lock()
        # Streaming sessions (PR 12): the manager is built lazily on
        # the first open_stream (it pulls the fitting stack in), so a
        # stateless-forward engine pays nothing for the subsystem.
        # ``_streams_stopped`` mirrors stop()/start() so a manager
        # built AFTER a stop (or racing one — both sides synchronize
        # on _live_lock) is born refusing registrations: the shutdown
        # contract must hold even when no stream was ever opened.
        self._streams = None
        self._streams_stopped = False
        # Per-device dispatch lanes (PR 13): built lazily at the first
        # warmup/dispatch — lane construction enumerates devices, and
        # the engine's constructor touches no backend by design.
        if lanes is not None and lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        self._lane_count = None if lanes is None else int(lanes)
        self._lane_probe = lane_probe
        if lane_probe is not None and lanes is None:
            raise ValueError("lane_probe requires lanes")
        self._laneset = None
        # Tiered subject store (PR 16): warm/cold tiers + the shard map
        # under the device table. Bound here to this engine's counters
        # (and lane count, when sharded — shards ARE the per-lane
        # tables); the store touches no backend at construction.
        if subject_store is not None:
            from mano_hand_tpu.serving.subject_store import SubjectStore

            if not isinstance(subject_store, SubjectStore):
                raise TypeError(
                    f"subject_store must be a serving.subject_store."
                    f"SubjectStore, got {type(subject_store).__name__}")
            if subject_store.config.sharded and self._lane_count is None:
                raise ValueError(
                    "a sharded subject_store requires lanes (the shards "
                    "are the per-lane tables; pass lanes=N)")
            subject_store.bind(self.counters, n_shards=self._lane_count)
        if store_warm_capacity is not None:
            # Warm-tier budget override (PR 18): applied through the
            # runtime resize AFTER bind, so a shrink against a pre-
            # populated (restored/shared) store evicts LRU-first with
            # counted evictions — same path `mano serve
            # --store-warm-capacity` rides.
            if subject_store is None:
                raise ValueError(
                    "store_warm_capacity requires subject_store (it "
                    "retargets the warm tier's row budget)")
            subject_store.resize_warm(int(store_warm_capacity))
        self._subject_store = subject_store

    @property
    def tracer(self):
        """The engine's ``obs.Tracer`` (or None): the wiring point for
        ``obs.metrics.engine_registry`` and ``obs.NumericsSentinel``."""
        return self._tracer

    @property
    def posed_kernel(self) -> str:
        """The SELECTED gathered-path kernel tier ("xla" | "fused");
        whether the fused tier actually serves also depends on the
        live table capacity — see ``_posed_fused_active``."""
        return self._posed_kernel

    @property
    def precision_policy(self):
        """The engine's ``serving.precision.PrecisionPolicy`` (or
        None = every tier f32, the pre-PR-14 engine exactly)."""
        return self._precision_policy

    @property
    def subject_store(self):
        """The engine's tiered ``serving.subject_store.SubjectStore``
        (or None = device-table-only, the pre-PR-16 engine exactly)."""
        return self._subject_store

    def _shard_of(self, digest: Optional[str]) -> Optional[int]:
        """The owning LANE of one subject digest under a sharded store
        (None on an unsharded/storeless engine) — content-based, so
        placement is stable across restarts and registration order."""
        store = self._subject_store
        if store is None or digest is None:
            return None
        return store.shard_for(digest)

    def _prefetch_subject(self, digest: Optional[str]) -> None:
        """Kick an async warm→device promotion the instant a subject is
        KNOWN to be dispatching soon (coalesce-admit here;
        streams.open_stream calls the same hook): the transfer overlaps
        the coalesce window instead of stalling inside the install.
        Hot or unknown digests are a dict-lookup no-op."""
        store = self._subject_store
        if store is None or digest is None:
            return
        with self._exe_lock:
            hot = digest in self._subject_slots
        if not hot:
            store.prefetch(digest)

    def _req_prec(self, req: "_Request") -> str:
        """The precision family ONE request's dispatch serves from:
        ``"bf16"`` only for a pose-only (subject) request whose tier
        the policy names — full-path requests, and every request on a
        policy-less engine, are f32 (the bf16 family exists only
        where the shape stage is pre-baked; serving/precision.py)."""
        if self._precision_policy is None or req.subject is None:
            return "f32"
        return self._precision_policy.dtype_for_tier(req.tier)

    def _bf16_serving(self) -> bool:
        """Whether any tier serves the bf16 gathered family — the
        warm-up / probe-export predicate."""
        return (self._precision_policy is not None
                and bool(self._precision_policy.bf16_tiers))

    def _resolve_posed_interpret(self) -> bool:
        """The fused tier's interpret flag, resolved once (a jax
        backend query — must never run inside ``_exe_lock``)."""
        if self._posed_interpret is None:
            self._posed_interpret = default_posed_interpret()
        return self._posed_interpret

    def _posed_fused_active(self, capacity: Optional[int]) -> bool:
        """Whether the fused kernel serves the gathered path at this
        table capacity — the ONE tier-selection predicate (shared by
        the executable builder and the sentinel export). Above the
        kernel's VMEM residency budget the XLA family serves instead;
        the flip is a capacity growth, i.e. warm-up-class work, counted
        like every growth recompile."""
        if self._posed_kernel != "fused" or capacity is None:
            return False
        from mano_hand_tpu.ops import pallas_posed

        return pallas_posed.posed_fused_capacity_ok(capacity)

    def numerics_probe_targets(self) -> dict:
        """One consistent read of every LIVE program family — the raw
        material of the numerics sentinel (obs/sentinel.py, PR 9).

        Returns shallow copies of the executable caches (the same
        chaos-wrapped, possibly lattice-loaded callables real
        dispatches use — probing anything else would audit a path the
        engine does not serve from), the current table snapshot, and
        the params handles, all from ONE ``_exe_lock`` hold. The
        sentinel probes only families present here, so it never
        triggers a compile and steady-state stays zero-recompile. The
        device_put of the params handle is staged OUTSIDE the lock
        (the _install_subject rule: no device work under _exe_lock).
        """
        if self._params_dev is None:
            self._params_dev = self._params.device_put()
        # Resolved OUTSIDE the lock (a jax backend query) — the
        # _install_subject rule: no device/backend work under _exe_lock.
        interp = (self._resolve_posed_interpret()
                  if self._posed_kernel == "fused" else False)
        with self._exe_lock:
            cap = self._table.capacity if self._table is not None else None
            return {
                "full": dict(self._exes),
                # Capacity-CONSISTENT entries only: a stale entry (built
                # before a table growth; rebuilt eagerly by
                # _install_subject, but a probe can race that rebuild)
                # may be a FUSED program whose jit would raise on a
                # table past the capacity gate — and would disagree
                # with the gather_fused flag below either way. A probe
                # that finds no current-capacity entry simply skips the
                # family this round (the sentinel's live-families rule).
                "gather": {b: exe for b, (c, exe)
                           in self._gather_exes.items() if c == cap},
                # The bf16 tier (PR 14): same capacity-consistency rule
                # as "gather". Judged by the sentinel against the
                # policy's ENVELOPE vs the f32 truth, never by
                # f32-digest equality (a reduced-precision family can
                # never match an f32 digest).
                "gather_bf16": {b: exe for b, (c, exe)
                                in self._gather_exes_bf16.items()
                                if c == cap},
                # Exported only when some tier actually serves bf16
                # (a policy with empty bf16_tiers builds no bf16
                # family — the sentinel must not derive/judge bf16
                # goldens for a program that can never serve).
                "precision_envelope": (
                    self._precision_policy.max_vertex_err_m
                    if self._bf16_serving() else None),
                "cpu": dict(self._cpu_exes),
                "table": self._table,
                "params": self._params,
                "params_dev": self._params_dev,
                "n_joints": self._n_joints,
                "n_shape": self._n_shape,
                "dtype": self._dtype,
                # PR 10: which family the "gather" callables actually
                # are, so the sentinel derives its clean reference from
                # the SAME trace (fused is not bit-identical to XLA —
                # an XLA reference would read as permanent drift).
                "posed_kernel": self._posed_kernel,
                "gather_fused": self._posed_fused_active(cap),
                "gather_fused_interpret": interp,
            }

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ServingEngine":
        if self._thread is None or not self._thread.is_alive():
            # A fresh dispatcher is a fresh chance: clear a previous
            # crash so the documented stop()/start() restart actually
            # accepts work instead of re-raising the stale failure.
            self._failure = None
            with self._live_lock:
                # The stream manager refuses registrations after a
                # stop() sweep; a restarted engine accepts new
                # sessions again (PR 12).
                self._streams_stopped = False
                mgr = self._streams
            if mgr is not None:
                mgr.reopen()
            self._running = True
            self._thread = threading.Thread(
                target=self._dispatch_loop, name="mano-serving", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout_s: Optional[float] = None) -> None:
        """Drain pending work, stop the dispatcher, resolve EVERY future.

        ``timeout_s`` bounds the join: if the dispatcher does not exit
        in time (wedged inside a device RPC — un-interruptible from
        in-process, see the module docstring), the thread is ABANDONED
        (daemon) and every outstanding future is resolved with a
        structured ``ServingError(phase="shutdown")`` so no caller ever
        blocks forever on a dead engine. Default: a supervised engine
        waits PROGRESS-AWARE — one supervised batch is bounded by the
        policy (deadline x attempts + grace), a queued backlog of them
        is not, so the implicit bound is per-batch windows re-armed as
        long as outstanding futures keep resolving (a draining backlog
        makes progress every window; a wedged RPC cannot make any). An
        unsupervised engine keeps the historical blocking join (its
        dispatch path has nothing that can wedge on CPU).
        """
        with self._live_lock:
            # Streaming sessions (PR 12): mark FIRST, under the same
            # lock the lazy manager build publishes under, so an
            # open_stream racing this stop either sees a swept manager
            # or builds one born stopped — never a live session the
            # one-shot sweep below missed.
            self._streams_stopped = True
            streams_mgr = self._streams
        if streams_mgr is not None:
            # Every still-open session reaches the ``shutdown``
            # terminal (span closed exactly once) BEFORE the future
            # sweeps below, so a session can never outlive the engine
            # that serves its frames — in-flight frames resolve
            # through those sweeps.
            streams_mgr.shutdown()
        if self._thread is None:
            return
        self._running = False
        self._queue.put(_SENTINEL)
        if timeout_s is not None:
            self._thread.join(timeout_s)
        elif self._policy is not None and self._policy.deadline_s:
            per_batch = (self._policy.deadline_s
                         * (self._policy.retries + 2)
                         + self._policy.backoff_cap_s
                         * (self._policy.retries + 1) + 5.0)
            while True:
                with self._live_lock:
                    before = len(self._live)
                self._thread.join(per_batch)
                if not self._thread.is_alive():
                    break
                with self._live_lock:
                    after = len(self._live)
                if after >= before:
                    # A full per-batch window with zero futures resolved
                    # (racing submits can only grow the count): wedged,
                    # not draining.
                    break
        else:
            self._thread.join()
        if self._thread.is_alive():
            err = ServingError(
                "dispatcher wedged in a device call at stop() — thread "
                "abandoned (only an external kill -9 clears a hung "
                "device RPC; see runtime/supervise.py)",
                phase="shutdown")
            self._failure = err
            self._thread = None
            stage = self._completion
            if stage is not None:
                # A batch wedged IN the completion stage (hung device
                # RPC on the stage worker) wedges the dispatcher behind
                # it via backpressure: close the stage so queued
                # batches poison, any blocked submit/drain wakes, and
                # sweep_live below resolves whatever the stuck worker
                # itself still holds. Both threads stay abandoned
                # (daemons) — the kill -9 rule.
                stage.close(err)
                self._completion = None
            if self._laneset is not None:
                # A wedged engine gets a short lane drain: sweep_live
                # below resolves whatever a wedged lane worker holds.
                self._laneset.stop(timeout_s=1.0)
            self._sweep_live(err)
            self._drain_cancelled(err)
            # Parked requests were resolved by the sweep (they are
            # registered); drop the stale objects so a later restart
            # does not re-dispatch already-resolved work.
            self._pending.clear()
            # If the abandoned thread ever unwedges it must find a
            # sentinel (the drain above may have eaten the original)
            # and exit instead of blocking on the empty queue forever.
            self._queue.put(_SENTINEL)
            return
        self._thread = None
        if self._laneset is not None:
            # The dispatcher is drained; let every lane finish its
            # queued batches (sentinel-after-backlog), then poison
            # whatever a wedged lane worker left behind. The final
            # sweep below backstops an abandoned worker's futures.
            self._laneset.stop(timeout_s=timeout_s)
        # A submit racing the shutdown can enqueue AFTER the dispatcher's
        # own drain; nothing will read the queue now, so sweep it again.
        self._drain_cancelled(self._failure)
        # Belt over braces: the registry must be empty here (the
        # dispatcher resolved or poisoned everything it saw) — if a
        # crash path missed one, resolving it late beats a hung caller.
        self._sweep_live(self._failure or ServingError(
            "serving engine stopped before this request was resolved",
            phase="shutdown"))

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- requests
    # Capacity the subject table starts at (clamped to max_subjects):
    # small enough that one-subject engines stay one-subject-sized, big
    # enough that the common few-subject tests/streams never grow.
    _TABLE_INIT_CAPACITY = 8

    def specialize(self, shape) -> str:
        """Bake one subject's betas; returns the subject key for
        ``submit(pose, subject=key)``.

        The per-subject specialization cache (models/core.py:specialize
        made serving-shaped): the first call for a betas value runs the
        shape stage ONCE on device and writes it into a row of the
        device-resident subject table under a content digest; repeats
        are a dict hit (which also refreshes the LRU position). Steady-
        state traffic then composes BOTH caches — this one (shape stage
        baked) and the gathered bucket-executable cache (one compiled
        program per bucket x table capacity, shared across every subject
        MIXTURE) — so a warm stream runs with zero recompiles AND zero
        shape-stage recomputes, observable on ``counters``
        (``specializations``/``shaped_hits``/``table_growths``/
        ``specializations_evicted``).
        """
        shape = np.ascontiguousarray(
            np.asarray(shape, self._dtype).reshape(self._n_shape))
        import hashlib

        key = hashlib.sha256(shape.tobytes()).hexdigest()[:16]
        with self._exe_lock:
            hit = key in self._subject_slots
            if hit:
                self._subject_lru.move_to_end(key)
        if hit:
            self.counters.count_specialize(hit=True)
            return key
        self._install_subject(key, shape)
        return key

    def register_subjects(self, betas_batch) -> list:
        """Register MANY subjects' betas WITHOUT baking a single table
        row — the O(100k) on-ramp of the tiered store (PR 16): raw
        betas cost ~40 bytes/subject (never evicted, exactly like the
        CPU-fallback registry above), while a baked row costs ~10 KB of
        device memory — bulk-baking the registry would defeat the
        tiers. A registered subject is immediately submittable
        (``submit(pose, subject=key)``); its row bakes — or promotes
        from a warm/cold tier — on first dispatch via the existing
        ``_resolve_batch`` re-bake path. Returns the subject keys, in
        input order (duplicates collapse to the same key).
        """
        import hashlib

        betas_batch = np.ascontiguousarray(
            np.asarray(betas_batch, self._dtype).reshape(
                -1, self._n_shape))
        keys = []
        rows = {}
        for b in betas_batch:
            b = np.ascontiguousarray(b)
            key = hashlib.sha256(b.tobytes()).hexdigest()[:16]
            keys.append(key)
            rows[key] = b
        with self._exe_lock:
            for key, b in rows.items():
                self._subject_betas.setdefault(key, b)
        return keys

    def _install_subject(self, key: str, betas: np.ndarray,
                         protected=(), shaped=None) -> int:
        """Bake ``betas`` and write them into a table row; returns the
        slot. ``shaped`` (PR 6) supplies PRE-BAKED rows — the
        checkpoint-restore path: the shape stage is NOT re-run, the
        persisted bytes are written verbatim (bit-identity across the
        restart) and the install counts ``subjects_restored`` instead
        of ``specializations``.
        Grows the table (doubling) while under ``max_subjects``,
        else evicts the least-recently-used subject's row — skipping
        ``protected`` digests (the subjects of the batch being launched,
        so resolving one batch can never evict its own members). Grown
        tables invalidate the warm gathered executables; they are
        rebuilt EAGERLY here (warm-up-class work — a growth compile must
        not land inside a latency-sensitive dispatch), counted like
        every compile. Counts ``specializations`` itself, and only when
        THIS call installed the row — a racing writer's install is that
        writer's count (one bake, one count).

        Locking: ``_install_lock`` serializes installers for the whole
        bake-and-swap, so the functional grow/set_row staged OUTSIDE
        ``_exe_lock`` can never lose a concurrent row write; ``_exe_lock``
        is held only for the dict/slot bookkeeping and the final swap.
        The dispatcher blocks on ``_exe_lock`` for every batch, and on
        the tunneled backend a device call (the row write's first-per-
        capacity trace, or a tunnel hiccup inside it) can stall for
        seconds — it must never sit inside the lock the dispatch path
        needs. Lock order is _install_lock -> _exe_lock, never the
        reverse (_resolve_batch releases _exe_lock before calling here).
        """
        from mano_hand_tpu.models import core

        if self._params_dev is None:
            self._params_dev = self._params.device_put()
        restored = shaped is not None
        store = self._subject_store
        tier = None
        if not restored and store is not None:
            # Tiered resolution (PR 16): a warm/cold row promotes
            # (device_put of persisted bytes — bit-identical, like the
            # checkpoint-restore path below) instead of re-baking; a
            # miss is COUNTED and falls through to the bake. Runs
            # before the install lock: the promotion stall must never
            # serialize other installers.
            fetched = store.fetch_row(key)
            if fetched is not None:
                handles, tier = fetched
                shaped = core.ShapedHand(
                    v_shaped=handles["v_shaped"],
                    joints=handles["joints"],
                    shape=handles["shape"],
                    pose_basis=self._params.pose_basis,
                    lbs_weights=self._params.lbs_weights,
                    parents=self._params.parents,
                )
            else:
                self.counters.count_store_miss()
        if shaped is None:
            shaped = core.jit_specialize(self._params_dev, betas)
        with self._install_lock:
            grew = False
            evicted = None
            victim_table = None
            with self._exe_lock:
                if key in self._subject_slots:     # racing writer won
                    self._subject_lru.move_to_end(key)
                    return self._subject_slots[key]
                self._subject_betas.setdefault(key, betas)
                table = self._table
                cap = (table.capacity if table is not None
                       else min(self._TABLE_INIT_CAPACITY,
                                self.max_subjects))
                if self._next_slot < cap:
                    slot = self._next_slot
                    self._next_slot += 1
                elif cap < self.max_subjects:
                    cap = min(self.max_subjects, cap * 2)
                    grew = True
                    slot = self._next_slot
                    self._next_slot += 1
                else:
                    for victim in self._subject_lru:
                        if victim not in protected:
                            break
                    else:
                        raise RuntimeError(
                            f"one batch references more live subjects "
                            f"than max_subjects={self.max_subjects} "
                            f"table rows")
                    # The victim leaves the maps NOW (an in-between
                    # dispatch sees neither victim nor newcomer — its
                    # row is unreferenced data until the swap below).
                    slot = self._subject_slots.pop(victim)
                    del self._subject_lru[victim]
                    self.counters.count_evict()
                    evicted = victim
                    # The victim's baked row still lives in THIS table
                    # snapshot (functional updates never mutate it);
                    # keep the reference so the demotion below can copy
                    # the row host-side after every lock is released.
                    victim_table = table
            if evicted is not None and self._tracer is not None:
                # Staged outside _exe_lock like the device work below:
                # the dispatch path must never queue behind telemetry.
                self._tracer.runtime_event("evict", subject=evicted,
                                           slot=slot)
            # Device work on a STAGED table, outside _exe_lock (no
            # other writer can interleave: installs are the table's
            # only mutators and _install_lock serializes them).
            if table is None:
                table = core.subject_table(self._params_dev, cap)
            elif grew:
                table = core.table_grow(table, cap)
                self.counters.count_table_growth()
            # The ONE audited exception to device-under-install-lock:
            # this hold EXISTS to stage the functional row write out of
            # _exe_lock (the dispatcher blocks there per batch, never
            # here), and installers are the only waiters.
            # analysis: allow(device-under-install-lock)
            table = core.jit_table_set_row(table, slot, shaped)
            with self._exe_lock:
                self._table = table
                self._table_version += 1
                version = self._table_version
                self._subject_slots[key] = slot
                self._subject_lru[key] = None
                stale = ([b for b, (c, _) in self._gather_exes.items()
                          if c != cap] if grew else [])
                stale_bf16 = ([b for b, (c, _)
                               in self._gather_exes_bf16.items()
                               if c != cap] if grew else [])
            if self._laneset is not None:
                # Replicate the freshly installed row into every lane's
                # table replica (PR 13): one functional row write per
                # lane device — data movement, never a recompile —
                # serialized by the _install_lock this whole method
                # already holds (installs are the table's only
                # mutators), and stamped with the new table version so
                # lane dispatch can prove replica/slot agreement. Still
                # staged OUTSIDE _exe_lock, like every device op here.
                self._laneset.broadcast_row(slot, shaped, grew=grew,
                                            version=version, digest=key)
        if evicted is not None and store is not None:
            # Demotion (PR 16): capture the evicted row into the warm
            # tier from the pre-swap snapshot — outside BOTH locks (the
            # D2H copy happens in the store; the dispatch path and
            # other installers never wait on it). Recompile-free by
            # construction: demotion touches no compiled program.
            row = core.table_row(victim_table, slot)
            store.demote(evicted, {"v_shaped": row.v_shaped,
                                   "joints": row.joints,
                                   "shape": row.shape})
        if restored:
            self.counters.count_restore()
        elif tier is None:
            self.counters.count_specialize(hit=False)
        # (A warm/cold-tier install counted its hit + promotion stall in
        # the store: the shape stage did NOT re-run, so counting it as a
        # specialization would overstate the bakes.)
        for b in stale:
            self._gather_executable(b)
        for b in stale_bf16:
            # The bf16 family's growth rebuild (PR 14): eager for the
            # same reason — a growth compile must never land inside a
            # latency-sensitive bf16 tier-0 dispatch.
            self._gather_executable(b, prec="bf16")
        return slot

    def _resolve_batch(self, reqs):
        """Map a coalesced pose-only batch to (table snapshot, slots),
        re-baking any subject evicted while the requests sat queued.
        The snapshot and the slot list come from ONE locked read, so the
        dispatched program sees a consistent table; a concurrent
        specialize/evict only ever swaps the LIVE reference."""
        digests = {r.subject for r in reqs}
        counted_hot = self._subject_store is None
        for _ in range(len(digests) + 2):
            with self._exe_lock:
                missing = [k for k in digests
                           if k not in self._subject_slots]
                if not counted_hot:
                    # Hot-tier hits (PR 16): batch digests already
                    # table-resident at first resolution — counted once
                    # per batch (the same under-lock counter pattern as
                    # count_evict above).
                    counted_hot = True
                    if len(digests) > len(missing):
                        self.counters.count_store_hot(
                            len(digests) - len(missing))
                if not missing:
                    table = self._table
                    slots = {k: self._subject_slots[k] for k in digests}
                    for k in digests:
                        self._subject_lru.move_to_end(k)
                    return table, [slots[r.subject] for r in reqs]
                betas = {k: self._subject_betas[k] for k in missing}
            for k, b in betas.items():
                # _install_subject counts the re-bake (a fresh
                # specialization): the eviction traded this recompute
                # for table space, and the counter keeps the trade
                # observable.
                self._install_subject(k, b, protected=digests)
        raise RuntimeError(           # racing evictions kept winning
            "could not pin this batch's subjects into the table; "
            "max_subjects is too small for the live working set")

    def warmup_posed(self, bucket_list: Optional[Sequence[int]] = None,
                     ) -> dict:
        """Build the gathered pose-only per-bucket executables up front
        (requires at least one ``specialize``d subject, so the table —
        whose capacity the programs are shaped over — exists). Returns
        {bucket: "jit" | "aot" | "cached"} ("aot": the lattice served
        it with zero re-traces) — after this, pose-only traffic over
        these buckets compiles NOTHING, for any number or mixture of
        subjects up to the current capacity (the composed-cache
        criterion; a capacity growth retraces once, counted)."""
        out = {}
        for b in bucket_list or self.buckets:
            if b not in self.buckets:
                raise ValueError(f"{b} is not one of {self.buckets}")
            with self._exe_lock:
                entry = self._gather_exes.get(b)
                cap = self._table.capacity if self._table is not None \
                    else None
            known = entry is not None and entry[0] == cap
            if known:
                out[b] = "cached"
                continue
            before = self.counters.aot_loads
            self._gather_executable(b)
            out[b] = "aot" if self.counters.aot_loads > before else "jit"
        if self._bf16_serving():
            # The bf16 tier (PR 14) warms beside the f32 family — the
            # zero-steady-recompile criterion covers BOTH precision
            # families (a bf16 tier-0 burst must never pay a compile
            # inside a latency-sensitive dispatch). Always "jit": the
            # bf16 family has no lattice tier by design.
            for b in bucket_list or self.buckets:
                with self._exe_lock:
                    entry = self._gather_exes_bf16.get(b)
                    cap = (self._table.capacity
                           if self._table is not None else None)
                if entry is None or entry[0] != cap:
                    self._gather_executable(b, prec="bf16")
        if self._lane_count is not None:
            # Same reasoning as warmup(): pose-only lane traffic and
            # sibling-ladder failovers must find every lane's gathered
            # executables warm.
            self._get_lanes().warm(bucket_list or self.buckets,
                                   posed=True)
        return out

    # ----------------------------------------------- dispatch lanes (PR 13)
    @property
    def lane_count(self) -> Optional[int]:
        """Configured per-device dispatch lanes (None = single-device
        dispatch, the pre-PR-13 path)."""
        return self._lane_count

    def _get_lanes(self):
        """The engine's ``LaneSet``, built on first use (device
        enumeration + per-lane breaker construction — never in the
        constructor). Race-tolerant the same way ``_stream_manager``
        is: the first publisher under ``_exe_lock`` wins, a losing
        builder is discarded (a LaneSet holds no threads until its
        first batch)."""
        if self._lane_count is None:
            return None
        ls = self._laneset
        if ls is None:
            from mano_hand_tpu.serving.lanes import LaneSet

            ls = LaneSet(self, self._lane_count, probe=self._lane_probe)
            with self._exe_lock:
                if self._laneset is None:
                    self._laneset = ls
                ls = self._laneset
        return ls

    # --------------------------------------------- streaming sessions (PR 12)
    def _stream_manager(self):
        """The engine's StreamManager, built on first use (race-
        tolerant: a losing builder is discarded — the manager holds no
        resources until sessions register). Publication happens under
        ``_live_lock``, the same hold ``stop()``/``start()`` flip
        ``_streams_stopped`` under, so a manager built after (or
        racing) a stop is born refusing registrations."""
        mgr = self._streams
        if mgr is None:
            from mano_hand_tpu.serving.streams import StreamManager

            mgr = StreamManager(self)
            with self._live_lock:
                if self._streams is None:
                    # Pre-publication: no other thread can hold the
                    # manager lock yet, so the direct flag write is
                    # race-free.
                    mgr._stopped = self._streams_stopped
                    self._streams = mgr
                mgr = self._streams
        return mgr

    def open_stream(self, subject, *, n_steps: int = 4,
                    data_term: str = "joints", solver: str = "lm",
                    frame_deadline_s: Optional[float] = None,
                    idle_timeout_s: Optional[float] = None,
                    resume_pose=None, **tracker_kw):
        """Open one per-user tracking session (PR 12 tentpole); returns
        a ``serving.streams.StreamSession``.

        ``subject`` is the user's betas array (baked via ``specialize``
        — idempotent, so an unknown subject is a first bake, not an
        error) or an existing ``specialize()`` key (an EVICTED key
        stays valid: its betas are registered and the table row
        re-bakes on the next dispatch). Each ``submit_frame(target)``
        then runs a frozen-shape LM solve (the PR-2 48-col path)
        warm-started from the last converged pose
        (``fitting/tracking.py:make_tracker``) and serves the posed
        verts through the gathered SubjectTable dispatch at tier 0 —
        concurrent streams' frames coalesce into mixed-subject batches
        with zero steady recompiles, and chaos/failover/overload
        compose unchanged (a CPU-failover frame is bit-identical and
        leaves the warm start untouched).

        ``frame_deadline_s`` is the default per-frame TTL (fit +
        dispatch; swept before solver time is spent);
        ``idle_timeout_s`` expires a session nobody feeds (terminal
        ``expired``); ``resume_pose`` seeds the warm start from a
        carried pose (e.g. a re-opened stream) instead of the rest
        pose. ``n_steps``/``data_term``/``solver``/``tracker_kw`` pass
        to ``make_tracker`` with ``frozen_shape`` pinned to the
        subject's betas. Lifecycle terminals — ``closed`` / ``expired``
        / ``shed`` / ``shutdown`` (``stop()`` sweeps open sessions) —
        each close the session's PR-8 span exactly once.
        """
        from mano_hand_tpu.serving import streams as streams_mod

        return streams_mod.open_stream(
            self, subject, n_steps=n_steps, data_term=data_term,
            solver=solver, frame_deadline_s=frame_deadline_s,
            idle_timeout_s=idle_timeout_s, resume_pose=resume_pose,
            **tracker_kw)

    # ------------------------------------------------- admission (PR 5)
    def _quota(self, tier: int) -> int:
        """Outstanding-count threshold at which tier ``tier`` sheds.
        Tier 0 defaults to the whole queue; lower-priority tiers to
        half of it — the gap is tier-0's reserved headroom."""
        q = self._tier_quotas.get(tier)
        if q is None:
            q = self.max_queued if tier <= 0 else self.max_queued // 2
        return min(q, self.max_queued)

    # --------------------------------------- live control surface (PR 19)
    def attach_control(self, source) -> None:
        """Attach a controller's snapshot source: a zero-arg callable
        returning the ``load()["control"]`` block, built in ONE
        controller-lock hold (the torn-telemetry rule — the same
        discipline every other load() sub-block follows). Detach with
        ``detach_control``; a failing source degrades the block to the
        empty shape, never a load() crash."""
        self._control_source = source

    def detach_control(self) -> None:
        self._control_source = None

    def set_coalesce_base(self, max_delay_s: float) -> dict:
        """Live-retune the coalesce window BASE (serving/control.py's
        batching actuator). The adaptive formula (``_coalesce_window``)
        reads the attribute per batch, so the new base takes effect at
        the next assembly — no lock is needed for a single float swap,
        and the window stays bounded by the same pressure collapse.
        Returns ``{"before", "after"}`` for the actuation event."""
        v = float(max_delay_s)
        if not 0.0 <= v <= 1.0:
            raise ValueError(
                f"max_delay_s must be in [0, 1] seconds, got {v}")
        before = self.max_delay_s
        self.max_delay_s = v
        return {"before": before, "after": v}

    def set_admission(self, *, max_queued: Optional[int] = None,
                      tier_quotas: Optional[dict] = None) -> dict:
        """Live-retune bounded admission (the PR-19 quota actuator):
        swap ``max_queued`` and/or ``tier_quotas`` in ONE ``_live_lock``
        hold — the same lock ``submit`` decides admission under, so a
        concurrent submitter sees either the old pair or the new pair,
        never a torn mix (the torn-telemetry rule applied to a WRITE).

        Boundedness itself is a construction-time choice: an engine
        built unbounded (``max_queued=None``) keeps its lock-free
        admission fast path, and this setter refuses to retrofit a
        bound (or remove one) at runtime. Validation mirrors the
        constructor. Returns ``{"before", "after"}`` dicts."""
        if self.max_queued is None:
            raise ValueError(
                "set_admission requires an engine built with bounded "
                "admission (max_queued=N); boundedness is a "
                "construction-time choice")
        if max_queued is not None and int(max_queued) < 0:
            raise ValueError(
                f"max_queued must be >= 0 (0 sheds everything), got "
                f"{max_queued}")
        for t, q in (tier_quotas or {}).items():
            if t < 0 or q < 0:
                raise ValueError(
                    f"tier_quotas entries must be non-negative, got "
                    f"{{{t}: {q}}}")
        with self._live_lock:
            before = {"max_queued": self.max_queued,
                      "tier_quotas": dict(self._tier_quotas)}
            if max_queued is not None:
                self.max_queued = int(max_queued)
            if tier_quotas is not None:
                self._tier_quotas = {int(t): int(q)
                                     for t, q in tier_quotas.items()}
            after = {"max_queued": self.max_queued,
                     "tier_quotas": dict(self._tier_quotas)}
        return {"before": before, "after": after}

    def set_bucket_bias(self, bias: int) -> dict:
        """Live-retune the bucket-ladder selection bias (the PR-19
        ladder actuator): ``bias`` rungs are added to the
        smallest-fitting bucket at ``_launch`` (capped at the largest).
        0 is today's policy exactly. A positive bias pads more rows per
        dispatch but narrows the set of executables steady traffic
        exercises to the ladder's top rungs — steadier batch shapes
        (and a smaller live-executable working set) at a bounded pad
        cost, the lever the controller pulls when latency-quantile
        spread, not throughput, is the burning objective."""
        b = int(bias)
        if not 0 <= b < len(self.buckets):
            raise ValueError(
                f"bucket_bias must be in [0, {len(self.buckets) - 1}], "
                f"got {b}")
        before = self.bucket_bias
        self.bucket_bias = b
        return {"before": before, "after": b}

    def load(self) -> dict:
        """The backpressure signal: a point-in-time load snapshot
        callers can poll BEFORE submitting (soft "try later"), instead
        of discovering overload via a shed exception. Per tier:
        ``"ok"`` (admitting), ``"busy"`` (admitting, but outstanding has
        crossed ``busy_fraction`` of the tier's quota — back off now
        and the hard shed may never come), ``"shed"`` (a submit at this
        instant would raise ``ServingError(kind="shed")``). With
        admission unbounded (``max_queued=None``) every tier is "ok"
        and only the observability numbers carry signal."""
        # Admission state derives inside the SAME _live_lock hold that
        # reads the outstanding count (and that set_admission swaps the
        # quota pair under, PR 19) — the per-tier states, the cap, and
        # the count always describe one instant, even against a live
        # controller retune (the torn-telemetry rule).
        with self._live_lock:
            outstanding = len(self._live)
            max_queued = self.max_queued
            tiers = {}
            if max_queued is not None:
                for t in sorted({0, 1} | set(self._tier_quotas)):
                    q = self._quota(t)
                    if outstanding >= q:
                        state = "shed"
                    elif outstanding >= self.busy_fraction * q:
                        state = "busy"
                    else:
                        state = "ok"
                    tiers[str(t)] = state
        queued = self._queue.qsize() + len(self._pending)
        out = {
            "outstanding": outstanding,
            "queued": queued,
            "max_queued": max_queued,
            "admission": tiers,
            "backlog_peak": self.counters.backlog_peak,
        }
        # Streaming sessions (PR 12): active-stream count + per-stream
        # backlog age, one manager-lock hold (the torn-telemetry rule;
        # the empty block keeps the load surface shape-stable — its
        # keys are pinned against StreamManager.snapshot in tests).
        mgr = self._streams
        if mgr is not None:
            out["streams"] = mgr.snapshot()
        else:
            from mano_hand_tpu.serving import streams as streams_mod

            out["streams"] = streams_mod.empty_snapshot()
        # Dispatch lanes (PR 13): per-lane backlog/breaker/ladder
        # telemetry, one LaneSet-lock hold (the torn-telemetry rule).
        ls = self._laneset
        if ls is not None:
            out["lanes"] = ls.snapshot()
        # Tiered subject store (PR 16): tier occupancy + in-flight
        # promotions, one store-lock hold (the torn-telemetry rule).
        if self._subject_store is not None:
            out["subject_store"] = self._subject_store.snapshot()
        # Closed-loop control (PR 19): the attached controller's state
        # (actuated values, decision counters, crash flag), one
        # controller-lock hold (the torn-telemetry rule). The empty
        # block keeps the load surface shape-stable — its keys are
        # pinned against Controller.snapshot in tests — and a FAILING
        # source degrades to it too: telemetry must never crash load().
        src = self._control_source
        if src is not None:
            try:
                out["control"] = src()
            except Exception:  # noqa: BLE001 — degrade, never crash
                from mano_hand_tpu.serving import control as control_mod

                out["control"] = control_mod.empty_snapshot()
        else:
            from mano_hand_tpu.serving import control as control_mod

            out["control"] = control_mod.empty_snapshot()
        # Precision tiers (PR 14): the policy is immutable, so this is
        # pure derivation — no lock needed, and an operator (or the
        # metrics scrape, obs/metrics.py:load_samples) can always see
        # WHICH tier serves which precision family and under what
        # stated envelope.
        if self._precision_policy is not None:
            pol = self._precision_policy
            out["precision"] = {
                "envelope_m": pol.max_vertex_err_m,
                "accumulate": pol.accumulate,
                "tiers": pol.tiers_snapshot(
                    (0, 1, *self._tier_quotas)),
            }
        if self._tracer is not None:
            # PR 8: per-tier resolve-latency quantiles + backlog age.
            # The tracer copies its samples and open-span starts in ONE
            # lock hold (obs/trace.py:load_snapshot — the same
            # torn-telemetry rule as ServingCounters.snapshot), so the
            # quantiles and the age describe the same instant.
            out.update(self._tracer.load_snapshot())
        return out

    # --------------------------------------------------- deadlines (PR 5)
    def _is_expired(self, req: _Request, now: Optional[float] = None,
                    ) -> bool:
        return (req.deadline is not None
                and (time.monotonic() if now is None else now)
                >= req.deadline)

    def _expire(self, req: _Request, phase: str) -> None:
        """Resolve one request as ``kind="expired"`` — the sweep that
        keeps chip time off results nobody will read. Counted once: the
        ``done()`` guard makes a double sweep (e.g. coalesce then a
        shutdown drain) a no-op."""
        if self._set_exception_safe(req, ServingError(
                f"request expired before {phase} (deadline_s elapsed "
                f"{time.monotonic() - req.deadline:.3g}s ago); a stale "
                "result would not be read, so none was produced",
                phase=phase, kind="expired")):
            self.counters.count_expired(req.tier)
            if self._tracer is not None:
                self._tracer.close(req.span, "expired", phase=phase)
        self._deregister(req)

    def submit(self, pose, shape=None, subject: Optional[str] = None,
               *, priority: int = 0, deadline_s: Optional[float] = None,
               ) -> Future:
        """Enqueue one forward request; returns a Future of the verts.

        ``pose`` is [n, J, 3] (Future resolves to [n, V, 3]) or a single
        [J, 3] (resolves to [V, 3]). ``shape`` defaults to zeros.
        ``subject`` (a key from ``specialize``) routes the request down
        the pose-only fast path instead — the baked shape stage is
        reused and only the pose stage runs per call; ``shape`` must be
        omitted there (the subject IS the shape).

        ``priority`` is the admission tier (0 = interactive, >= 1 =
        batch/fitting): under a bounded queue (``max_queued``) overload
        sheds high-numbered tiers first — a shed raises a structured
        ``ServingError(kind="shed")`` HERE, in O(µs), without touching
        the device (poll ``load()`` to back off before that happens).
        ``deadline_s`` is this request's end-to-end time-to-live: once
        it elapses the request resolves to
        ``ServingError(kind="expired")`` instead of a result, and the
        engine sweeps it WITHOUT dispatching wherever the expiry is
        seen pre-dispatch (queue, parked, failover) — an already-
        expired deadline resolves the returned future immediately.
        """
        pose = np.asarray(pose, self._dtype)
        squeeze = pose.ndim == 2
        if squeeze:
            pose = pose[None]
        if pose.ndim != 3 or pose.shape[1:] != (self._n_joints, 3):
            raise ValueError(
                f"pose must be [n, {self._n_joints}, 3] or "
                f"[{self._n_joints}, 3], got {pose.shape}")
        n = pose.shape[0]
        if n < 1:
            # A zero-row request has no result to wait for; letting it
            # through would crash the dispatcher at bucket selection.
            raise ValueError("request must have at least one row")
        if n > self.buckets[-1]:
            raise ValueError(
                f"request of {n} rows exceeds the largest bucket "
                f"{self.buckets[-1]}; chunk upstream "
                "(core.forward_chunked) or raise max_bucket")
        if subject is not None:
            if shape is not None:
                raise ValueError(
                    "pass either shape (full path) or subject (pose-only "
                    "path), not both — the subject IS the baked shape")
            with self._exe_lock:
                # Betas registry, not the slot map: an EVICTED subject
                # is still servable (its row re-bakes at dispatch);
                # only a never-specialized key is a caller error.
                known = subject in self._subject_betas
                if subject in self._subject_lru:
                    # Live traffic refreshes LRU position at submit, so
                    # queued requests' subjects resist eviction.
                    self._subject_lru.move_to_end(subject)
            if not known:
                raise ValueError(
                    f"unknown subject {subject!r}; call "
                    "specialize(betas) first")
        elif shape is None:
            shape = np.zeros((n, self._n_shape), self._dtype)
        else:
            shape = np.asarray(shape, self._dtype)
            if shape.ndim == 1:
                shape = np.broadcast_to(shape[None], (n, self._n_shape))
            if shape.shape != (n, self._n_shape):
                raise ValueError(
                    f"shape must be [{n}, {self._n_shape}] to match pose, "
                    f"got {shape.shape}")
        tier = int(priority)
        if tier < 0:
            raise ValueError(f"priority must be >= 0, got {priority}")
        if self._failure is not None:
            raise RuntimeError(
                "serving engine dispatcher died") from self._failure
        self.counters.count_tier_submit(tier)
        deadline = (None if deadline_s is None
                    else time.monotonic() + float(deadline_s))
        req = _Request(pose, shape, n, squeeze, subject,
                       deadline=deadline, tier=tier)
        # The future the CALLER sees carries the cancel hook from
        # birth — one wiring mechanism, no attribute overwrite to
        # forget (nothing has observed the placeholder future yet).
        req.future = _CancellableFuture(lambda: self._on_cancel(req))
        tr = self._tracer
        if tr is not None:
            # The span opens HERE — after validation (a caller error is
            # not a request), before any resolution path, so every
            # terminal kind below closes exactly this span.
            req.span = tr.start("posed" if subject is not None else "full",
                                tier=tier, rows=n)
        if deadline is not None and float(deadline_s) <= 0:
            # Born expired: resolve the future right here — no
            # registration, no queue slot, no dispatch (the satellite
            # edge case; count_expired keeps it observable).
            self._expire(req, "admission")
            return req.future
        if self.max_queued is not None:
            # Admission check ATOMIC with registration (one _live_lock
            # hold): concurrent submitters cannot both squeeze past the
            # same last slot, so the bound is a bound, not a hint. The
            # whole decision is dict bookkeeping — O(µs), no device.
            # The quota READ rides inside the same hold (PR 19): a live
            # set_admission swaps max_queued + tier_quotas under this
            # lock, so a submit sees one coherent pair, never a torn
            # mix of old cap and new quota.
            with self._live_lock:
                quota = self._quota(tier)
                outstanding = len(self._live)
                admitted = outstanding < quota
                if admitted:
                    self._live[id(req)] = req
                    outstanding += 1
            if not admitted:
                self.counters.count_shed(tier)
                if tr is not None:
                    # Shed is a terminal resolution: close the span on
                    # the O(µs) admission path (two cheap tracer calls;
                    # note_shed's streak detector turns a sustained
                    # burst into ONE flight-recorder incident).
                    tr.close(req.span, "shed")
                    tr.note_shed()
                raise ServingError(
                    f"admission shed: {outstanding} outstanding >= "
                    f"tier-{tier} quota {quota} "
                    f"(max_queued={self.max_queued}); the engine is "
                    "over capacity for this priority class — poll "
                    "load() and retry later",
                    phase="admission", kind="shed")
            self.counters.observe_backlog(outstanding)
        else:
            self.counters.observe_backlog(self._register(req))
        if tr is not None:
            tr.note_admit()   # resets the shed-burst streak
        self.start()
        self._queue.put(req)
        if self._failure is not None:
            # The dispatcher died between the check above and the put:
            # nothing will ever read the queue again, so drain it here —
            # a future that can never resolve must not be handed out.
            self._drain_cancelled(self._failure)
            raise RuntimeError(
                "serving engine dispatcher died") from self._failure
        return req.future

    def forward(self, pose, shape=None, subject: Optional[str] = None,
                *, priority: int = 0,
                deadline_s: Optional[float] = None) -> np.ndarray:
        """Synchronous convenience: ``submit(...).result()``."""
        return self.submit(pose, shape, subject=subject,
                           priority=priority,
                           deadline_s=deadline_s).result()

    def warmup(self, bucket_list: Optional[Sequence[int]] = None) -> dict:
        """Build (or AOT-load) executables for the given buckets up front.

        Default: every configured bucket. Returns {bucket: source} where
        source is "jit" | "aot" | "cached". Warm-up is where compile
        latency belongs — after this, steady-state traffic over these
        buckets runs with ZERO further compiles (the acceptance test).
        """
        out = {}
        for b in bucket_list or self.buckets:
            if b not in self.buckets:
                raise ValueError(f"{b} is not one of {self.buckets}")
            with self._exe_lock:
                known = b in self._exes
            if known:
                out[b] = "cached"
                continue
            before = self.counters.aot_loads
            self._executable(b)
            out[b] = "aot" if self.counters.aot_loads > before else "jit"
        if self._policy is not None and self._policy.cpu_fallback:
            # Warm the graceful-degradation tier alongside the primary:
            # compiling the fallback DURING an outage would stack a
            # cold compile on top of the failure it exists to absorb.
            for b in bucket_list or self.buckets:
                self._fallback_executable(b)
        if self._lane_count is not None:
            # Lane-aware engines serve full-path traffic from per-lane
            # executables — warm all N lanes' caches here too, so
            # steady lane traffic (and ladder failovers onto ANY
            # sibling) compiles nothing (counted warm-up compiles).
            self._get_lanes().warm(bucket_list or self.buckets,
                                   posed=False)
        return out

    # ------------------------------------------- crash-safe restart (PR 6)
    def _params_digest(self) -> str:
        if self._digest is None:
            from mano_hand_tpu.io.export_aot import params_digest

            self._digest = params_digest(self._params)
        return self._digest

    def _get_lattice(self):
        """The aot_dir's executable lattice, opened once per engine.

        A manifest that is unreadable, schema-incompatible, or baked for
        a different parameter set degrades to a COUNTED latticeless boot
        (``aot_load_failures``) — the recompile storm is the fallback,
        never a crash and never another asset's executables."""
        if self.aot_dir is None:
            return None
        with self._exe_lock:
            if self._lattice_loaded:
                return self._lattice
        # Single-flight under the dedicated lock (a racing pair would
        # double-count a manifest-level failure); disk work stays out of
        # _exe_lock, which the dispatch path blocks on per batch.
        with self._lattice_lock:
            with self._exe_lock:
                if self._lattice_loaded:
                    return self._lattice
            from mano_hand_tpu.io.export_aot import load_lattice

            lat = load_lattice(
                self.aot_dir, self._params_digest(),
                on_failure=lambda key, reason:
                    self.counters.count_aot_load_failure())
            with self._exe_lock:
                self._lattice = lat
                self._lattice_loaded = True
                return self._lattice

    def _lattice_capacities(self):
        """The table-capacity doubling ladder this engine can reach:
        ``_TABLE_INIT_CAPACITY`` doubling up to ``max_subjects`` — the
        capacities ``bake_lattice`` must cover so a growth at runtime
        loads instead of compiling."""
        caps = []
        c = min(self._TABLE_INIT_CAPACITY, self.max_subjects)
        while True:
            caps.append(c)
            if c >= self.max_subjects:
                return caps
            c = min(c * 2, self.max_subjects)

    def bake_lattice(self, *, capacities: Optional[Sequence[int]] = None,
                     platforms: Optional[Sequence[str]] = None,
                     include_cpu_fallback: Optional[bool] = None,
                     log=None) -> dict:
        """Pre-bake THIS engine's reachable executable lattice into
        ``aot_dir`` (io/export_aot.py:bake_lattice): every bucket's full
        program, every (bucket x capacity-ladder) gathered program, and
        — when the policy enables CPU failover (or ``include_cpu_
        fallback=True``) — the CPU degradation tier. After this, a cold
        process on the same aot_dir boots every one of those programs
        from disk with zero re-traces (``warmup``/``warmup_posed``
        report "aot"; the cold-start drill's criterion). Returns the
        manifest; trace+serialize only, no backend compile."""
        if self.aot_dir is None:
            raise ValueError("bake_lattice requires aot_dir")
        from mano_hand_tpu.io.export_aot import bake_lattice

        if include_cpu_fallback is None:
            include_cpu_fallback = bool(
                self._policy is not None and self._policy.cpu_fallback)
        if capacities is None:
            caps = self._lattice_capacities()
            # Per-lane tier (PR 18): sharded lanes dispatch against
            # shard-LOCAL tables of a FIXED capacity — the even split
            # of max_subjects over N lanes (lanes.py:_shard_capacity_
            # max) — which is generally NOT on the doubling ladder.
            # Bake it too, or every lane's gathered program misses the
            # lattice and the per-worker cold boot pays N compiles.
            store = getattr(self, "_subject_store", None)
            if (self._lane_count and store is not None
                    and getattr(store, "sharded", False)):
                shard_cap = max(
                    1, -(-self.max_subjects // self._lane_count))
                if shard_cap not in caps:
                    caps.append(shard_cap)
        else:
            caps = list(capacities)
        manifest = bake_lattice(
            self._params, self.aot_dir,
            buckets=self.buckets,
            capacities=caps,
            platforms=tuple(platforms) if platforms else ("cpu", "tpu"),
            cpu_fallback=include_cpu_fallback,
            log=log,
        )
        with self._exe_lock:
            # Re-open on next fetch: the bake may have replaced a stale
            # or damaged lattice this engine already gave up on.
            self._lattice_loaded = False
            self._lattice = None
        return manifest

    _CKPT_SCHEMA = 1

    def checkpoint_subjects(self, path) -> str:
        """Persist the warm SubjectTable state — baked rows, raw betas,
        and LRU order — so a restarted process serves every specialized
        subject bit-identically WITHOUT re-running a single shape-stage
        bake (io/orbax_ckpt.py:save_state; pickle fallback when orbax
        is absent). Evicted-but-registered subjects ride along as
        betas-only entries (they re-bake transparently on first use,
        exactly as they would have pre-restart). Taken under
        ``_install_lock``, so the snapshot can never interleave with a
        concurrent ``specialize()``'s bake-and-swap."""
        from mano_hand_tpu.io import orbax_ckpt

        with self._install_lock:
            with self._exe_lock:
                table = self._table
                slots = dict(self._subject_slots)
                lru = list(self._subject_lru)
                betas = dict(self._subject_betas)
        live = [k for k in lru if k in slots]       # LRU order, oldest first
        evicted = [k for k in betas if k not in slots]
        if table is not None and live:
            rows = [slots[k] for k in live]
            v_shaped = np.asarray(table.v_shaped)[rows]
            joints = np.asarray(table.joints)[rows]
            shape_rows = np.asarray(table.shape)[rows]
        else:
            n_v = self._params.v_template.shape[0]
            v_shaped = np.zeros((0, n_v, 3), self._dtype)
            joints = np.zeros((0, self._n_joints, 3), self._dtype)
            shape_rows = np.zeros((0, self._n_shape), self._dtype)
        meta = {
            "schema": self._CKPT_SCHEMA,
            "params_digest": self._params_digest(),
            "capacity": table.capacity if table is not None else 0,
            "digests": live,
            "evicted_digests": evicted,
            "dtype": str(self._dtype),
        }
        arrays = {
            "betas": (np.stack([betas[k] for k in live])
                      if live else np.zeros((0, self._n_shape), self._dtype)),
            "v_shaped": v_shaped,
            "joints": joints,
            "shape_rows": shape_rows,
            "evicted_betas": (np.stack([betas[k] for k in evicted])
                              if evicted
                              else np.zeros((0, self._n_shape), self._dtype)),
        }
        return str(orbax_ckpt.save_state(meta, arrays, path))

    def restore_subjects(self, path, *, strict: bool = False) -> dict:
        """Revive a ``checkpoint_subjects`` snapshot into this engine.

        Each live subject's BAKED rows are written straight into the
        table (``subjects_restored`` counted; no shape-stage recompute),
        in checkpointed LRU order so eviction priority survives the
        restart; betas-only (evicted) subjects re-register for
        transparent re-bake. Restores go through the same
        ``_install_lock`` serialized installer as ``specialize()``, so
        a restore racing live specialize calls stays consistent — a
        subject the race already installed is skipped, never
        double-installed. A missing/damaged/digest-mismatched
        checkpoint DEGRADES to an empty restore with an ``"error"``
        field (subjects simply re-specialize on demand) unless
        ``strict=True``."""
        from mano_hand_tpu.io import orbax_ckpt
        from mano_hand_tpu.models import core

        summary = {"restored": 0, "betas_only": 0, "skipped": 0}
        try:
            meta, arrays = orbax_ckpt.load_state(path)
            if meta.get("schema") != self._CKPT_SCHEMA:
                raise ValueError(
                    f"checkpoint schema {meta.get('schema')} != supported "
                    f"{self._CKPT_SCHEMA}")
            if meta.get("params_digest") != self._params_digest():
                raise ValueError(
                    "checkpoint params_digest does not match this "
                    "engine's parameter set — restoring would serve "
                    "another asset's subjects")
            digests = list(meta.get("digests") or ())
            for name in ("betas", "v_shaped", "joints", "shape_rows"):
                if len(arrays[name]) != len(digests):
                    raise ValueError(
                        f"checkpoint arrays[{name!r}] rows "
                        f"{len(arrays[name])} != {len(digests)} digests")
        except Exception as e:  # noqa: BLE001 — degrade, not crash
            if strict:
                raise
            _LOG.warning(
                f"subject checkpoint {path}: {type(e).__name__}: {e}; "
                "restoring nothing (subjects re-specialize on demand)")
            summary["error"] = f"{type(e).__name__}: {e}"
            return summary
        for k, b in zip(meta.get("evicted_digests") or (),
                        arrays["evicted_betas"]):
            with self._exe_lock:
                self._subject_betas.setdefault(
                    k, np.ascontiguousarray(b, self._dtype))
            summary["betas_only"] += 1
        for i, key in enumerate(digests):
            with self._exe_lock:
                present = key in self._subject_slots
            if present:          # a racing specialize() already baked it
                summary["skipped"] += 1
                continue
            shaped = core.ShapedHand(
                v_shaped=arrays["v_shaped"][i],
                joints=arrays["joints"][i],
                shape=arrays["shape_rows"][i],
                pose_basis=self._params.pose_basis,
                lbs_weights=self._params.lbs_weights,
                parents=self._params.parents,
            )
            self._install_subject(
                key, np.ascontiguousarray(arrays["betas"][i], self._dtype),
                shaped=shaped)
            summary["restored"] += 1
        return summary

    # ---------------------------------------------------------- executables
    def _on_chaos_fault(self, kind: Optional[str] = None,
                        index: Optional[int] = None) -> None:
        """Chaos-plan fault hook: the counter tick plus (PR 8) the
        fault on the request timeline — ``ChaosPlan.wrap`` passes the
        fault kind and call index when given a hook that accepts
        them."""
        self.counters.count_fault()
        if self._tracer is not None:
            self._tracer.runtime_event("chaos_fault", kind=kind,
                                       index=index)

    def _artifact_path(self, bucket: int):
        from pathlib import Path

        from mano_hand_tpu.io.export_aot import params_digest

        d = Path(self.aot_dir)
        return d / (f"serve_{params_digest(self._params)}_"
                    f"b{bucket}.jaxexp")

    def _executable(self, bucket: int):
        """The compiled per-bucket entry — in-memory, then disk, then jit.

        Compile order is the whole caching story: a hit in ``_exes``
        costs a dict lookup; a disk hit deserializes the traced/lowered
        artifact (no re-trace; counted in ``aot_loads``); only a full
        miss traces + compiles (counted in ``compiles``) and, when
        ``aot_dir`` is set, writes the artifact the NEXT process will
        hit.
        """
        with self._exe_lock:
            exe = self._exes.get(bucket)
        if exe is not None:
            return exe

        loaded = None
        lat = self._get_lattice()
        if lat is not None:
            # The lattice tier (PR 6): params as runtime ARGUMENTS, the
            # same program family as the live jit below — a lattice-
            # served bucket is bit-identical to the direct path (unlike
            # the legacy constants-baked artifact, which agrees to float
            # rounding). A damaged entry was already counted + warned by
            # the lattice; fall through to the legacy/jit tiers.
            import jax

            call = lat.get("full", bucket,
                           platform=jax.default_backend())
            if call is not None:
                try:
                    if self._lat_leaves is None:
                        from mano_hand_tpu.io.export_aot import (
                            params_leaves,
                        )

                        if self._params_dev is None:
                            self._params_dev = self._params.device_put()
                        self._lat_leaves = params_leaves(self._params_dev)
                    leaves = self._lat_leaves
                    loaded = lambda p, s: call(leaves, p, s)  # noqa: E731
                    # Eagerly warmed like every sibling builder: the XLA
                    # backend compile of the deserialized program lands
                    # at load time (and is absorbed by jax's persistent
                    # compilation cache when enabled), never inside a
                    # latency-sensitive dispatch. The warm ALSO proves
                    # the entry executes on this backend — a call-time
                    # failure degrades to the jit tier (counted) rather
                    # than crashing boot.
                    jax.block_until_ready(loaded(
                        np.zeros((bucket, self._n_joints, 3), self._dtype),
                        np.zeros((bucket, self._n_shape), self._dtype)))
                    self.counters.count_aot_load()
                    if self._tracer is not None:
                        self._tracer.runtime_event(
                            "lattice_load", family="full", bucket=bucket)
                except Exception as e:  # noqa: BLE001 — degrade
                    self.counters.count_aot_load_failure()
                    _LOG.warning(
                        f"lattice full/b{bucket} entry failed at "
                        f"execution ({type(e).__name__}: {e}); "
                        "recompiling (counted)")
                    if self._tracer is not None:
                        self._tracer.runtime_event(
                            "lattice_load_failed", family="full",
                            bucket=bucket)
                    loaded = None
        if loaded is None and self.aot_dir is not None:
            from mano_hand_tpu.io.export_aot import load_forward

            path = self._artifact_path(bucket)
            if path.exists():
                try:
                    fwd = load_forward(path)
                    have = fwd.meta.get("params_digest")
                    if have is not None and have != self._params_digest():
                        raise ValueError(
                            f"artifact params_digest {have} does not "
                            "match this engine's parameter set — serving "
                            "it would return another asset's meshes")
                    loaded = lambda p, s: fwd(p, s)["verts"]  # noqa: E731
                    self.counters.count_aot_load()
                except Exception as e:  # noqa: BLE001 — self-heal
                    # A truncated/corrupt/mismatched artifact (a process
                    # killed mid-write by an older version, disk trouble,
                    # a file copied across assets) must not wedge this
                    # bucket forever OR serve silently-wrong results:
                    # counted degradation, then the jit path below, which
                    # also re-exports a good artifact.
                    self.counters.count_aot_load_failure()
                    _LOG.warning(
                        f"invalid serving artifact {path} "
                        f"({type(e).__name__}: {e}); recompiling and "
                        "rewriting it")
                    loaded = None
        if loaded is None:
            # Params ride as runtime ARGUMENTS, exactly like
            # core.jit_forward_batched: baking them in as constants lets
            # XLA fold them differently and the results stop being
            # bit-identical to the direct path (measured on CPU). The
            # AOT artifacts DO bake constants (a consumer needs nothing
            # else) and agree with the live path to float rounding, the
            # same contract tests/test_export_aot.py pins.
            if self._params_dev is None:
                self._params_dev = self._params.device_put()
            loaded = build_bucket_executable(
                self._params_dev, bucket, self._n_joints, self._n_shape,
                self._dtype, donate=self.donate)
            self.counters.count_compile()
            if self._tracer is not None:
                self._tracer.runtime_event("compile", family="full",
                                           bucket=bucket)
            if self.aot_dir is not None:
                import os
                from pathlib import Path

                from mano_hand_tpu.io.export_aot import export_forward

                Path(self.aot_dir).mkdir(parents=True, exist_ok=True)
                path = self._artifact_path(bucket)
                # Atomic write (temp + rename): a process killed
                # mid-export must leave either no artifact or a whole
                # one — a truncated file would cost the next cold
                # process a warning + recompile (the fallback above).
                tmp = path.with_suffix(f".tmp{os.getpid()}")
                tmp.write_bytes(export_forward(self._params, batch=bucket))
                os.replace(tmp, path)
        if self._policy is not None and self._policy.chaos is not None:
            # Chaos wraps the PRIMARY executable ONCE, at cache time:
            # every later dispatch attempt consults the plan (each
            # attempt advances the plan's call index), while the CPU
            # fallback path stays clean by construction — failover is
            # measured recovery, not roulette.
            loaded = self._policy.chaos.wrap(
                loaded, on_fault=self._on_chaos_fault)
        with self._exe_lock:
            # Two threads can race the build; first writer wins so the
            # cache never flips executables under steady traffic.
            exe = self._exes.setdefault(bucket, loaded)
        return exe

    def _gather_executable(self, bucket: int, table=None,
                           prec: str = "f32"):
        """The gathered pose-only per-bucket entry — in-memory then jit,
        no AOT tier (table and index are runtime arguments, so the
        artifact would bake nothing subject-specific; the jit compile
        is already amortized across ALL subject mixtures). Keyed on the
        table CAPACITY as well as the bucket: a growth makes the warm
        entry stale, and the rebuild — O(log subjects) times ever — is
        counted on ``counters`` exactly like every compile.

        ``table`` pins the capacity the caller will actually invoke the
        executable with (the dispatch snapshot from ``_resolve_batch``)
        — resolving against the LIVE table instead would let a racing
        growth hand back a wider program whose jit then silently
        retraces on the snapshot mid-dispatch. Default (None): the live
        table (warm-up paths).

        ``prec`` (PR 14) selects the precision FAMILY: ``"bf16"`` is
        the policy tier's bf16-compute/f32-accumulate program (fused or
        XLA per the same ``_posed_fused_active`` gate), cached in
        ``_gather_exes_bf16`` under identical capacity keying — and
        deliberately NEVER lattice-served (the lattice contract is f32
        bit-identity with the live jit; a silent family swap across a
        restart is exactly what the sentinel exists to prevent).
        """
        if table is None:
            with self._exe_lock:
                table = self._table
        if table is None:
            # Unreachable through submit (it requires a registered
            # subject), but warmup_posed can get here.
            raise RuntimeError(
                "no specialized subject to warm the pose-only path "
                "with; call specialize(betas) first")
        cap = table.capacity
        if prec == "bf16":
            return self._gather_bf16_executable(bucket, table, cap)
        with self._exe_lock:
            entry = self._gather_exes.get(bucket)
        if entry is not None and entry[0] == cap:
            return entry[1]
        exe = None
        fused = self._posed_fused_active(cap)
        if fused:
            # The fused kernel tier (PR 10): same runtime-argument
            # contract (zero per-subject recompiles), different program
            # family — and deliberately NO lattice tier for it (fused
            # is within ~1e-5 of the XLA family, not bit-identical;
            # serving a lattice-persisted XLA program under the fused
            # selection would silently swap numerics across a restart).
            # Resolve interpret BEFORE any build (backend query).
            interp = self._resolve_posed_interpret()
            exe = build_posed_gather_fused_executable(
                table, bucket, self._n_joints, self._dtype,
                donate=self.donate, interpret=interp)
            self.counters.count_compile()
            if self._tracer is not None:
                self._tracer.runtime_event("compile", family="gather_fused",
                                           bucket=bucket, capacity=cap)
        lat = self._get_lattice() if exe is None else None
        if lat is not None:
            # Lattice tier (PR 6): the gathered program finally has a
            # persistent form — table and index are runtime arguments,
            # so the entry bakes NOTHING subject-specific and one
            # artifact per (bucket, capacity) serves every subject
            # mixture across restarts (bit-identical; the entry is the
            # same trace as the jit below).
            import jax

            call = lat.get("gather", bucket, cap,
                           platform=jax.default_backend())
            if call is not None:
                try:
                    from mano_hand_tpu.io.export_aot import table_leaves

                    exe = (lambda tab, idx, p:
                           call(table_leaves(tab), idx, p))
                    # Same eager warm-up contract as build_posed_gather_
                    # executable: backend compile at load, not dispatch
                    # — and a call-time failure degrades to the jit
                    # build below (counted), never crashes boot.
                    jax.block_until_ready(exe(
                        table, np.zeros((bucket,), np.int32),
                        np.zeros((bucket, self._n_joints, 3),
                                 self._dtype)))
                    self.counters.count_aot_load()
                    if self._tracer is not None:
                        self._tracer.runtime_event(
                            "lattice_load", family="gather",
                            bucket=bucket, capacity=cap)
                except Exception as e:  # noqa: BLE001 — degrade
                    self.counters.count_aot_load_failure()
                    _LOG.warning(
                        f"lattice gather/b{bucket}/c{cap} entry failed "
                        f"at execution ({type(e).__name__}: {e}); "
                        "recompiling (counted)")
                    if self._tracer is not None:
                        self._tracer.runtime_event(
                            "lattice_load_failed", family="gather",
                            bucket=bucket, capacity=cap)
                    exe = None
        if exe is None:
            exe = build_posed_gather_executable(
                table, bucket, self._n_joints, self._dtype,
                donate=self.donate)
            self.counters.count_compile()
            if self._tracer is not None:
                self._tracer.runtime_event("compile", family="gather",
                                           bucket=bucket, capacity=cap)
        if self._policy is not None and self._policy.chaos is not None:
            # Same primary-only chaos wrapping as the full path.
            exe = self._policy.chaos.wrap(
                exe, on_fault=self._on_chaos_fault)
        with self._exe_lock:
            cur = self._gather_exes.get(bucket)
            if cur is not None and cur[0] == cap:
                return cur[1]  # racing builder won at the same capacity
            if cur is None or cur[0] < cap:
                # Never let a build against an OLD snapshot clobber a
                # newer-capacity entry (capacity only grows): the stale
                # program still serves THIS dispatch, uncached.
                self._gather_exes[bucket] = (cap, exe)
        return exe

    def _gather_bf16_executable(self, bucket: int, table, cap: int):
        """The bf16-tier gathered entry (PR 14): in-memory then jit —
        no lattice tier by design (see ``_gather_executable``). Chaos
        wraps it exactly like every primary family, so the sentinel
        drill can inject silent corruption into THIS tier and prove
        detection. Publication follows the same capacity-monotonic
        rules as the f32 cache."""
        with self._exe_lock:
            entry = self._gather_exes_bf16.get(bucket)
        if entry is not None and entry[0] == cap:
            return entry[1]
        fused = self._posed_fused_active(cap)
        # Resolved OUTSIDE any lock (a jax backend query).
        interp = self._resolve_posed_interpret() if fused else False
        exe = build_posed_gather_bf16_executable(
            table, bucket, self._n_joints, self._dtype,
            donate=self.donate, fused=fused, interpret=interp)
        self.counters.count_compile()
        if self._tracer is not None:
            self._tracer.runtime_event(
                "compile",
                family="gather_fused_bf16" if fused else "gather_bf16",
                bucket=bucket, capacity=cap)
        if self._policy is not None and self._policy.chaos is not None:
            exe = self._policy.chaos.wrap(
                exe, on_fault=self._on_chaos_fault)
        with self._exe_lock:
            cur = self._gather_exes_bf16.get(bucket)
            if cur is not None and cur[0] == cap:
                return cur[1]  # racing builder won at the same capacity
            if cur is None or cur[0] < cap:
                self._gather_exes_bf16[bucket] = (cap, exe)
        return exe

    def _fallback_executable(self, bucket: int):
        """The CPU graceful-degradation entry — in-memory then jit.

        Normally built eagerly by ``warmup()`` (which warms the whole
        fallback tier whenever ``policy.cpu_fallback`` is set — a cold
        compile must not stack on top of the outage it absorbs); this
        lazy path only pays the compile if a failover hits a bucket
        that was never warmed. Counted as a compile either way. Serves
        both request kinds: full requests directly, subject
        requests by re-running the full forward with the stored betas
        — the same program family as the primary, params as runtime
        args, so failover results are bit-identical to a direct CPU
        bucketed call (the parity criterion in tests/test_runtime.py).
        """
        with self._exe_lock:
            exe = self._cpu_exes.get(bucket)
        if exe is not None:
            return exe
        exe = None
        lat = self._get_lattice()
        if lat is not None:
            # Lattice tier (PR 6): the failover executables pre-bake
            # too — compiling the degradation tier DURING the outage it
            # absorbs was already ruled out at warmup(); now a RESTART
            # mid-outage boots it from disk as well. Same program
            # family, params as runtime args, pinned to host CPU via
            # committed inputs — failover stays bit-identical to a
            # direct CPU bucketed call.
            call = lat.get("cpu", bucket, platform="cpu")
            if call is not None:
                try:
                    import jax

                    cpu = jax.devices("cpu")[0]
                    if self._lat_leaves_cpu is None:
                        from mano_hand_tpu.io.export_aot import (
                            params_leaves,
                        )

                        self._lat_leaves_cpu = tuple(
                            jax.device_put(np.asarray(x), cpu)
                            for x in params_leaves(self._params))
                    leaves = self._lat_leaves_cpu

                    def put(x):
                        return jax.device_put(np.asarray(x), cpu)

                    exe = (lambda p, s:               # noqa: E731
                           call(leaves, put(p), put(s)))
                    jax.block_until_ready(exe(
                        np.zeros((bucket, self._n_joints, 3), self._dtype),
                        np.zeros((bucket, self._n_shape), self._dtype)))
                    self.counters.count_aot_load()
                    if self._tracer is not None:
                        self._tracer.runtime_event(
                            "lattice_load", family="cpu", bucket=bucket)
                except Exception as e:  # noqa: BLE001 — degrade
                    self.counters.count_aot_load_failure()
                    _LOG.warning(
                        f"lattice cpu/b{bucket} entry failed at "
                        f"execution ({type(e).__name__}: {e}); "
                        "recompiling (counted)")
                    if self._tracer is not None:
                        self._tracer.runtime_event(
                            "lattice_load_failed", family="cpu",
                            bucket=bucket)
                    exe = None
        if exe is None:
            exe = build_cpu_fallback_executable(
                self._params, bucket, self._n_joints, self._n_shape,
                self._dtype)
            self.counters.count_compile()
            if self._tracer is not None:
                self._tracer.runtime_event("compile", family="cpu",
                                           bucket=bucket)
        with self._exe_lock:
            exe = self._cpu_exes.setdefault(bucket, exe)
        return exe

    # ------------------------------------------------------------ dispatch
    def _admit(self, nxt: _Request, posed: bool, subjects: set,
               rows: int, prec: str = "f32",
               shard: Optional[int] = None) -> Optional[str]:
        """Why ``nxt`` cannot join the batch being coalesced, or None.

        ``"kind"``: full-path and pose-only requests cannot share a
        program. ``"precision"`` (PR 14): a batch serves ONE precision
        family — a pose-only request whose policy tier maps to the
        other family is parked (policy-less engines never hit this:
        every request maps f32). ``"shard"`` (PR 16): under a sharded
        subject store a batch serves from ONE lane's shard table, so a
        request whose subject another lane owns is parked — the
        cross-shard batch split. ``"subjects"``: admitting one more
        DISTINCT subject would exceed the table's ``max_subjects`` rows
        (so _resolve_batch could never pin the batch). ``"overflow"``:
        the rows would exceed the largest bucket — the one reason that
        also stops the scan (anything later would overflow too once
        this batch is near-full). Note what is ABSENT: a
        subject-equality rule — different subjects coalescing is the
        PR-4 tentpole.
        """
        if (nxt.subject is not None) != posed:
            return "kind"
        if posed and self._precision_policy is not None \
                and self._req_prec(nxt) != prec:
            return "precision"
        if posed and shard is not None \
                and self._shard_of(nxt.subject) != shard:
            # Sharded store (PR 16): a batch dispatches to ONE lane's
            # shard table, so cross-shard batches split here — the
            # parked request leads a later batch bound for ITS lane.
            # Checked before overflow: a cross-shard request keeps the
            # scan going (its rows were never joining this batch).
            return "shard"
        if rows + nxt.rows > self.buckets[-1]:
            return "overflow"
        if (posed and nxt.subject not in subjects
                and len(subjects) >= self.max_subjects):
            return "subjects"
        return None

    def _coalesce(self, first: _Request):
        """Gather more pending requests behind ``first`` until the largest
        bucket fills or the coalesce window elapses. Returns
        (requests, rows, staging).

        Same-path requests coalesce regardless of subject (the gathered
        dispatch takes a per-row subject index); a request that cannot
        join — any reason _admit names: other path kind, genuine bucket
        overflow (``coalesce_overflows``), or a max_subjects-wide batch
        — is parked on ``_pending``, which leads the next batches, so
        head-of-line blocking is bounded to one batch instead of
        starving behind the live queue.

        Staged assembly (PR 17): each admitted request's pose (and
        shape, full path) rows are copied into a pre-allocated slab AT
        ADMIT TIME — the copy overlaps the coalesce wait below instead
        of re-stacking every member on the launch critical path. The
        window itself is adaptive (``_coalesce_window``): it shrinks as
        backlog age/depth rise, down to zero once a full batch is
        already waiting — waiting for stragglers only pays when the
        device would otherwise idle.
        """
        reqs, rows = [first], first.rows
        posed = first.subject is not None
        subjects = {first.subject} if posed else set()
        prec = self._req_prec(first)   # the batch's precision family
        shard = self._shard_of(first.subject) if posed else None
        staging = self._staging_acquire(posed)
        staging.append(first)
        if posed:
            # Prefetch at the coalesce boundary (PR 16): the async
            # promotion overlaps the max_delay_s window below.
            self._prefetch_subject(first.subject)

        def admit(nxt, fresh=True) -> Optional[str]:
            if self._skip_cancelled(nxt):
                # The caller withdrew it (already counted + span-closed
                # by the cancel hook): never batched, never parked.
                return "cancelled"
            if self._is_expired(nxt):
                # The pre-dispatch deadline sweep (PR 5): an expired
                # request is resolved HERE — never batched, never
                # parked, never costing a device row.
                self._expire(nxt, "coalesce")
                return "expired"
            why = self._admit(nxt, posed, subjects, rows, prec, shard)
            if why is None:
                reqs.append(nxt)
                staging.append(nxt)
                if posed:
                    subjects.add(nxt.subject)
                    self._prefetch_subject(nxt.subject)
                if self._tracer is not None:
                    self._tracer.event(nxt.span, "coalesce")
                return None
            self._pending.append(nxt)
            if self._tracer is not None:
                self._tracer.event(nxt.span, "park", why=why)
            if why == "overflow" and fresh:
                # Count each overflowING request once, at its FIRST
                # park from the live queue — a re-park of an already-
                # parked request is the same capacity event, not a new
                # one.
                self.counters.count_overflow()
            return why

        # Parked requests first — they have already waited a batch.
        # Snapshot the count: admit() re-parks rejects on the right.
        for _ in range(len(self._pending)):
            if rows >= self.buckets[-1]:
                break
            nxt = self._pending.popleft()
            if admit(nxt, fresh=False) is None:
                rows += nxt.rows
        deadline = time.perf_counter() + self._coalesce_window(first)
        while rows < self.buckets[-1]:
            timeout = deadline - time.perf_counter()
            try:
                nxt = (self._queue.get_nowait() if timeout <= 0
                       else self._queue.get(timeout=timeout))
            except queue.Empty:
                break
            if nxt is _SENTINEL:
                self._queue.put(_SENTINEL)  # re-post for the main loop
                break
            why = admit(nxt)
            if why is None:
                rows += nxt.rows
            elif why == "overflow":
                # Genuine overflow: dispatch what we have (the parked
                # overhang leads the next batch). A kind/subjects park
                # keeps scanning instead — later same-path requests can
                # still fill this batch.
                break
        return reqs, rows, staging

    def _coalesce_window(self, first: _Request) -> float:
        """How long ``_coalesce`` may wait for stragglers THIS batch.

        The adaptive coalesce window (PR 17), fed by the same signals
        ``load()`` exports (queue depth + backlog age): the base
        ``max_delay_s`` is the latency/throughput knob when traffic is
        sparse, but once a backlog exists the wait stops buying
        anything — the batch will fill from the queue instantly — and
        only adds head-of-line latency. So the window (a) collapses to
        zero when the waiting backlog could already fill the largest
        bucket, (b) scales down linearly with backlog depth below
        that, and (c) decays as the head request's age climbs to MANY
        multiples of the base window (backlog age rising = the
        dispatcher is congested, stop buying latency) — but a head
        that is merely one dispatch-cycle old does NOT shrink it:
        under paced load the head is always about one cycle old, and
        charging that age collapses every batch to whatever already
        sits queued, thinning batches until per-batch dispatch
        overhead dominates (measured: 3x throughput LOSS —
        docs/roadmap.md PR-17 dead-ends). ``adaptive_coalesce=False``
        pins the legacy fixed window.

        Depth-1 serial-equivalence note: the window only shapes how
        long assembly WAITS for not-yet-arrived requests — never which
        requests may join a batch — so results stay bit-identical at
        every depth; see the "Dispatch pipeline" README section for
        the depth-1 contract this rides beside.
        """
        base = self.max_delay_s
        if not self.adaptive_coalesce or base <= 0.0:
            return base
        backlog = self._queue.qsize() + len(self._pending)
        cap = self.buckets[-1]
        if backlog + 1 >= cap:
            return 0.0
        age = time.perf_counter() - first.t_submit
        pressure = max(backlog / cap, min(1.0, age / (8.0 * base)))
        return base * (1.0 - pressure)

    def _pop_parked(self) -> _Request:
        """Take the highest-priority parked request: lowest tier first,
        then EARLIEST DEADLINE within the tier (EDF — the PR-5 Open
        item, closed by PR 17), deadline-less requests after deadlined
        ones, earliest-parked among remaining ties. Parked requests
        already lead the next batches (the anti-starvation rule); under
        priority classes the lead goes to tier 0 FIRST, so a parked
        interactive request can never starve behind parked batch work —
        and within a tier the request closest to expiry now leads, so a
        deep parked backlog sheds the fewest deadlines."""
        best = 0
        for i in range(1, len(self._pending)):
            a, b = self._pending[i], self._pending[best]
            if a.tier != b.tier:
                if a.tier < b.tier:
                    best = i
            elif (a.deadline is not None
                    and (b.deadline is None or a.deadline < b.deadline)):
                best = i
        req = self._pending[best]
        del self._pending[best]
        return req

    def _dispatch_loop(self) -> None:
        # The pipelined dispatch path (PR 17): at depth > 1 on the
        # single-device path, launched batches hand off to a bounded
        # completion stage (readback, deadline re-check, future
        # resolution, span close on a worker pool with FIFO delivery)
        # so batch N+1 assembles and dispatches while batch N executes
        # — the dispatcher only ever blocks on the queue or on stage
        # backpressure. Depth 1 keeps
        # the serial assemble->launch->block->resolve cycle on this one
        # thread, byte-for-byte in telemetry shape (no stage, no
        # "staged" stamps, no pipeline events). Lane mode bypasses both
        # (lanes ARE the overlap; each lane worker is its own FIFO
        # completion stage).
        stage = None
        if self.inflight_depth > 1 and self._lane_count is None:
            stage = _CompletionStage(self, self.inflight_depth)
            self._completion = stage
            if self._tracer is not None:
                self._tracer.runtime_event(
                    "pipeline", depth=self.inflight_depth)
        try:
            while True:
                if self._pending:
                    first = self._pop_parked()
                else:
                    first = self._queue.get()
                if first is _SENTINEL:
                    if not self._running:
                        break
                    continue
                if self._skip_cancelled(first):
                    continue
                if self._is_expired(first):
                    # Deadline sweep at the head of batch assembly: an
                    # expired request (sat queued or parked too long)
                    # resolves without a dispatch.
                    self._expire(first, "dispatch")
                    continue
                self.counters.observe_queue_depth(
                    self._queue.qsize() + len(self._pending) + 1)
                reqs, rows, staging = self._coalesce(first)
                item = self._launch(reqs, rows, staging)
                if item is not None:
                    # Depth-1 serial cycle (or an unsupervised async
                    # handle): retire it before assembling the next
                    # batch. Pipelined/lane launches return None — the
                    # stage (or a lane worker) owns the resolution.
                    self._resolve(item)
            if stage is not None:
                # Clean exit: every launched batch resolves before the
                # queue drains below (re-raises a stage engine-fatal
                # failure here, into the crash handler).
                stage.drain()
                stage.close()
                self._completion = None
            self._drain_cancelled()
        except BaseException as e:  # noqa: BLE001 — futures must not hang
            self._failure = e
            if stage is not None:
                # Queued never-dispatched stage batches are poisoned;
                # the worker retires (idempotent if IT failed first).
                stage.close(e)
                self._completion = None
            if self._pending:
                # Requests parked by _coalesce are in neither the stage
                # nor the queue — their futures must not hang (the PR-3
                # poison guarantee extended to the _pending deque).
                self._poison(list(self._pending), e)
                self._pending.clear()
            self._drain_cancelled(e)
            raise

    def _staging_acquire(self, posed: bool) -> _Staging:
        """One assembly slab pair from the pool (allocate on a dry
        pool — the pool only ever holds recycled slabs). The pool is
        shared with the completion worker (it recycles from its own
        thread), hence the lock."""
        with self._slab_lock:
            st = self._slab_pool.pop() if self._slab_pool else None
        if st is None:
            cap = self.buckets[-1]
            st = _Staging(
                np.empty((cap, self._n_joints, 3), self._dtype),
                np.empty((cap, self._n_shape), self._dtype))
        st.rows = 0
        st.full = not posed
        return st

    def _staging_release(self, st: Optional[_Staging]) -> None:
        """Recycle a batch's slab once its dispatch has consumed it
        (bounded pool: depth in-flight + one assembling + slack; an
        overflow slab is simply dropped to the allocator)."""
        if st is None:
            return
        with self._slab_lock:
            if len(self._slab_pool) < self.inflight_depth + 2:
                self._slab_pool.append(st)

    def _launch(self, reqs, rows, staging: Optional[_Staging] = None):
        # Final deadline sweep at the launch boundary: coalescing can
        # hold a batch for the coalesce window (and a predecessor batch
        # can hold the loop far longer), so re-check each member NOW —
        # the last instant a sweep still costs zero chip time. An
        # all-expired batch dispatches nothing at all.
        if any(r.deadline is not None or r.future.cancelled()
               for r in reqs):
            now = time.monotonic()
            alive = []
            for r in reqs:
                if self._skip_cancelled(r):
                    continue          # withdrawn between coalesce + launch
                if self._is_expired(r, now):
                    self._expire(r, "dispatch")
                else:
                    alive.append(r)
            if not alive:
                self._staging_release(staging)
                return None
            if len(alive) != len(reqs):
                # The staged slab has holes where swept members sat —
                # this (rare: a mid-coalesce expiry/cancel) batch falls
                # back to the legacy re-stack below.
                self._staging_release(staging)
                staging = None
                reqs = alive
                rows = sum(r.rows for r in reqs)
        try:
            bucket = bucket_mod.bucket_for(rows, self.buckets)
            bias = self.bucket_bias
            if bias:
                # Ladder bias (PR 19): round ``bias`` rungs past the
                # smallest fit, capped at the top — pad waste bought
                # deliberately for steadier batch shapes (the values
                # stay policy-exact: pads are repeats of row 0, masked
                # out at delivery like every padded dispatch).
                i = self.buckets.index(bucket)
                bucket = self.buckets[min(len(self.buckets) - 1,
                                          i + bias)]
            tr = self._tracer
            if tr is not None:
                # The launch boundary: queue/coalesce wait ends here;
                # batch assembly, executable fetch, and the dispatch
                # itself land between "launch" and "dispatched".
                for r in reqs:
                    tr.event(r.span, "launch", bucket=bucket)
            posed = reqs[0].subject is not None  # uniform kind (_coalesce)
            if staging is not None:
                # Staged assembly (PR 17): the rows were copied at
                # admit time; what remains is the pad fill — identical
                # bytes to pad_rows (repeat row 0).
                pose, shape = staging.finish(bucket)
            else:
                if len(reqs) == 1:
                    pose = reqs[0].pose
                else:
                    pose = np.concatenate([r.pose for r in reqs])
                pose = bucket_mod.pad_rows(pose, bucket)
                shape = None
                if not posed:
                    shape = (reqs[0].shape if len(reqs) == 1 else
                             np.concatenate([r.shape for r in reqs]))
                    shape = bucket_mod.pad_rows(shape, bucket)
            table = idx = None
            n_subjects = 1
            if self._lane_count is not None:
                if staging is not None:
                    # A lane batch outlives the dispatcher's recycling
                    # horizon (it queues on the lane), so it takes a
                    # compact copy and the slab returns to the pool
                    # right away.
                    pose = np.array(pose)
                    shape = None if shape is None else np.array(shape)
                    self._staging_release(staging)
                    staging = None
                # Lane-aware dispatch (PR 13): the assembled batch goes
                # to the least-backlogged healthy lane; that lane's
                # worker runs the supervised dispatch + failover ladder
                # and resolves the futures (count_dispatch and the
                # dispatched/readback span events land there). A posed
                # batch's slots are resolved IN THE WORKER against a
                # version-validated lane replica — resolving here and
                # dispatching later would let an eviction reuse a slot
                # while the batch sits in the lane's backlog. The
                # dispatcher immediately assembles the next batch —
                # lanes ARE the overlap, so the inflight deque stays
                # unused in this mode.
                self._get_lanes().submit_batch(
                    bucket, pose, shape, posed, reqs, rows,
                    # Sharded store (PR 16): every request in a posed
                    # batch shares one shard (the _admit "shard" split),
                    # so the batch routes to its owner lane.
                    shard=(self._shard_of(reqs[0].subject)
                           if posed else None))
                return None
            prec = self._req_prec(reqs[0]) if posed else "f32"
            if posed:
                # Resolved HERE (not in the completion worker): the
                # (table, slots) pair is a functional SNAPSHOT taken
                # under _exe_lock, so it stays self-consistent however
                # specialize/evict mutate the live table while the
                # batch waits in the stage — unlike a lane replica,
                # which is why lanes resolve in their workers instead.
                table, slots = self._resolve_batch(reqs)
                idx = bucket_mod.subject_index_rows(
                    slots, [r.rows for r in reqs], bucket)
                n_subjects = len(set(slots))
            stage = self._completion
            if stage is not None:
                # Pipelined dispatch (PR 17): hand the assembled batch
                # to the completion stage and assemble the next one
                # immediately — the dispatch itself, the readback, and
                # the future resolution all run on the stage worker,
                # in strict launch (FIFO) order. The closure captures
                # the functional table snapshot; executables for the
                # unsupervised paths are fetched HERE so a warm-up
                # compile stays on the dispatcher (the stage worker
                # never builds programs, it only runs them).
                if self._policy is not None:
                    def fn(pose=pose, shape=shape, reqs=reqs,
                           table=table, idx=idx, bucket=bucket,
                           prec=prec):
                        return self._supervised_dispatch(
                            bucket, pose, shape, reqs, table, idx,
                            prec=prec)
                elif posed:
                    exe = self._gather_executable(bucket, table, prec)
                    def fn(exe=exe, table=table, idx=idx, pose=pose):  # noqa: E306
                        return exe(table, idx, pose)
                else:
                    exe = self._executable(bucket)
                    def fn(exe=exe, pose=pose, shape=shape):  # noqa: E306
                        return exe(pose, shape)
                if tr is not None:
                    # Stamped BEFORE submit: a submit that blocks on
                    # stage backpressure is itself stage wait. The
                    # inflight field counts this batch in.
                    depth_now = stage.inflight() + 1
                    for r in reqs:
                        tr.event(r.span, "staged", inflight=depth_now)
                n = stage.submit(fn, reqs, rows, bucket, n_subjects,
                                 staging)
                self.counters.observe_pipeline_inflight(n)
                return None
            if self._policy is not None:
                # Supervised serial (depth 1): resolved to a HOST array
                # inside the policy's deadline/retry/failover envelope
                # before the next batch launches (bounded latency over
                # overlap).
                out = self._supervised_dispatch(bucket, pose, shape,
                                                reqs, table, idx,
                                                prec=prec)
            elif posed:
                out = self._gather_executable(bucket, table,
                                              prec)(table, idx, pose)
            else:
                exe = self._executable(bucket)
                out = exe(pose, shape)  # async dispatch: pre-completion
            self.counters.count_dispatch(bucket, rows,
                                         requests=len(reqs),
                                         subjects=n_subjects)
            if tr is not None:
                # Supervised dispatch returns a HOST array (device time
                # already paid); unsupervised returns an async handle —
                # either way this is where the batch left the engine.
                for r in reqs:
                    tr.event(r.span, "dispatched")
            return out, reqs, bucket, staging
        except ServingError as e:
            # Supervision exhausted for THIS batch: its futures get the
            # structured error and the dispatcher lives on — a failed
            # batch is traffic, not an engine invariant breach. (The
            # fault may clear; later submits must still be servable.)
            self._poison(reqs, e)
            self._staging_release(staging)
            return None
        except BaseException as e:
            # This batch's requests live only in our locals — the outer
            # crash handler cannot see them, so a caller blocked on one
            # of these futures would otherwise hang forever.
            self._poison(reqs, e)
            self._staging_release(staging)
            raise

    def _supervised_dispatch(self, bucket: int, pose, shape,
                             reqs, table, idx, prec: str = "f32"):
        """One batch through the full fault-tolerance envelope:
        supervised primary attempts (deadline + classified retries with
        backoff, breaker-gated), then CPU graceful degradation, then a
        structured ``ServingError``. Deterministic failures (compile
        errors, shape bugs) are NOT retried and NOT failed over — they
        propagate and stay engine-fatal, the pre-PR-3 contract. A
        pose-only batch (``table``/``idx`` set) runs the gathered
        primary; its fallback re-runs the FULL forward with each row's
        raw betas — mixed subjects included — in the same
        params-as-runtime-args program family, so failover stays
        bit-identical to a direct CPU bucketed call.

        Executables are fetched (and so possibly built) OUTSIDE the
        per-attempt deadline: builds are warm-up-class work — size the
        deadline for dispatch, and ``warmup()`` engines ahead of
        supervised traffic.
        """
        from mano_hand_tpu.runtime import supervise

        pol = self._policy
        breaker = pol.breaker
        if table is not None:
            exe = self._gather_executable(bucket, table, prec)
            primary = lambda: np.asarray(exe(table, idx, pose))  # noqa: E731
        else:
            exe = self._executable(bucket)
            primary = lambda: np.asarray(exe(pose, shape))   # noqa: E731

        # End-to-end deadline plumbing (PR 5): supervision gives up once
        # every request in the batch has expired — a retry or failover
        # past the LATEST member deadline produces a result nobody will
        # read. Any member without a deadline keeps the budget unbounded.
        # The bound is computed when THIS call starts (on the completion
        # worker when pipelined), from absolute monotonic deadlines —
        # so time a batch spent queued in the completion stage has
        # already been charged against it (supervise.batch_give_up_by).
        give_up_by = supervise.batch_give_up_by(
            r.deadline for r in reqs)
        tr = self._tracer
        if tr is None:
            on_retry = self.counters.count_retry
            on_kill = self.counters.count_deadline_kill
        else:
            def on_retry():
                self.counters.count_retry()
                tr.runtime_event("retry", bucket=bucket)

            def on_kill():
                # A deadline kill abandons a wedged worker thread — an
                # incident worth a flight-recorder capture, not just a
                # counter tick.
                self.counters.count_deadline_kill()
                tr.incident("deadline_kill", bucket=bucket)
        last = None
        attempts = 0
        if breaker is None or breaker.allow_primary():
            try:
                out = supervise.supervised_call(
                    primary,
                    deadline_s=pol.deadline_s,
                    retries=pol.retries,
                    backoff_s=pol.backoff_s,
                    backoff_cap_s=pol.backoff_cap_s,
                    jitter=pol.jitter,
                    give_up_by=give_up_by,
                    keep_trying=(breaker.allow_primary
                                 if breaker is not None else None),
                    on_retry=on_retry,
                    on_deadline_kill=on_kill,
                    on_attempt_failure=(breaker.record_failure
                                        if breaker is not None else None),
                    name=f"serve-dispatch-b{bucket}",
                )
                if breaker is not None:
                    breaker.record_success()
                return out
            except supervise.RetriesExhausted as e:
                last, attempts = e.cause, e.attempts
        # Deadline sweep at the post-primary boundary: the primary
        # attempts may have consumed the batch's whole deadline budget
        # (give_up_by kills the attempt at the LATEST member deadline,
        # so by then every member has expired), and an expired request
        # must not buy a fallback dispatch — nor resolve as
        # kind="error" when the only thing that failed is its own
        # deadline. Runs with cpu_fallback on OR off: each member
        # resolves as expired and the batch-level error reaches only
        # already-done futures (_poison's done() guard makes it a
        # no-op).
        now = time.monotonic()
        if all(self._is_expired(r, now) for r in reqs):
            for r in reqs:
                self._expire(r, "failover")
            raise ServingError(
                f"every request in the batch expired during the "
                f"primary attempts ({attempts}); no further dispatch "
                "attempted — no caller would read the result",
                phase="failover", kind="expired",
                attempts=attempts, cause=last)
        if pol.cpu_fallback:
            self.counters.count_failover()
            if tr is not None:
                tr.incident("failover", bucket=bucket, attempts=attempts)
            fb_shape = self._fallback_shape(reqs, bucket, shape,
                                            posed=table is not None)
            fb = self._fallback_executable(bucket)  # built un-deadlined
            try:
                return supervise.call_with_deadline(
                    lambda: np.asarray(fb(pose, fb_shape)),
                    pol.deadline_s, name=f"serve-fallback-b{bucket}")
            except BaseException as e:
                raise ServingError(
                    f"dispatch failed on the primary path "
                    f"({attempts} attempt(s)) AND the CPU fallback: "
                    f"{type(e).__name__}: {e}",
                    attempts=attempts, cause=e) from e
        raise ServingError(
            "dispatch failed: primary path "
            + ("unavailable (circuit breaker open)" if last is None
               else f"exhausted after {attempts} attempt(s): "
                    f"{type(last).__name__}: {last}")
            + " and cpu_fallback is disabled",
            attempts=attempts, cause=last)

    def _fallback_shape(self, reqs, bucket: int, shape, *, posed: bool):
        """The CPU degradation tier's shape argument — THE shared
        reconstruction (used by ``_supervised_dispatch`` and the lane
        ladder's last rung, serving/lanes.py, so the rule cannot
        drift): a full-path batch reuses its padded shape as-is; a
        pose-only batch re-materializes per-ROW betas (pad rows repeat
        request 0's betas, matching pad_rows/idx row 0)."""
        if not posed:
            return shape
        with self._exe_lock:
            betas = [self._subject_betas[r.subject] for r in reqs]
        fb_shape = bucket_mod.pad_rows(
            np.concatenate([
                np.broadcast_to(b[None], (r.rows, self._n_shape))
                for b, r in zip(betas, reqs)]),
            bucket)
        return np.ascontiguousarray(fb_shape)

    def _resolve(self, item) -> None:
        out, reqs, bucket, staging = item
        try:
            verts = np.asarray(out)  # blocks until the device batch is done
        except BaseException as e:
            self._poison(reqs, e)  # same reasoning as _launch
            raise
        finally:
            # The dispatch (and any readback above) has consumed the
            # staged slab either way — recycle it.
            self._staging_release(staging)
        self._deliver(reqs, verts, bucket)

    def _deliver(self, reqs, verts, bucket: int) -> None:
        """Slice one completed batch back into its requests' futures —
        the single delivery path, shared by the dispatcher's readback
        (``_resolve``) and the per-lane workers (serving/lanes.py), so
        the expiry-at-readback / late-result-discard / span-close
        discipline cannot drift between the two."""
        now = time.perf_counter()
        mono = time.monotonic()
        tr = self._tracer
        lo = 0
        for r in reqs:
            piece = verts[lo:lo + r.rows]
            lo += r.rows
            if tr is not None:
                # The batch's device wait ended at the np.asarray above;
                # what remains per request is host-side slice + future
                # delivery (the "readback" stage tail).
                tr.event(r.span, "readback")
            if self._is_expired(r, mono):
                # The result exists but arrived past the request's own
                # deadline: a stale pose is worthless (PAPER.md §0), so
                # the contract stays "a result WITHIN the deadline, or
                # expired" — never a late result that looks fresh.
                self._expire(r, "readback")
                continue
            # A shutdown sweep or a cancel() can win the race; either
            # way the late result is discarded, never served stale.
            if self._set_result_safe(r, piece[0] if r.squeeze else piece):
                self.counters.count_served(r.tier)
                if tr is not None:
                    tr.close(r.span, "ok", bucket=bucket)
            self._deregister(r)
            self.counters.record_latency(bucket, now - r.t_submit)

    # ------------------------------------------------- resolution guarantees
    # Every request is registered at submit and deregistered at the ONE
    # place its future is resolved; ``_sweep_live`` is the last-resort
    # resolver for a wedged/dead dispatcher. The invariant under test
    # (tests/test_runtime.py): no future handed out by submit() can ever
    # be waited on forever.
    def _register(self, req: _Request) -> int:
        """Returns the post-insert outstanding count (one lock hold —
        the unbounded submit path feeds it to observe_backlog without
        a second acquisition)."""
        with self._live_lock:
            self._live[id(req)] = req
            return len(self._live)

    def _deregister(self, req: _Request) -> None:
        with self._live_lock:
            self._live.pop(id(req), None)

    # ------------------------------------------- cancellation (PR 13)
    def _on_cancel(self, req: _Request) -> None:
        """One caller-initiated ``future.cancel()`` (fired exactly once
        by ``_CancellableFuture``): free the admission slot NOW — the
        deregister drops ``outstanding`` so a bounded engine admits a
        replacement immediately instead of after the deadline sweep —
        count it per tier, and close the span at its new terminal
        kind. The request object may still sit queued/parked; every
        dispatch boundary skips a cancelled future (``_skip_cancelled``
        / the done() guards), so it never buys a device row."""
        self.counters.count_cancelled(req.tier)
        if self._tracer is not None:
            self._tracer.close(req.span, "cancelled", phase="cancel")
        self._deregister(req)

    def _skip_cancelled(self, req: _Request) -> bool:
        """True iff ``req`` was cancelled (already counted/closed by
        the cancel hook — the sweep just drops the stale object)."""
        if req.future.cancelled():
            self._deregister(req)   # idempotent belt-over-braces
            return True
        return False

    def _set_result_safe(self, req: _Request, value) -> bool:
        """Resolve a future to a result unless something else (a
        cancel in the done()-check race window) got there first."""
        if req.future.done():
            return False
        try:
            req.future.set_result(value)
            return True
        except InvalidStateError:
            return False

    def _set_exception_safe(self, req: _Request, exc: BaseException,
                            ) -> bool:
        if req.future.done():
            return False
        try:
            req.future.set_exception(exc)
            return True
        except InvalidStateError:
            return False

    @staticmethod
    def _terminal_kind(exc: Optional[BaseException]) -> str:
        """The span-close kind for an exception-resolved future —
        exactly the ``ServingError.kind`` the caller sees; any other
        exception class is an engine "error"."""
        if isinstance(exc, ServingError):
            return exc.kind
        return "shutdown" if exc is None else "error"

    def _sweep_live(self, exc: BaseException) -> None:
        with self._live_lock:
            reqs, self._live = list(self._live.values()), {}
        kind = self._terminal_kind(exc)
        for r in reqs:
            if self._set_exception_safe(r, exc):
                if self._tracer is not None:
                    self._tracer.close(r.span, kind, phase="sweep")

    def _poison(self, reqs, exc: BaseException) -> None:
        kind = self._terminal_kind(exc)
        for r in reqs:
            if self._set_exception_safe(r, exc):
                if self._tracer is not None:
                    self._tracer.close(r.span, kind, phase="poison")
            self._deregister(r)

    def _drain_cancelled(self, exc: Optional[BaseException] = None) -> None:
        """After stop()/crash: no request future may hang forever."""
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if req is _SENTINEL:
                continue
            err = (exc if exc is not None else
                   ServingError("serving engine stopped before this "
                                "request was dispatched",
                                phase="shutdown"))
            if self._set_exception_safe(req, err):
                if self._tracer is not None:
                    self._tracer.close(req.span, self._terminal_kind(err),
                                       phase="drain")
            self._deregister(req)
