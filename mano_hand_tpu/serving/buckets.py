"""Shape-bucket policy for the serving/fitting hot paths.

Every jitted entry point retraces — and on the tunneled chip recompiles,
at minutes of dead time — for each NOVEL leading batch dimension. The
fix is a shape policy: round every request batch up to a power-of-two
bucket, pad the tail rows, and mask them back out of the results. The
whole request universe then compiles into ``log2(max_bucket)`` programs,
once, ever.

Padding is row-independent by construction: the batched forward is a
``vmap`` over independent per-row programs, so pad rows cannot perturb
live rows — the engine's padded/masked results are bit-identical to a
direct unpadded call at the same dtype (pinned in tests/test_serving.py).

This module is pure numpy/python (no jax import): the bucket policy is
host-side bookkeeping, usable from the engine, the model layer, and the
fitting wrappers without dragging a backend in.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


def bucket_sizes(min_bucket: int = 1, max_bucket: int = 1024) -> Tuple[int, ...]:
    """The powers of two in [min_bucket, max_bucket], endpoints rounded up.

    >>> bucket_sizes(8, 64)
    (8, 16, 32, 64)
    """
    if min_bucket < 1 or max_bucket < min_bucket:
        raise ValueError(
            f"need 1 <= min_bucket <= max_bucket, got "
            f"({min_bucket}, {max_bucket})")
    lo = 1 << (int(min_bucket) - 1).bit_length()
    hi = 1 << (int(max_bucket) - 1).bit_length()
    return tuple(1 << e for e in range(lo.bit_length() - 1,
                                       hi.bit_length()))


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """The smallest bucket >= n. ``buckets`` must be sorted ascending.

    Raises when n exceeds the largest bucket: a silently truncated
    request would drop rows, and a silently grown one would recompile —
    the caller decides (the engine rejects at submit; batch workloads
    chunk upstream via ``core.forward_chunked``).
    """
    if n < 1:
        raise ValueError(f"request rows must be >= 1, got {n}")
    for b in buckets:
        if n <= b:
            return int(b)
    raise ValueError(
        f"request of {n} rows exceeds the largest bucket "
        f"{buckets[-1]}; raise max_bucket or chunk the request")


def pad_rows(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Pad ``arr``'s leading dim up to ``bucket`` by repeating row 0.

    Row 0 (real data) rather than zeros: pad rows then run the exact
    numeric regime of live traffic — no denormals, no degenerate
    geometry — so a pad row can never cost more than a live row, and
    fitting pad problems converge like their live neighbours instead of
    wandering. Works on numpy and jax arrays (returns the input's kind).
    """
    n = arr.shape[0]
    if n == bucket:
        return arr
    if n > bucket:
        raise ValueError(f"cannot pad {n} rows down to bucket {bucket}")
    if isinstance(arr, np.ndarray):
        pad = np.broadcast_to(arr[:1], (bucket - n, *arr.shape[1:]))
        return np.concatenate([arr, pad])
    import jax.numpy as jnp

    pad = jnp.broadcast_to(arr[:1], (bucket - n, *arr.shape[1:]))
    return jnp.concatenate([arr, pad])


def subject_index_rows(slots: Sequence[int], rows: Sequence[int],
                       bucket: int) -> np.ndarray:
    """The per-row int32 subject index of a coalesced mixed-subject batch.

    Request ``k`` contributes ``rows[k]`` rows of table slot
    ``slots[k]``; the result is padded to ``bucket`` by repeating row 0
    (the ``pad_rows`` contract: pad rows replay live traffic's regime,
    here the first request's subject). Host-side bookkeeping like the
    rest of this module — the produced array is the gathered dispatch's
    ``subject_idx`` runtime argument.
    """
    slots = np.asarray(slots, np.int32)
    rows = np.asarray(rows, np.int64)
    if slots.shape != rows.shape:
        raise ValueError(
            f"slots and rows must pair up, got {slots.shape} vs "
            f"{rows.shape}")
    if rows.size and rows.min() < 1:
        raise ValueError("every request must contribute >= 1 row")
    idx = np.repeat(slots, rows)
    if idx.size < 1:
        raise ValueError("a batch needs at least one row")
    return pad_rows(idx, bucket)


def pad_tree_rows(tree: dict, bucket: int) -> dict:
    """``pad_rows`` over every leaf of a flat {name: array} dict (warm-start
    seeds for the bucketed fit wrappers)."""
    return {k: pad_rows(np.asarray(v), bucket) for k, v in tree.items()}
