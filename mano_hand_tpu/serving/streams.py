"""Streaming sessions: per-user hand tracking as a served workload.

Eleven PRs built serving machinery for STATELESS forwards; real traffic
(PAPER.md §0 — interactive hand tracking) is per-user streams of
CORRELATED frames: one subject, one identity, frame t's solution a few
millimeters from frame t-1's. This module is the product shape those
PRs were for — ``ServingEngine.open_stream(subject)`` returns a
session-affine handle whose per-frame step composes the whole stack:

* **frozen-shape LM fitting** (the PR-2 48-col path) is the per-frame
  solve: the subject's betas are a known constant, so every frame fits
  pose only, WARM-STARTED from the last converged pose via
  ``fitting/tracking.py:make_tracker`` — a handful of GN steps suffice
  because the solution moved only as far as the hand did. All sessions
  with the same target/step geometry share ONE compiled LM program
  (shapes are static), so the N-th stream compiles nothing.
* **cross-session coalescing** (PR 4): the converged pose is served
  back through ``engine.submit(pose, subject=key)`` — the gathered
  SubjectTable dispatch — so concurrent streams' frames merge into one
  mixed-subject batch per bucket. N streams share one program family
  with zero steady recompiles; the frame's verts are bit-identical to
  the per-subject posed program.
* **tier-0 per-frame deadlines** (PR 5): every frame carries an
  end-to-end TTL spanning fit + dispatch. A frame already expired is
  swept BEFORE the fit (no solver time on a result nobody reads) and
  the remaining budget rides the engine's own deadline sweeps; an
  expired frame resolves ``kind="expired"``, never late-but-fresh.
* **lifecycle spans** (PR 8): each session carries a tracer span from
  ``open`` to exactly one terminal — ``closed`` (client close),
  ``expired`` (idle past ``idle_timeout_s``), ``shed`` (admission
  refused the open), or ``shutdown`` (``engine.stop`` swept it) —
  while each frame rides the engine's own request span. "Every stream
  closes exactly once" and "every frame resolves" are judged by the
  same flight-record accounting as every drill.
* **SLOs** (PR 9): frames are tier-0 traffic, so the per-tier
  burn-rate report covers them; the stream drill
  (serving/measure.py:stream_drill_run, bench config15) adds a frame-
  latency-p99 objective on top.

Chaos, failover, and overload compose UNCHANGED: the serving half of a
frame is an ordinary engine request, so a CPU-failover frame is
bit-identical to a direct CPU call (the PR-3 contract), and — because
the fit runs BEFORE dispatch and never touches the chaos-wrapped
executables — the warm start stays valid through any serving fault.
The PR-17 dispatch pipeline composes the same way: at
``inflight_depth > 1`` a frame's future may resolve on the engine's
completion-stage thread rather than the dispatcher, which is invisible
here because frames are ordinary requests and the manager's single
lock is thread-agnostic — per-frame FIFO within a session still holds
(the stage completes strictly in launch order).

Locking: the ``StreamManager`` owns ONE lock guarding the registry and
every session's lifecycle fields (terminal kind, in-flight frame table,
last-activity stamp), so ``snapshot()`` — the ``ServingEngine.load()``
streams block — is a single lock-held copy (the PR-5 torn-telemetry
rule). Each session owns a separate ``_fit_lock`` that only serializes
its warm-start chain (frame N+1's fit must see frame N's pose); the two
are never nested, and neither is ever held across an engine lock —
tracer span closes are staged outside the manager lock.

Typical use::

    eng = ServingEngine(params, ...)
    with eng:
        sess = eng.open_stream(user_betas, frame_deadline_s=0.05)
        for target in sensor:                 # [J, 3] keypoints
            fut = sess.submit_frame(target)   # fit + gathered dispatch
            res = fut.result()                # FrameResult(pose, verts)
        sess.close()
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import NamedTuple, Optional

import numpy as np

from mano_hand_tpu.serving.engine import ServingError

_UNSET = object()

#: Stream terminal kinds — the session-lifecycle vocabulary (a strict
#: superset member, "closed", joins the engine's request kinds; see
#: obs/trace.py:TERMINAL_KINDS).
STREAM_TERMINAL_KINDS = ("closed", "expired", "shed", "shutdown")

#: The ``ServingEngine.load()["streams"]`` keys when no stream was ever
#: opened — kept in lockstep with ``StreamManager.snapshot`` (pinned in
#: tests/test_streams.py) so the load surface never changes shape.
EMPTY_SNAPSHOT = {
    "active": 0,
    "opened": 0,
    "frames_submitted": 0,
    "frames_resolved": 0,
    "frames_in_flight": 0,
    "backlog_age_s": 0.0,
    "closed_by_kind": {},
    "frames_by_kind": {},
}


def empty_snapshot() -> dict:
    """A FRESH empty streams block (``ServingEngine.load()`` uses
    this, never the constant: a shallow ``dict(EMPTY_SNAPSHOT)`` would
    alias the nested by-kind dicts, and one consumer mutating its
    load() result would corrupt every later snapshot)."""
    return {**EMPTY_SNAPSHOT, "closed_by_kind": {},
            "frames_by_kind": {}}


class _FrameFuture(Future):
    """The Future ``submit_frame`` hands out, with caller cancellation
    FORWARDED to the engine-level request future (PR 13's
    ``_CancellableFuture``): a network edge whose client disconnects
    mid-frame calls ``cancel()`` here, and the engine's cancel
    bookkeeping fires — admission slot freed, request span closed as
    terminal kind ``cancelled``, the dispatch boundary skips the work.
    Without the forwarding, cancelling the frame future would strand
    the underlying engine request until its deadline sweep.

    ``_attach`` is called once the serving dispatch exists; a cancel
    landing BEFORE that (the fit is still running in the submitter's
    thread) is honored at attach time — the engine request is
    cancelled the instant it is created.
    """

    def __init__(self):
        super().__init__()
        self._vfut: Optional[Future] = None
        self._vlock = threading.Lock()

    def _attach(self, vfut: Future) -> None:
        with self._vlock:
            self._vfut = vfut
            cancelled = self.cancelled()
        if cancelled:
            vfut.cancel()

    def cancel(self) -> bool:
        if not super().cancel():
            return False
        with self._vlock:
            vfut = self._vfut
        if vfut is not None:
            # Outside _vlock: the engine-side hook does counter/span
            # work that must never run under a streams-layer lock.
            vfut.cancel()
        return True


class FrameResult(NamedTuple):
    """One resolved stream frame: the converged pose (the next frame's
    warm start) and the posed verts served through the gathered
    engine dispatch (bit-identical to the per-subject posed program)."""

    pose: np.ndarray       # [J, 3] converged axis-angle pose
    verts: np.ndarray      # [V, 3] posed verts from the engine
    fit_loss: float        # final LM residual (frozen-shape, pose-only)
    frame: int             # 0-based frame index within the session


class StreamSession:
    """Session-affine handle over one subject's frame stream.

    Built by ``ServingEngine.open_stream`` — not directly. Frames are
    serialized per session (the warm-start chain is causal); DIFFERENT
    sessions' frames run concurrently and their serving dispatches
    coalesce in the engine.
    """

    def __init__(self, manager: "StreamManager", stream_id: int,
                 subject: str, betas: np.ndarray, span, state, step,
                 frame_deadline_s: Optional[float],
                 idle_timeout_s: Optional[float]):
        self._mgr = manager
        self.stream_id = stream_id
        self.subject = subject          # the specialize() key
        self.betas = betas              # frozen shape (the CPU-failover
        #   tier re-derives the full forward from these — engine-owned)
        self.span = span                # PR-8 stream-lifecycle span id
        self.frame_deadline_s = frame_deadline_s
        self.idle_timeout_s = idle_timeout_s
        # Warm-start chain: guarded by _fit_lock (never nested with the
        # manager lock — see the module docstring).
        self._fit_lock = threading.Lock()
        self._state = state
        self._step = step
        # Lifecycle fields below are guarded by the MANAGER's lock.
        self.terminal: Optional[str] = None
        self.last_activity = time.monotonic()
        self.inflight: dict = {}        # frame id -> submit t (monotonic)
        self.frames_submitted = 0
        self.frames_by_kind: dict = {}

    # ------------------------------------------------------------- frames
    @property
    def pose(self) -> np.ndarray:
        """The current warm start ([J, 3]) — the last converged pose."""
        with self._fit_lock:
            return np.asarray(self._state.pose)

    @property
    def frame(self) -> int:
        """Frames the tracker has consumed so far."""
        with self._fit_lock:
            return int(self._state.frame)

    def submit_frame(self, target, *, deadline_s=_UNSET) -> Future:
        """Fit one frame and serve its verts; returns a Future of a
        ``FrameResult``.

        The frozen-shape LM solve runs in the CALLING thread (warm-
        started under the session's fit lock, so concurrent submitters
        chain causally), then the converged pose is submitted through
        the engine's gathered pose-only path at tier 0 with whatever
        remains of the frame's deadline. Every outcome is structured:
        ``ok`` (a FrameResult), or a ``ServingError`` of kind ``shed``
        / ``expired`` / ``error`` / ``shutdown`` SET ON the future —
        never raised from here, never stranded — except a frame sent
        to a stream already at a terminal, which raises immediately
        (kind="shed", phase="stream": the session refused admission).
        Cancelling the returned future forwards to the engine request
        (PR 13 — the network edge's client-disconnect path): the
        frame resolves CANCELLED and the ledger records the terminal.
        """
        eng = self._mgr.engine
        if deadline_s is _UNSET:
            deadline_s = self.frame_deadline_s
        fid = self._mgr.admit_frame(self)   # raises if terminal
        tr = eng.tracer
        if tr is not None:
            tr.event(self.span, "frame", n=fid)
        fut: Future = _FrameFuture()
        deadline = (None if deadline_s is None
                    else time.monotonic() + float(deadline_s))
        loss = float("nan")
        try:
            with self._fit_lock:
                if deadline is None or time.monotonic() < deadline:
                    state, res = self._step(self._state, target)
                    # Force the solve INSIDE the lock so the state
                    # frame N+1 warm-starts from is frame N's converged
                    # pose, not an in-flight device value.
                    pose = np.asarray(res.pose)
                    loss = float(np.asarray(res.final_loss))
                    self._state = state
                else:
                    # Expired before the fit: no solver time is spent —
                    # the warm pose rides to the engine's born-expired
                    # path below purely so the expiry is counted and
                    # span-closed by the one resolution machinery.
                    pose = np.asarray(self._state.pose)
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            vfut = eng.submit(pose, subject=self.subject, priority=0,
                              deadline_s=remaining)
        except ServingError as e:
            # Admission shed (or born-expired raced): structured
            # resolution on the frame future — the caller has ONE
            # channel for every outcome.
            self._mgr.frame_done(self, fid, e.kind)
            fut.set_exception(e)
            return fut
        except BaseException as e:  # noqa: BLE001 — never strand a frame
            self._mgr.frame_done(self, fid, "error")
            fut.set_exception(e)
            return fut

        def _resolve(f, pose=pose, loss=loss, fid=fid):
            if f.cancelled():
                # PR-13 caller cancellation (forwarded by _FrameFuture
                # or aimed at the engine future directly): the engine
                # already freed the slot and closed the request span as
                # ``cancelled``; mirror the terminal on the frame
                # future + session ledger.
                fut.cancel()
                self._mgr.frame_done(self, fid, "cancelled")
                return
            exc = f.exception()
            try:
                if exc is None:
                    fut.set_result(FrameResult(
                        pose=pose, verts=f.result(), fit_loss=loss,
                        frame=fid))
                    kind = "ok"
                else:
                    fut.set_exception(exc)
                    kind = (exc.kind if isinstance(exc, ServingError)
                            else "error")
            except InvalidStateError:
                # The frame future was cancelled in the gap between
                # the cancelled() check and resolution: the result is
                # discarded (the late-result discipline) and the frame
                # records the caller's terminal.
                kind = "cancelled"
            self._mgr.frame_done(self, fid, kind)

        fut._attach(vfut)
        vfut.add_done_callback(_resolve)
        return fut

    def step(self, target, *, deadline_s=_UNSET) -> FrameResult:
        """Synchronous convenience: ``submit_frame(...).result()``."""
        return self.submit_frame(target, deadline_s=deadline_s).result()

    # ---------------------------------------------------------- lifecycle
    def close(self) -> bool:
        """Resolve this session's span with the ``closed`` terminal;
        returns False when it already reached a terminal (idempotent —
        a double close is a no-op, never a double span close)."""
        return self._mgr.close(self, "closed")

    def __enter__(self) -> "StreamSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class StreamManager:
    """Registry + lifecycle owner for an engine's stream sessions.

    One lock guards everything the ``snapshot()`` reports — the
    registry, per-session terminals, in-flight frame tables, activity
    stamps — so ``ServingEngine.load()``'s streams block is a single
    lock-held copy (the torn-telemetry rule). Span closes are staged
    OUTSIDE the lock (the tracer calls nothing back, but the dispatch
    path must never queue behind telemetry).
    """

    def __init__(self, engine):
        self.engine = engine
        self._lock = threading.Lock()
        self._active: dict = {}         # stream id -> StreamSession
        # Sessions that reached a terminal with frames still in
        # flight: their frames must stay visible to snapshot() until
        # they resolve (the ledger's two views — frames_in_flight and
        # submitted-minus-resolved — must never contradict), then the
        # entry drops, so memory stays bounded by in-flight work.
        self._draining: dict = {}
        # Set by shutdown() UNDER the lock, checked by register()'s
        # insertion hold: an open_stream racing (or following)
        # engine.stop() must be refused, not registered into a manager
        # whose one-shot sweep already ran — that session's span would
        # never close. engine.start() re-opens (the documented
        # stop()/start() restart).
        self._stopped = False
        # Active sessions that carry an idle_timeout_s: the sweep's
        # fast path — with none, admit_frame's per-frame sweep is one
        # counter read under the lock, never an O(active) scan.
        self._idle_sessions = 0
        self._next_id = 1
        self.opened = 0
        self.frames_submitted = 0
        self.frames_resolved = 0
        self.closed_by_kind: dict = {}
        self.frames_by_kind: dict = {}

    # ------------------------------------------------------------ opening
    def register(self, session_factory) -> StreamSession:
        """Allocate an id and register the session the factory builds
        (the factory runs OUTSIDE the lock — it compiles nothing, but
        it does build tracker closures). Raises a structured
        ``ServingError(kind="shutdown")`` when the manager was swept
        by ``engine.stop()`` — including a stop that lands BETWEEN the
        two lock holds here (the caller owns closing its span)."""
        with self._lock:
            if self._stopped:
                raise ServingError(
                    "engine stopped; open_stream refused (restart the "
                    "engine to open new streams)",
                    phase="stream", kind="shutdown")
            sid = self._next_id
            self._next_id += 1
        sess = session_factory(sid)
        with self._lock:
            if self._stopped:
                raise ServingError(
                    "engine stopped while this stream was opening; "
                    "open_stream refused (restart the engine)",
                    phase="stream", kind="shutdown")
            self._active[sid] = sess
            self.opened += 1
            if sess.idle_timeout_s is not None:
                self._idle_sessions += 1
        return sess

    def reopen(self) -> None:
        """``engine.start()``'s hook: a restarted engine accepts new
        streams again (already-swept sessions stay terminal)."""
        with self._lock:
            self._stopped = False

    # ------------------------------------------------------------- frames
    def admit_frame(self, sess: StreamSession) -> int:
        """Admission for one frame: sweeps idle-expired sessions first,
        then registers the frame in-flight. Raises a structured
        ``ServingError(kind="shed", phase="stream")`` when the session
        already reached a terminal — a closed stream refuses frames the
        way a full queue refuses submits."""
        self.sweep_idle()
        now = time.monotonic()
        with self._lock:
            if sess.terminal is not None:
                terminal = sess.terminal
            else:
                fid = sess.frames_submitted
                sess.frames_submitted += 1
                sess.inflight[fid] = now
                sess.last_activity = now
                self.frames_submitted += 1
                return fid
        raise ServingError(
            f"stream {sess.stream_id} is {terminal}; frames after a "
            "terminal are refused — open a new stream (the warm pose "
            "is available as session.pose)",
            phase="stream", kind="shed")

    def frame_done(self, sess: StreamSession, fid: int,
                   kind: str) -> None:
        with self._lock:
            sess.inflight.pop(fid, None)
            sess.last_activity = time.monotonic()
            self.frames_resolved += 1
            sess.frames_by_kind[kind] = sess.frames_by_kind.get(kind, 0) + 1
            self.frames_by_kind[kind] = self.frames_by_kind.get(kind, 0) + 1
            if sess.terminal is not None and not sess.inflight:
                self._draining.pop(sess.stream_id, None)

    # ---------------------------------------------------------- lifecycle
    def close(self, sess: StreamSession, kind: str) -> bool:
        """Move one session to a terminal exactly once; the first
        caller wins and closes the span, a repeat is a no-op."""
        with self._lock:
            if sess.terminal is not None:
                return False
            sess.terminal = kind
            self._active.pop(sess.stream_id, None)
            if sess.idle_timeout_s is not None:
                self._idle_sessions -= 1
            if sess.inflight:
                self._draining[sess.stream_id] = sess
            self.closed_by_kind[kind] = self.closed_by_kind.get(kind, 0) + 1
        tr = self.engine.tracer
        if tr is not None:
            # Outside the lock: span closes are telemetry, and the
            # frame path must never queue behind them.
            tr.close(sess.span, kind, frames=sess.frames_submitted)
        return True

    def sweep_idle(self, now: Optional[float] = None) -> int:
        """Expire sessions idle past their ``idle_timeout_s`` — the
        deadline-pressure eviction: a stream nobody feeds must not pin
        its span (or its admission slot) forever. Swept at every frame
        admission AND every ``snapshot()`` (the ``load()``/status
        polling path), so expiry needs frame traffic OR monitoring —
        a fully untouched engine sweeps at its next stop(). Returns
        the number expired this sweep."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if self._idle_sessions == 0:
                return 0       # fast path: nothing can expire
            victims = [s for s in self._active.values()
                       if s.idle_timeout_s is not None
                       and now - s.last_activity >= s.idle_timeout_s]
        n = 0
        for s in victims:
            if self.close(s, "expired"):
                n += 1
        return n

    def shutdown(self) -> int:
        """``engine.stop``'s sweep: every still-open session reaches
        the ``shutdown`` terminal (span closed exactly once); in-flight
        frames resolve through the engine's own future sweeps, and new
        registrations are refused until ``engine.start()`` reopens."""
        with self._lock:
            self._stopped = True
            open_now = list(self._active.values())
        n = 0
        for s in open_now:
            if self.close(s, "shutdown"):
                n += 1
        return n

    # ------------------------------------------------------------ reading
    def snapshot(self) -> dict:
        """The ``load()`` streams block: active count, frame ledger,
        and the backlog age (the oldest in-flight frame across every
        session), all from ONE lock hold — a snapshot racing live
        frames is internally consistent, never a torn tuple. Also
        sweeps idle expiry first (outside the snapshot hold), so a
        session nobody feeds expires on the monitoring path, not just
        at the next frame admission."""
        self.sweep_idle()
        now = time.monotonic()
        with self._lock:
            inflight = 0
            oldest = None
            for table in (self._active, self._draining):
                for s in table.values():
                    inflight += len(s.inflight)
                    for t0 in s.inflight.values():
                        if oldest is None or t0 < oldest:
                            oldest = t0
            return {
                "active": len(self._active),
                "opened": self.opened,
                "frames_submitted": self.frames_submitted,
                "frames_resolved": self.frames_resolved,
                "frames_in_flight": inflight,
                "backlog_age_s": (0.0 if oldest is None
                                  else max(0.0, now - oldest)),
                "closed_by_kind": dict(self.closed_by_kind),
                "frames_by_kind": dict(self.frames_by_kind),
            }


def open_stream(engine, subject, *, n_steps: int = 4,
                data_term: str = "joints", solver: str = "lm",
                frame_deadline_s: Optional[float] = None,
                idle_timeout_s: Optional[float] = None,
                resume_pose=None, **tracker_kw) -> StreamSession:
    """``ServingEngine.open_stream``'s implementation (see the engine
    method's docstring for the caller-facing contract)."""
    from mano_hand_tpu.fitting import tracking

    mgr = engine._stream_manager()
    # Resolve the subject to (key, betas). An ARRAY is the natural
    # identity — unknown betas simply bake (specialize is idempotent),
    # and an EVICTED subject's key stays servable because the betas
    # registry outlives its table row (the row re-bakes at dispatch).
    if isinstance(subject, str):
        with engine._exe_lock:
            betas = engine._subject_betas.get(subject)
        if betas is None:
            raise ValueError(
                f"unknown subject {subject!r}; pass the betas array "
                "(open_stream bakes it) or a specialize() key")
        key = subject
        # Tiered store (PR 16): start the async host->device promotion
        # BEFORE the (re-)bake below — an evicted-but-warm subject's
        # row transfer overlaps the open instead of stalling it.
        engine._prefetch_subject(key)
        engine.specialize(betas)    # refresh LRU; re-bake if evicted
    else:
        betas = np.ascontiguousarray(
            np.asarray(subject, engine._dtype).reshape(engine._n_shape))
        from mano_hand_tpu.serving.subject_store import subject_digest

        engine._prefetch_subject(subject_digest(betas))
        key = engine.specialize(betas)

    tr = engine.tracer
    span = tr.start("stream", tier=0) if tr is not None else None
    # Stream-open admission (PR 5): under a bounded queue, a tier-0
    # outstanding count at quota means every frame this stream submits
    # right now would shed — refuse the OPEN with the same structured
    # kind instead of handing back a handle that can only shed. The
    # check is advisory (a racing submit can still fill the queue);
    # per-frame admission stays the hard bound.
    if engine.max_queued is not None:
        with engine._live_lock:
            outstanding = len(engine._live)
        quota = engine._quota(0)
        if outstanding >= quota:
            if tr is not None:
                tr.close(span, "shed")
            raise ServingError(
                f"stream open shed: {outstanding} outstanding >= tier-0 "
                f"quota {quota} — the engine is over capacity; poll "
                "load() and retry",
                phase="stream", kind="shed")

    try:
        state, step = tracking.make_tracker(
            engine._params, n_steps=n_steps, solver=solver,
            data_term=data_term, frozen_shape=betas,
            init_pose=resume_pose, **tracker_kw)

        def factory(sid: int) -> StreamSession:
            return StreamSession(
                mgr, sid, key, betas, span, state, step,
                frame_deadline_s=frame_deadline_s,
                idle_timeout_s=idle_timeout_s)

        sess = mgr.register(factory)
    except ServingError as e:
        # A stopped-manager refusal (register's shutdown race) keeps
        # its own terminal kind on the span.
        if tr is not None:
            tr.close(span, e.kind)
        raise
    except BaseException:
        # A tracker-build error (bad solver/tracker_kw) must not leak
        # the just-opened span — the closed-exactly-once accounting is
        # a judged criterion, and one leak fails every later drill on
        # this tracer.
        if tr is not None:
            tr.close(span, "error")
        raise
    if tr is not None:
        tr.event(span, "open", subject=key, stream=sess.stream_id)
    return sess
