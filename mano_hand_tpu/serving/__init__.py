"""Serving layer: shape-bucketed dynamic micro-batching for the hot paths.

The production-traffic story (ROADMAP north star): independent
forward/fitting requests with ragged batch sizes are coalesced into
power-of-two shape buckets, dispatched through a per-bucket compiled
executable cache (in-memory + persistent AOT artifacts), and overlapped
with host-side batch assembly via double-buffered async dispatch.

    from mano_hand_tpu.serving import ServingEngine, bucket_for, bucket_sizes
"""

from mano_hand_tpu.serving.buckets import (
    bucket_for,
    bucket_sizes,
    pad_rows,
    pad_tree_rows,
    subject_index_rows,
)
from mano_hand_tpu.serving.engine import ServingEngine, ServingError
from mano_hand_tpu.serving.lanes import Lane, LaneSet
from mano_hand_tpu.serving.measure import (
    coalesce_bench_run,
    cold_start_drill_run,
    lane_drill_run,
    measure_overhead,
    overload_drill_run,
    precision_bench_run,
    recovery_drill_run,
    serve_bench_run,
    stream_drill_run,
)
from mano_hand_tpu.serving.precision import PrecisionPolicy
from mano_hand_tpu.serving.streams import (
    FrameResult,
    StreamManager,
    StreamSession,
)

__all__ = [
    "ServingEngine",
    "ServingError",
    "FrameResult",
    "Lane",
    "LaneSet",
    "StreamManager",
    "StreamSession",
    "coalesce_bench_run",
    "cold_start_drill_run",
    "lane_drill_run",
    "overload_drill_run",
    "precision_bench_run",
    "PrecisionPolicy",
    "recovery_drill_run",
    "measure_overhead",
    "serve_bench_run",
    "stream_drill_run",
    "bucket_for",
    "bucket_sizes",
    "pad_rows",
    "pad_tree_rows",
    "subject_index_rows",
]
