"""Closed-loop control: the SLO layer drives the engine and the edge
(PR 19 tentpole).

Every throughput knob in the stack was static and hand-picked — the
coalesce window base (PR 17), the per-tier admission quotas and shed
thresholds (PR 5), the bucket ladder's selection rung, the edge's
per-tier Retry-After (PR 15), the subject store's warm capacity
(PR 16) — while the signals to drive them were already exported:
per-tier error-budget burn rates (``obs.metrics.slo_report``, PR 9),
backlog age and per-lane telemetry (``load()``, PR 8/13), stream
latency quantiles (PR 12).  ``Controller`` closes the loop: a thread
that, at a bounded cadence, reads ONE-lock-hold snapshots of that
telemetry and actuates the engine's live setters
(``set_coalesce_base`` / ``set_admission`` / ``set_bucket_bias``,
``SubjectStore.resize_warm``) and the edge's ``retry_after_source``.

The control law, in one paragraph: tier 0's error-budget burn rate is
the protected signal.  While tier 0 burns COLD (every burn rate under
``tier0_burn_low``), the gap between the static tier-1 quota and the
queue bound is idle headroom — the controller reallocates it, growing
the tier-1 quota toward ``tier1_quota_max_fraction`` of ``max_queued``
so batch traffic that a static config would shed gets served.  The
moment tier 0 burns HOT (any burn rate over ``tier0_burn_high``), the
tier-1 quota walks back below its static default and tier-1's
Retry-After grows — admission control at the wire, not just at
submit.  Independently, backlog age drives the coalesce window base
down (waiting buys nothing a backlog can't fill) and back up when the
queue drains; sustained warm-tier misses grow the subject store's warm
capacity, idleness shrinks it home.

Discipline, because a controller that misbehaves is worse than no
controller:

* **Hysteresis** — every signal has a low and a high watermark; in the
  deadband between them the controller holds.  No decision flaps on a
  signal hovering at one threshold.
* **Rate limits** — per-actuator minimum re-actuation interval
  (``min_actuation_interval_s``) and a maximum relative step
  (``max_step_fraction``); a panicked signal cannot slam a knob across
  its range in one tick.
* **Bounds** — every actuator has hard floors/ceilings
  (``ControlConfig``); the engine's own setters re-validate.
* **Evented** — every actuation lands on the PR-8 timeline as a
  ``runtime_event("control", actuator=..., before=..., after=...)``
  with the decision's reason, and bumps
  ``ServingCounters.control_actuations``.
* **Crash = static defaults** — the tick thread's failure path reverts
  every actuator to the values captured at ``start()`` (each revert
  independently best-effort, so one failing setter cannot wedge the
  rest), marks the snapshot ``crashed``, and files a flight-recorder
  incident.  A dead controller degrades to today's hand-picked
  behavior; it can never wedge admission — the engine's setters hold
  no lock across any callout, and ``retry_after_for`` falls back to
  the static protocol formula the moment the controller is crashed.

``load()["control"]`` is this module's telemetry block, built in ONE
controller-lock hold (the torn-telemetry rule every other load()
sub-block follows); ``empty_snapshot()`` keeps the surface
shape-stable on engines with no controller attached.

Clocks are ``time.monotonic`` throughout (the analysis wallclock
rule).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["ControlConfig", "Controller", "empty_snapshot"]


#: Keys every control block carries — ``empty_snapshot`` and
#: ``Controller.snapshot`` are pinned to the same set in tests, so a
#: scrape/consumer never branches on controller presence.
_SNAPSHOT_KEYS = (
    "attached", "running", "crashed", "ticks", "actuations", "reverts",
    "version", "values", "last_reason", "history",
)

#: Bounded actuation history (forensics in the snapshot; the full
#: stream is on the tracer timeline).
_HISTORY = 32


def empty_snapshot() -> dict:
    """The shape-stable ``load()["control"]`` block of an engine with
    no controller attached (or whose controller's snapshot source
    failed) — same keys as ``Controller.snapshot``, all zeros."""
    return {
        "attached": False,
        "running": False,
        "crashed": False,
        "ticks": 0,
        "actuations": 0,
        "reverts": 0,
        "version": 0,
        "values": {},
        "last_reason": None,
        "history": [],
    }


class ControlConfig:
    """Bounds, watermarks, and pacing for one ``Controller``.

    The defaults are deliberately conservative: watermarks a healthy
    engine never crosses, steps that take several decisions to
    traverse an actuator's range.  Every field is validated — a typo'd
    config must fail construction, not silently misdrive the engine
    (the chaos-grammar precedent)."""

    def __init__(self, *,
                 cadence_s: float = 0.25,
                 hysteresis: float = 0.5,
                 min_actuation_interval_s: float = 0.5,
                 max_step_fraction: float = 0.5,
                 tier0_burn_high: float = 1.0,
                 tier0_burn_low: Optional[float] = None,
                 backlog_age_high_s: float = 0.25,
                 backlog_age_low_s: Optional[float] = None,
                 coalesce_min_s: float = 0.0,
                 coalesce_max_s: float = 0.05,
                 tier1_quota_min_fraction: float = 0.25,
                 tier1_quota_max_fraction: float = 0.95,
                 retry_after_max_s: int = 8,
                 bucket_bias_max: int = 1,
                 batch_fill_low: float = 0.25,
                 warm_miss_grow_per_tick: int = 4,
                 warm_grow_ticks: int = 2,
                 warm_idle_shrink_ticks: int = 8,
                 warm_capacity_max: int = 1 << 17):
        self.cadence_s = float(cadence_s)
        if self.cadence_s <= 0:
            raise ValueError(
                f"cadence_s must be > 0, got {cadence_s}")
        self.hysteresis = float(hysteresis)
        if not 0.0 < self.hysteresis < 1.0:
            raise ValueError(
                f"hysteresis must be in (0, 1), got {hysteresis}")
        self.min_actuation_interval_s = float(min_actuation_interval_s)
        if self.min_actuation_interval_s < 0:
            raise ValueError(
                "min_actuation_interval_s must be >= 0, got "
                f"{min_actuation_interval_s}")
        self.max_step_fraction = float(max_step_fraction)
        if not 0.0 < self.max_step_fraction <= 1.0:
            raise ValueError(
                f"max_step_fraction must be in (0, 1], got "
                f"{max_step_fraction}")
        self.tier0_burn_high = float(tier0_burn_high)
        # The LOW watermark defaults to the hysteresis fraction of the
        # high one — one knob moves the whole deadband.
        self.tier0_burn_low = (
            self.hysteresis * self.tier0_burn_high
            if tier0_burn_low is None else float(tier0_burn_low))
        self.backlog_age_high_s = float(backlog_age_high_s)
        self.backlog_age_low_s = (
            self.hysteresis * self.backlog_age_high_s
            if backlog_age_low_s is None else float(backlog_age_low_s))
        for name, lo, hi in (
                ("tier0_burn", self.tier0_burn_low,
                 self.tier0_burn_high),
                ("backlog_age", self.backlog_age_low_s,
                 self.backlog_age_high_s)):
            if not 0.0 <= lo < hi:
                raise ValueError(
                    f"{name} watermarks must satisfy 0 <= low < high, "
                    f"got ({lo}, {hi})")
        self.coalesce_min_s = float(coalesce_min_s)
        self.coalesce_max_s = float(coalesce_max_s)
        if not 0.0 <= self.coalesce_min_s < self.coalesce_max_s:
            raise ValueError(
                "coalesce bounds must satisfy 0 <= min < max, got "
                f"({coalesce_min_s}, {coalesce_max_s})")
        self.tier1_quota_min_fraction = float(tier1_quota_min_fraction)
        self.tier1_quota_max_fraction = float(tier1_quota_max_fraction)
        if not (0.0 < self.tier1_quota_min_fraction
                < self.tier1_quota_max_fraction <= 1.0):
            raise ValueError(
                "tier1 quota fractions must satisfy 0 < min < max <= 1"
                f", got ({tier1_quota_min_fraction}, "
                f"{tier1_quota_max_fraction})")
        self.retry_after_max_s = int(retry_after_max_s)
        if self.retry_after_max_s < 1:
            raise ValueError(
                f"retry_after_max_s must be >= 1, got "
                f"{retry_after_max_s}")
        self.bucket_bias_max = int(bucket_bias_max)
        if self.bucket_bias_max < 0:
            raise ValueError(
                f"bucket_bias_max must be >= 0, got {bucket_bias_max}")
        self.batch_fill_low = float(batch_fill_low)
        if not 0.0 <= self.batch_fill_low <= 1.0:
            raise ValueError(
                f"batch_fill_low must be in [0, 1], got "
                f"{batch_fill_low}")
        self.warm_miss_grow_per_tick = int(warm_miss_grow_per_tick)
        self.warm_grow_ticks = int(warm_grow_ticks)
        self.warm_idle_shrink_ticks = int(warm_idle_shrink_ticks)
        self.warm_capacity_max = int(warm_capacity_max)
        if min(self.warm_miss_grow_per_tick, self.warm_grow_ticks,
               self.warm_idle_shrink_ticks,
               self.warm_capacity_max) < 1:
            raise ValueError("warm_* knobs must all be >= 1")


class Controller:
    """The adaptive controller over ONE ``ServingEngine`` (and,
    through ``retry_after_for``, the edge in front of it).

    ``start()`` captures the engine's current knob values as the
    static-default revert anchor, attaches the snapshot source
    (``load()["control"]``), and spawns the tick thread; ``stop()``
    halts it (``revert=True`` restores the anchor — the drill's
    paired-run hygiene).  ``tick()`` is public and takes an optional
    pre-built signals dict so tests drive the decision logic
    deterministically without a live engine under load."""

    def __init__(self, engine, *, config: Optional[ControlConfig] = None,
                 objectives: Optional[dict] = None,
                 log: Optional[Callable[[str], None]] = None):
        self._eng = engine
        self._cfg = config or ControlConfig()
        self._objectives = objectives
        self._log = log or (lambda m: None)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._running = False
        self._crashed = False
        self._crash_error: Optional[str] = None
        self._ticks = 0
        self._actuations = 0
        self._reverts = 0
        self._last_reason: Optional[str] = None
        self._history: List[dict] = []
        self._defaults: Optional[dict] = None
        # Per-actuator rate-limit ledger: actuator -> monotonic stamp.
        self._last_actuation: Dict[str, float] = {}
        # Tick-delta baselines (counters are lifetime-cumulative).
        self._last_misses: Optional[int] = None
        self._last_rows_live: Optional[int] = None
        self._last_dispatches: Optional[int] = None
        self._warm_pressure_ticks = 0
        self._warm_idle_ticks = 0
        # Actuated per-tier Retry-After (None = static protocol
        # formula; ints once the controller has an opinion).
        self._retry_after: Dict[int, int] = {}

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Controller":
        if self._thread is not None:
            return self
        eng = self._eng
        store = eng.subject_store
        self._defaults = {
            "coalesce_base_s": eng.max_delay_s,
            "max_queued": eng.max_queued,
            "tier_quotas": dict(eng._tier_quotas),
            "bucket_bias": eng.bucket_bias,
            "warm_capacity": (None if store is None
                              else store.config.warm_capacity),
        }
        with self._lock:
            self._running = True
            self._crashed = False
        eng.attach_control(self.snapshot)
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="mano-control", daemon=True)
        self._thread.start()
        return self

    def stop(self, *, revert: bool = False,
             timeout_s: float = 10.0) -> None:
        """Halt the tick thread (bounded join). ``revert=True``
        restores the static defaults afterwards — the clean-shutdown
        counterpart of the crash path's forced revert."""
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
        self._thread = None
        with self._lock:
            self._running = False
        if revert and self._defaults is not None:
            self.revert_to_defaults("stop")

    def _run(self) -> None:
        try:
            while not self._stop_evt.wait(self._cfg.cadence_s):
                self.tick()
        except BaseException as e:  # noqa: BLE001 — crash = revert
            self._crash(e)

    def _crash(self, e: BaseException) -> None:
        """The never-wedge guarantee: a controller failure REVERTS
        every actuator to the static defaults and marks the snapshot,
        so a dead controller is exactly yesterday's hand-tuned engine.
        Each step is independently best-effort — one failing revert
        must not strand the others, and admission keeps running on
        whatever values land (the engine's setters never hold a lock
        across a callout)."""
        msg = f"{type(e).__name__}: {e}"
        with self._lock:
            self._crashed = True
            self._crash_error = msg
            self._running = False
        self._stop_evt.set()          # a crashed loop must not respin
        self._log(f"controller crashed ({msg}); reverting to static "
                  "defaults")
        tr = self._eng.tracer
        if tr is not None:
            try:
                tr.incident(f"control_crash: {msg}"[:200])
            except Exception:  # noqa: BLE001 — forensics, not control
                pass
        self.revert_to_defaults("crash")

    def revert_to_defaults(self, reason: str) -> dict:
        """Restore every actuator to the values captured at start().
        Best-effort per actuator; returns {actuator: ok}. Counted in
        ``control_reverts`` and evented like any actuation."""
        dflt = self._defaults or {}
        eng = self._eng
        ok: Dict[str, bool] = {}

        def step(name: str, fn) -> None:
            try:
                fn()
                ok[name] = True
            except Exception as exc:  # noqa: BLE001 — best-effort
                ok[name] = False
                self._log(f"revert {name} failed: "
                          f"{type(exc).__name__}: {exc}")

        if "coalesce_base_s" in dflt:
            step("coalesce", lambda: eng.set_coalesce_base(
                dflt["coalesce_base_s"]))
        if dflt.get("max_queued") is not None:
            step("admission", lambda: eng.set_admission(
                max_queued=dflt["max_queued"],
                tier_quotas=dflt["tier_quotas"]))
        if "bucket_bias" in dflt:
            step("bucket_bias", lambda: eng.set_bucket_bias(
                dflt["bucket_bias"]))
        store = eng.subject_store
        if store is not None and dflt.get("warm_capacity"):
            step("warm_capacity", lambda: store.resize_warm(
                dflt["warm_capacity"]))
        with self._lock:
            self._retry_after = {}
            self._reverts += 1
            self._last_reason = f"revert:{reason}"
        ok["retry_after"] = True
        try:
            eng.counters.count_control_revert()
        except Exception:  # noqa: BLE001 — telemetry, not control
            pass
        tr = eng.tracer
        if tr is not None:
            try:
                tr.runtime_event("control_revert", reason=reason,
                                 restored=sum(ok.values()))
            except Exception:  # noqa: BLE001
                pass
        return ok

    # ------------------------------------------------------------ telemetry
    def snapshot(self) -> dict:
        """The ``load()["control"]`` block: controller state in ONE
        lock hold (the torn-telemetry rule). ``version`` equals
        ``actuations`` and every history entry carries the version it
        was recorded under — the invariant the torn-snapshot test
        pins (a reader can never see a history newer than the
        counters beside it)."""
        with self._lock:
            return {
                "attached": True,
                "running": self._running,
                "crashed": self._crashed,
                "ticks": self._ticks,
                "actuations": self._actuations,
                "reverts": self._reverts,
                "version": self._actuations,
                "values": {
                    "coalesce_base_s": self._eng.max_delay_s,
                    "max_queued": self._eng.max_queued,
                    "bucket_bias": self._eng.bucket_bias,
                    "retry_after_s": {str(t): v for t, v
                                      in self._retry_after.items()},
                },
                "last_reason": self._last_reason,
                "history": list(self._history),
            }

    def retry_after_for(self, tier: int, load: Optional[dict] = None,
                        ) -> Optional[int]:
        """The edge's ``retry_after_source``: the actuated per-tier
        Retry-After, or None when the controller has no opinion (no
        actuation yet, or crashed) — the caller then falls back to the
        static ``protocol.retry_after_s`` formula, so a dead
        controller degrades to today's wire behavior exactly."""
        with self._lock:
            if self._crashed or not self._retry_after:
                return None
            key = 0 if int(tier) <= 0 else 1
            return self._retry_after.get(key)

    # ------------------------------------------------------------- decision
    def _signals(self) -> dict:
        """One telemetry sweep: the engine's load() (every sub-block a
        one-lock-hold copy), the SLO report derived from ONE counters
        snapshot, and this tick's counter deltas."""
        from mano_hand_tpu.obs.metrics import slo_report

        eng = self._eng
        load = eng.load()
        snap = eng.counters.snapshot()
        slo = slo_report(snap, self._objectives,
                         load.get("latency_by_tier"))
        return {"load": load, "slo": slo, "counters": snap}

    @staticmethod
    def _tier_burn(slo: dict, tier: str) -> float:
        t = (slo.get("tiers") or {}).get(tier)
        if not t:
            return 0.0
        burns = [v for v in (t.get("burn_rates") or {}).values()
                 if v == v]           # drop NaN defensively
        return max(burns) if burns else 0.0

    def _allowed(self, actuator: str, now: float) -> bool:
        last = self._last_actuation.get(actuator)
        return (last is None
                or now - last >= self._cfg.min_actuation_interval_s)

    def _actuate(self, actuator: str, before, after, reason: str,
                 now: float) -> None:
        """Record + event one applied actuation (the setter already
        ran; this is the bookkeeping half). History append, counter
        bump, and version bump share ONE lock hold with the values the
        snapshot reads beside them."""
        with self._lock:
            self._actuations += 1
            self._last_reason = reason
            self._last_actuation[actuator] = now
            self._history.append({
                "actuator": actuator, "before": before,
                "after": after, "reason": reason,
                "version": self._actuations,
            })
            del self._history[:-_HISTORY]
        try:
            self._eng.counters.count_control_actuation()
        except Exception:  # noqa: BLE001 — telemetry, not control
            pass
        tr = self._eng.tracer
        if tr is not None:
            try:
                tr.runtime_event("control", actuator=actuator,
                                 before=before, after=after,
                                 reason=reason)
            except Exception:  # noqa: BLE001
                pass

    def tick(self, signals: Optional[dict] = None) -> List[dict]:
        """One control decision: read signals, compare against the
        watermarks, actuate whatever is both out of its deadband and
        past its rate limit.  Returns the applied actuations (tests
        assert on it); every one is also evented and counted.

        A crashed controller never actuates again — the revert the
        crash path applied IS the final word until a fresh start()."""
        cfg = self._cfg
        with self._lock:
            if self._crashed:
                return []
        if signals is None:
            signals = self._signals()
        with self._lock:
            self._ticks += 1
        try:
            self._eng.counters.count_control_tick()
        except Exception:  # noqa: BLE001
            pass
        now = time.monotonic()
        eng = self._eng
        slo = signals.get("slo") or {}
        load = signals.get("load") or {}
        counters = signals.get("counters") or {}
        applied: List[dict] = []

        def apply(actuator: str, fn, reason: str) -> None:
            if not self._allowed(actuator, now):
                return
            try:
                delta = fn()
            except Exception as exc:  # noqa: BLE001 — one bad setter
                # must not kill the tick (the thread's crash path is
                # for CONTROLLER bugs; a rejected value is a no-op).
                self._log(f"actuate {actuator} rejected: "
                          f"{type(exc).__name__}: {exc}")
                return
            if delta["before"] == delta["after"]:
                return                # saturated at a bound: no event
            self._actuate(actuator, delta["before"], delta["after"],
                          reason, now)
            applied.append({"actuator": actuator, **delta,
                            "reason": reason})

        burn0 = self._tier_burn(slo, "0")
        backlog_age = float(load.get("backlog_age_s") or 0.0)
        max_queued = eng.max_queued

        # -- tier-1 quota: reallocate tier-0's idle headroom ------------
        if max_queued is not None:
            quota1 = eng._tier_quotas.get(1)
            if quota1 is None:
                quota1 = max_queued // 2
            lo = max(1, int(cfg.tier1_quota_min_fraction * max_queued))
            hi = max(lo, int(cfg.tier1_quota_max_fraction * max_queued))
            step = max(1, int(cfg.max_step_fraction * max_queued))
            if burn0 <= cfg.tier0_burn_low and quota1 < hi:
                target = min(hi, quota1 + step)
                apply("tier1_quota",
                      lambda: self._set_quota1(target),
                      f"tier0 burn {burn0:.2f} <= "
                      f"{cfg.tier0_burn_low} (cold): grow tier-1 "
                      f"quota {quota1} -> {target}")
            elif burn0 >= cfg.tier0_burn_high and quota1 > lo:
                target = max(lo, quota1 - step)
                apply("tier1_quota",
                      lambda: self._set_quota1(target),
                      f"tier0 burn {burn0:.2f} >= "
                      f"{cfg.tier0_burn_high} (hot): shed tier-1 "
                      f"sooner, quota {quota1} -> {target}")
            # Retry-After tracks the quota direction: clients get told
            # the truth about how long backing off actually helps.
            self._steer_retry_after(burn0, apply)

        # -- coalesce base: stop buying latency under a backlog ---------
        base = eng.max_delay_s
        if backlog_age >= cfg.backlog_age_high_s and \
                base > cfg.coalesce_min_s:
            target = max(cfg.coalesce_min_s,
                         base * (1.0 - cfg.max_step_fraction))
            apply("coalesce",
                  lambda: eng.set_coalesce_base(target),
                  f"backlog age {backlog_age * 1e3:.1f} ms >= "
                  f"{cfg.backlog_age_high_s * 1e3:.0f} ms: shrink "
                  "window base")
        elif backlog_age <= cfg.backlog_age_low_s:
            dflt = (self._defaults or {}).get("coalesce_base_s")
            if dflt is not None and base < dflt:
                target = min(dflt, cfg.coalesce_max_s,
                             max(base * (1.0 + cfg.max_step_fraction),
                                 dflt * cfg.max_step_fraction))
                apply("coalesce",
                      lambda: eng.set_coalesce_base(target),
                      f"backlog age {backlog_age * 1e3:.1f} ms <= "
                      f"{cfg.backlog_age_low_s * 1e3:.0f} ms: restore "
                      "window base")

        # -- bucket-ladder bias: shape uniformity under fragmentation ---
        fill = self._batch_fill(counters)
        if cfg.bucket_bias_max > 0 and fill is not None:
            if (burn0 >= cfg.tier0_burn_high
                    and fill < cfg.batch_fill_low
                    and eng.bucket_bias < cfg.bucket_bias_max):
                target = eng.bucket_bias + 1
                apply("bucket_bias",
                      lambda: eng.set_bucket_bias(target),
                      f"tier0 hot with fragmented batches "
                      f"(fill {fill:.2f}): bias ladder +1")
            elif (burn0 <= cfg.tier0_burn_low and eng.bucket_bias >
                  (self._defaults or {}).get("bucket_bias", 0)):
                target = (self._defaults or {}).get("bucket_bias", 0)
                apply("bucket_bias",
                      lambda: eng.set_bucket_bias(target),
                      "tier0 cold: restore ladder bias")

        # -- warm capacity: grow on sustained miss pressure -------------
        self._steer_warm(counters, apply)
        return applied

    # The setter thunks live apart from tick() so the decision block
    # reads as policy, not plumbing.
    def _set_quota1(self, target: int) -> dict:
        eng = self._eng
        quotas = dict(eng._tier_quotas)
        before = quotas.get(1, (eng.max_queued or 0) // 2)
        quotas[1] = int(target)
        eng.set_admission(tier_quotas=quotas)
        return {"before": before, "after": int(target)}

    def _steer_retry_after(self, burn0: float, apply) -> None:
        cfg = self._cfg
        with self._lock:
            cur = self._retry_after.get(1, 2)
        if burn0 >= cfg.tier0_burn_high:
            target = min(cfg.retry_after_max_s, max(cur * 2, 2))
        elif burn0 <= cfg.tier0_burn_low:
            target = max(1, cur // 2)
        else:
            return
        if target == cur and 1 in getattr(self, "_retry_after", {}):
            return

        def setter(t=target):
            with self._lock:
                before = self._retry_after.get(1)
                self._retry_after[1] = t
                self._retry_after.setdefault(0, 1)
            return {"before": before, "after": t}

        apply("retry_after",
              setter,
              f"tier0 burn {burn0:.2f}: tier-1 Retry-After -> "
              f"{target}s")

    def _batch_fill(self, counters: dict) -> Optional[float]:
        """Mean live-row fill of this tick's dispatches relative to
        the LARGEST bucket (the fragmentation signal the ladder bias
        keys on); None until two ticks have passed or when nothing
        dispatched."""
        rows = counters.get("rows_live")
        disp = counters.get("dispatches")
        if rows is None or disp is None:
            return None
        lr, ld = self._last_rows_live, self._last_dispatches
        self._last_rows_live, self._last_dispatches = rows, disp
        if lr is None or disp <= (ld or 0):
            return None
        cap = self._eng.buckets[-1]
        return (rows - lr) / max(1, (disp - ld)) / cap

    def _steer_warm(self, counters: dict, apply) -> None:
        cfg = self._cfg
        store = self._eng.subject_store
        if store is None:
            return
        misses = counters.get("subject_store_misses")
        if misses is None:
            return
        last = self._last_misses
        self._last_misses = misses
        if last is None:
            return
        delta = misses - last
        if delta >= cfg.warm_miss_grow_per_tick:
            self._warm_pressure_ticks += 1
            self._warm_idle_ticks = 0
        elif delta == 0:
            self._warm_idle_ticks += 1
            self._warm_pressure_ticks = 0
        else:
            self._warm_pressure_ticks = 0
            self._warm_idle_ticks = 0
        cap = store.config.warm_capacity
        dflt = (self._defaults or {}).get("warm_capacity") or cap
        if (self._warm_pressure_ticks >= cfg.warm_grow_ticks
                and cap < cfg.warm_capacity_max):
            target = min(cfg.warm_capacity_max,
                         int(cap * (1.0 + cfg.max_step_fraction)) + 1)
            apply("warm_capacity",
                  lambda: self._resize_warm(target),
                  f"warm misses +{delta}/tick x"
                  f"{self._warm_pressure_ticks} ticks: grow warm "
                  f"{cap} -> {target}")
            self._warm_pressure_ticks = 0
        elif (self._warm_idle_ticks >= cfg.warm_idle_shrink_ticks
              and cap > dflt):
            target = max(dflt,
                         int(cap * (1.0 - cfg.max_step_fraction)))
            apply("warm_capacity",
                  lambda: self._resize_warm(target),
                  f"warm idle {self._warm_idle_ticks} ticks: shrink "
                  f"warm {cap} -> {target}")
            self._warm_idle_ticks = 0

    def _resize_warm(self, target: int) -> dict:
        store = self._eng.subject_store
        r = store.resize_warm(int(target))
        return {"before": r.get("previous"),
                "after": r.get("warm_capacity")}
