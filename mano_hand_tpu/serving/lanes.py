"""Per-device dispatch lanes with a sibling-failover ladder (PR 13).

Everything in ``parallel/`` compiles on a multi-device mesh, but the
serving engine dispatched to exactly ONE device — a fleet of chips was
invisible to the layer that actually serves traffic, and one bad chip
was a service outage instead of a capacity loss. This module makes
dispatch mesh-aware:

* **N per-device lanes** fed by the engine's existing bucket/coalesce
  queue: the dispatcher still assembles batches exactly as before
  (coalescing is a host-side policy — splitting it per lane would
  fragment batches), then hands each assembled batch to the
  least-backlogged healthy lane. Each lane owns a device handle,
  device-pinned executable caches (the same
  ``build_bucket_executable`` / ``build_posed_gather_executable``
  program families as the engine — params/table as runtime arguments,
  so per-lane results are bit-identical to the single-device path on
  the same platform), a worker thread, and a ``CircuitBreaker``.
* **The SubjectTable replicated per lane.** A ``specialize()`` row
  write broadcasts to every lane replica as a functional
  ``table_set_row`` on that lane's device — a ROW of data movement per
  lane, never a recompile (the table stays a runtime argument). A lane
  that has no replica yet adopts the engine's live table wholesale on
  first use (warm-up-class work), and a capacity growth re-adopts +
  eagerly rebuilds that lane's gathered executables, counted exactly
  like the engine's own growth compiles.
* **Or SHARDED, not replicated (PR 16).** Under a sharded
  ``serving.subject_store.SubjectStore`` the N lanes hold N DISJOINT
  shard tables instead of N full replicas: ``shard_of(digest, N)``
  (content-based) names each subject's owner lane, the engine's
  ``_admit`` splits cross-shard batches at coalesce, and
  ``submit_batch`` pins each posed batch to its owner lane while that
  lane is healthy. A shard table is digest-keyed — its slot map and
  table reference swap together under ONE ``_lock`` hold (epoch-
  guarded against racing adopters), so a captured (table, slots) pair
  is immutably consistent without the replicated path's engine-version
  proof. Ladder hops and an owner-lane outage fall back to a per-batch
  ``device_put`` of the engine snapshot — always correct, paid only
  off the happy path. The win: per-lane device-resident rows drop from
  ``max_subjects`` to ~``max_subjects / N`` (the capacity ladder's
  fleet multiplier; bench config19).
* **The failover LADDER** (``runtime/health.py``): the PR-3 breaker
  generalized from "device -> CPU" to "device -> least-loaded healthy
  sibling lane -> CPU". A lane whose supervised primary exhausts its
  retries walks its healthy siblings in ``failover_ladder`` order (one
  supervised attempt each, that sibling's breaker consulted and
  updated), and only when every rung fails lands on the engine's CPU
  degradation tier — still the bit-identical
  params-as-runtime-args family. Failback is recompile-free by the
  same argument as PR 3: the lane's executable caches stay warm while
  its breaker is open, and the breaker's outage-length-aware re-probe
  (exponential backoff, capped) closes it without a single re-trace.
* **Per-lane chaos + telemetry.** Lane executables are chaos-wrapped
  with their lane index, so a ``%LANE``-tagged plan event
  (runtime/chaos.py) can kill exactly one lane while siblings serve
  clean — the lane-loss drill (bench config16,
  serving/measure.py:lane_drill_run). Every lane counter (backlog,
  in-flight, assigned/dispatched, ladder hops in/out, CPU failovers)
  mutates under ONE ``LaneSet`` lock, so ``load()["lanes"]`` is a
  single-lock-hold snapshot (the torn-telemetry rule), and lane spans
  ride the PR-8 tracer (a ``lane`` event per request, breaker
  transitions and ladder hops as runtime events/incidents).

Lock discipline: ``_lock`` guards placement + telemetry + the replica
reference swaps ONLY — all device work (params/table device_put,
executable builds, row writes) is staged OUTSIDE it, mirroring the
engine's ``_install_subject`` bake-and-swap (lane workers block on
``_lock`` per batch, so a device call inside it would stall every
lane at once; the ``mano analyze`` lock checker covers this file).
Replica broadcasts are serialized upstream by the engine's
``_install_lock`` (``_install_subject`` is the table's only mutator),
so ``broadcast_row`` needs no install lock of its own.

The PR-13 scope bound that lane executables had no AOT-lattice tier is
CLOSED (PR 18): ``_full_executable`` and ``_gather_executable`` try the
PR-6 lattice FIRST, exactly like the engine's single-device builders —
the per-lane twist is that the deserialized program's runtime arguments
(the ``params_leaves`` / ``table_leaves``) are COMMITTED to the lane's
device, so jax's committed-argument placement pins the backend compile
and every later dispatch to that lane (no default-device detour), and
the eager warm uses host-side zeros exactly as dispatch passes host
batches. A lane boot from a baked lattice therefore reports 0 jit
compiles at lanes=N (``aot_loads`` counts the revivals) — the fleet
drill's per-worker cold-boot criterion. The bf16 and fused families
stay deliberately OUT of the lattice tier (the PR-6 exclusion: the
lattice contract is f32 bit-identity with live jit). The PR-13 bound
that lanes served only the XLA gathered family is CLOSED (PR 14): a
lane's gathered cache serves the FUSED Pallas family under
``posed_kernel="fused"`` through the engine's own capacity gate, and
under a ``PrecisionPolicy`` each lane also carries the bf16-tier
gathered family (same capacity keying, growth re-adoption, and chaos
wrapping as the f32 cache) — so lane placement, the sibling ladder,
and failback never silently change a request's kernel or precision
family.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from mano_hand_tpu.obs import log as obs_log
from mano_hand_tpu.runtime import health

_SENTINEL = object()

_LOG = obs_log.get_logger("serving.lanes")


class Lane:
    """One per-device dispatch lane: a device handle, device-pinned
    executable caches + SubjectTable replica, a work queue, a worker
    thread, and a circuit breaker. Telemetry fields mutate ONLY under
    the owning ``LaneSet._lock`` (the one-lock-hold snapshot rule)."""

    def __init__(self, index: int, device, breaker):
        self.index = index
        self.device = device
        self.breaker = breaker
        self.q: queue.Queue = queue.Queue()
        self.worker: Optional[threading.Thread] = None
        # Device-pinned state, built lazily (the engine's default-device
        # caches are untouched — the sentinel keeps probing those).
        self.params_dev = None
        self.lat_leaves = None       # lane-device params_leaves (PR 18)
        self.table = None            # SubjectTable replica on self.device
        # Which engine ``_table_version`` the replica derives from: the
        # worker dispatches only after proving (one engine-lock hold)
        # that its resolved slots belong to EXACTLY this version —
        # evictions reuse slots, so a replica ahead of OR behind the
        # slots' version could silently serve the wrong subject.
        self.table_version = -1
        self.exes: dict = {}         # bucket -> full-path executable
        self.gather_exes: dict = {}  # bucket -> (capacity, executable)
        self.gather_exes_bf16: dict = {}  # bucket -> (capacity, exe)
        #   The bf16-tier gathered family (PR 14), per lane — same
        #   keying/invalidation as gather_exes; populated only under
        #   an engine PrecisionPolicy with bf16 tiers.
        # -- sharded mode (PR 16): lane.table is a shard-LOCAL table --
        # digest-keyed: shard_slots maps subject digest -> local slot,
        # and it swaps together with ``table`` (one _lock hold, epoch-
        # guarded), so a captured (table, slots) pair is consistent by
        # construction — shard tables need no engine-version proof.
        self.shard_slots: dict = {}          # digest -> local slot
        self.shard_lru = collections.OrderedDict()  # digest -> None
        self.shard_next_slot = 0             # first never-used local row
        self.shard_epoch = 0                 # bumped at every shard swap
        # -- telemetry (LaneSet._lock) --
        self.backlog_batches = 0     # queued + in flight
        self.backlog_rows = 0
        self.inflight = 0            # batches executing right now
        self.assigned = 0            # batches ever placed here
        self.dispatched = 0          # batches that reached a device
        self.served_requests = 0     # requests resolved ok by this lane
        self.failovers_out = 0       # batches this lane handed up-ladder
        self.failovers_in = 0        # sibling batches this lane absorbed
        self.cpu_failovers = 0       # batches that fell through to CPU
        self.errors = 0              # batches resolved as ServingError


class LaneSet:
    """The engine's lane fleet: placement, per-lane workers, replica
    broadcast, and the failover ladder. Built lazily by
    ``ServingEngine`` (first warmup/dispatch — the engine constructor
    touches no backend by design)."""

    def __init__(self, engine, n: int,
                 probe: Optional[Callable[[int], bool]] = None,
                 devices: Optional[Sequence] = None):
        from mano_hand_tpu.parallel import mesh
        from mano_hand_tpu.runtime.health import CircuitBreaker

        if n < 1:
            raise ValueError(f"lanes must be >= 1, got {n}")
        self._eng = engine
        self._lock = threading.Lock()
        self._rr = 0    # equal-backlog tie-break cursor (placement)
        # Sharded mode (PR 16): disjoint per-lane shard tables instead
        # of full replicas — decided once at construction from the
        # engine's store (the store's shard map was bound to this lane
        # count in the engine constructor).
        store = getattr(engine, "_subject_store", None)
        self._sharded = bool(store is not None and store.sharded)
        # Shard-rebalance kick guard (PR 20): shards whose adoption
        # thread has been spawned (under ``_lock``) — one rebalance per
        # dead shard, never a spawn storm from a hot dispatcher loop.
        self._rebalance_kicked: set = set()
        devs = mesh.lane_devices(n, devices=devices)
        self.n_devices = len({str(d) for d in devs})
        pol = engine._policy
        proto = getattr(pol, "breaker", None) if pol is not None else None
        tracer = engine._tracer
        self.lanes = []
        for i, dev in enumerate(devs):
            breaker = None
            if pol is not None:
                # Per-lane breakers: the policy's breaker (if any) is
                # the TEMPLATE — thresholds/cadence copied, state NOT
                # shared (one sick chip must not open its siblings'
                # breakers). ``probe`` overrides the probe per lane
                # (the drill's hand on each simulated tunnel).
                kw = {}
                if proto is not None:
                    kw = dict(
                        failure_threshold=proto.failure_threshold,
                        probe_interval_s=proto.probe_interval_s,
                        probe_backoff=proto.probe_backoff,
                        probe_interval_cap_s=proto.probe_interval_cap_s,
                        respect_priority_claim=(
                            proto.respect_priority_claim),
                        # CAVEAT (real multi-chip fleets): the
                        # template's probe is typically the
                        # backend-WIDE device_probe — with one dead
                        # chip on a healthy backend it re-probes
                        # green and the dead lane flaps open/closed.
                        # Production lanes over real chips need a
                        # per-DEVICE probe via ``lane_probe`` (the
                        # drill's pattern); on this box the failure
                        # domain is the whole tunnel, where the
                        # backend-wide probe is exactly right.
                        probe=proto.probe,
                        # The template's clock rides along: a
                        # deterministic-time breaker (the test/drill
                        # pattern) must drive the lane cadences too.
                        clock=proto.clock,
                    )
                if probe is not None:
                    kw["probe"] = (lambda i=i: bool(probe(i)))
                breaker = CircuitBreaker(**kw)
                if tracer is not None:
                    breaker.on_transition = (
                        lambda old, new, i=i: tracer.runtime_event(
                            "lane_breaker", lane=i, old=old, new=new))
                elif proto is not None and proto.on_transition is not None:
                    # No tracer: a caller-wired template hook still
                    # hears every lane's transitions (lane identity via
                    # the breaker argument closure is the caller's job;
                    # the tracer path above carries it explicitly).
                    breaker.on_transition = proto.on_transition
            self.lanes.append(Lane(i, dev, breaker))

    def __len__(self) -> int:
        return len(self.lanes)

    # ------------------------------------------------------------ placement
    def submit_batch(self, bucket: int, pose, shape, posed: bool, reqs,
                     rows: int, shard: Optional[int] = None) -> None:
        """Place one assembled batch on the least-backlogged healthy
        lane (breaker not DOWN; all down -> least-backlogged anyway,
        whose worker walks the ladder straight to CPU) and wake its
        worker. ``shard`` (PR 16, sharded store only): the batch's
        owner lane — placement pins there while it is healthy, else
        degrades to normal placement (the worker then serves via the
        engine-snapshot fallback, always correct). Called only by the
        engine's dispatcher thread."""
        with self._lock:
            lane = self._place_locked(rows, shard)
            lane.assigned += 1
            lane.backlog_batches += 1
            lane.backlog_rows += rows
            if lane.worker is None or not lane.worker.is_alive():
                lane.worker = threading.Thread(
                    target=self._worker, args=(lane,),
                    name=f"mano-lane-{lane.index}", daemon=True)
                lane.worker.start()
        for ln in self.lanes:
            # Failback driver: placement AVOIDS a DOWN lane, so unlike
            # the single-device engine (whose every dispatch consults
            # allow_primary) nothing would ever re-probe it. Kick any
            # due re-probe onto a disposable thread — probe_due() is a
            # lock-and-compare, the probe itself (a killable
            # subprocess, possibly seconds) never runs on the
            # dispatcher thread, and the breaker single-flights +
            # backs off the cadence internally.
            if (ln.breaker is not None and ln is not lane
                    and ln.breaker.probe_due()):
                threading.Thread(
                    target=ln.breaker.allow_primary,
                    name=f"mano-lane-{ln.index}-probe",
                    daemon=True).start()
        tr = self._eng._tracer
        if tr is not None:
            for r in reqs:
                tr.event(r.span, "lane", lane=lane.index)
        lane.q.put((bucket, pose, shape, posed, reqs, rows))

    def _place_locked(self, rows: int, shard: Optional[int] = None) -> Lane:
        # Caller holds self._lock. Sharded routing first (PR 16): the
        # subject→lane map IS the placement for a posed batch — only an
        # owner-lane outage falls through to load-based placement (and
        # the engine-snapshot dispatch fallback keeps that correct).
        if shard is not None:
            owner = self.lanes[shard]
            if owner.breaker is None or owner.breaker.state != health.DOWN:
                return owner
            if self._sharded and shard not in self._rebalance_kicked:
                # Owner lane DOWN (PR 20): adopt its shard onto the
                # survivors OFF-thread — the adoption stages device
                # work, which must never run on the dispatcher. Spawn
                # is once per shard (guarded here under ``_lock``);
                # a failed attempt re-arms so a later placement can
                # retry once the race clears.
                self._rebalance_kicked.add(shard)
                threading.Thread(
                    target=self._rebalance_kick, args=(shard,),
                    name=f"mano-shard-rebalance-{shard}",
                    daemon=True).start()
        # Backlog = queued + in-flight rows;
        # ties rotate round-robin — a low-rate stream (every lane idle
        # at every placement) must still spread across the fleet, or
        # one lane serves everything while its siblings' caches go
        # cold and the drill's balance criterion reads as one hot
        # lane. The rotation keeps placement deterministic.
        cands = [ln for ln in self.lanes
                 if ln.breaker is None or ln.breaker.state != health.DOWN]
        if not cands:
            cands = self.lanes
        n = len(self.lanes)
        lane = min(cands, key=lambda ln: (ln.backlog_rows,
                                          (ln.index - self._rr) % n))
        self._rr = (lane.index + 1) % n
        return lane

    # ----------------------------------------------------------- lane state
    def _lane_params(self, lane: Lane):
        """The lane-device-pinned params (staged outside every lock)."""
        if lane.params_dev is None:
            lane.params_dev = self._eng._params.device_put(
                sharding=lane.device)
        return lane.params_dev

    def _lane_lat_leaves(self, lane: Lane):
        """The lane-device-committed ``params_leaves`` a lattice-loaded
        full program takes as runtime arguments (PR 18): committed
        leaves pin the deserialized program's backend compile — and
        every dispatch — to THIS lane's device (staged outside every
        lock, cached on the lane like ``params_dev``)."""
        if lane.lat_leaves is None:
            from mano_hand_tpu.io.export_aot import params_leaves

            lane.lat_leaves = params_leaves(self._lane_params(lane))
        return lane.lat_leaves

    def _adopt(self, lane: Lane):
        """Re-derive the lane's replica from the engine's LIVE table
        (whole-table device_put — warm-up-class data movement): the
        source table and its version are read under ONE engine-lock
        hold, and the swap is version-monotonic, so a racing broadcast
        or adopter can never roll a replica back. Returns the lane's
        (table, version) after the attempt."""
        import jax

        eng = self._eng
        if self._sharded:
            return self._adopt_shard(lane)
        with eng._exe_lock:
            src = eng._table
            v = eng._table_version
        if src is None:
            raise RuntimeError(
                "no specialized subject to replicate into lanes; call "
                "specialize(betas) first")
        staged = jax.device_put(src, lane.device)
        with self._lock:
            if lane.table is None or lane.table_version < v:
                lane.table, lane.table_version = staged, v
            return lane.table, lane.table_version

    # ------------------------------------------------- shard tables (PR 16)
    def _effective_shard(self, digest: str) -> int:
        """The digest's EFFECTIVE owner lane: the store's shard map —
        which applies any PR-20 rebalance overlay, so after a lane
        loss every ownership consumer here (adopt, broadcast, the
        sharded-resolve fast path) agrees with the engine's ``_admit``
        grouping and the dispatcher's shard tags — falling back to the
        pure content placement when no store is bound (tests build
        LaneSets bare)."""
        store = getattr(self._eng, "_subject_store", None)
        if store is not None:
            s = store.shard_for(digest)
            if s is not None:
                return s
        from mano_hand_tpu.serving.subject_store import shard_of

        return shard_of(digest, len(self.lanes))

    def _shard_capacity_max(self) -> int:
        """The per-lane row budget under sharding: an even split of the
        engine's ``max_subjects`` (ceiling) — the per-lane footprint
        the replicated design multiplied by N collapses to ~1/N."""
        n = len(self.lanes)
        return max(1, -(-self._eng.max_subjects // n))

    def _adopt_shard(self, lane: Lane):
        """(Re)derive ``lane``'s shard-LOCAL table from the engine's
        live state: the rows this lane OWNS (``shard_of``), most
        recently used first, up to the per-lane budget. The sharded
        counterpart of ``_adopt`` — warm-up-class data movement, and
        the first-use path for a lane that has never seen a broadcast.
        Returns the lane's (table, version) after the attempt."""
        from mano_hand_tpu.models import core

        eng = self._eng
        with eng._exe_lock:
            src = eng._table
            v = eng._table_version
            owned = [d for d in eng._subject_lru
                     if self._effective_shard(d) == lane.index]
            eslots = {d: eng._subject_slots[d] for d in owned}
        if src is None:
            raise RuntimeError(
                "no specialized subject to shard into lanes; call "
                "specialize(betas) first")
        owned = owned[-self._shard_capacity_max():]   # LRU keeps the tail
        rows = {d: core.table_row(src, eslots[d]) for d in owned}
        for _ in range(4):
            if self._install_shard_rows(lane, rows, version=v):
                break
            # A racing swap (broadcast / sibling adopter) bumped the
            # epoch mid-stage; retry from the fresh state — on
            # exhaustion dispatch still serves via the engine-snapshot
            # fallback, so giving up here is safe.
        with self._lock:
            return lane.table, lane.table_version

    def _install_shard_rows(self, lane: Lane, rows: dict,
                            version: Optional[int] = None) -> bool:
        """Stage ``rows`` (digest -> ShapedHand) into ``lane``'s shard
        table and swap (table + slot map + LRU together, one ``_lock``
        hold, epoch-guarded).

        Capacity policy: the shard table is allocated at the FULL
        per-lane budget (``ceil(max_subjects / N)``) on first build and
        never resized — the budget is exactly the advertised sharded
        footprint (still ~1/N of a replica), and a fixed capacity
        keeps the gathered executables' input shapes stable, so
        steady-state dispatch is structurally recompile-free (the
        engine's pre-grow-at-warmup reasoning, applied per lane).
        Slots fill never-used rows first, then local-LRU eviction
        reuses a slot INSIDE the staged table only — captured
        (table, slots) pairs from earlier holds stay consistent.
        Returns False on an epoch race (nothing swapped) or when
        ``rows`` exceeds the per-lane budget (the caller's dispatch
        falls back to the engine snapshot)."""
        import jax

        from mano_hand_tpu.models import core

        cap = self._shard_capacity_max()
        if len(rows) > cap:
            return False
        with self._lock:
            tab = lane.table
            slots = dict(lane.shard_slots)
            lru = list(lane.shard_lru)
            nxt = lane.shard_next_slot
            epoch = lane.shard_epoch
        assign = {}
        for d in rows:
            if d in slots:
                assign[d] = slots[d]
            elif nxt < cap:
                assign[d] = nxt
                slots[d] = nxt
                nxt += 1
            else:
                victim = next((k for k in lru if k not in rows), None)
                if victim is None:       # rows wider than the budget
                    return False
                s = slots.pop(victim)
                lru.remove(victim)
                assign[d] = s
                slots[d] = s
            if d in lru:
                lru.remove(d)
            lru.append(d)
        # Device work on the STAGED table, outside _lock (the lane
        # workers block there per batch — the _install_subject rule).
        if tab is None:
            tab = core.subject_table(self._lane_params(lane), cap)
        for d, shaped in rows.items():
            tab = core.jit_table_set_row(
                tab, assign[d], jax.device_put(shaped, lane.device))
        with self._lock:
            if lane.shard_epoch != epoch:
                return False             # a concurrent swap won; retry
            lane.table = tab
            lane.shard_slots = slots
            lane.shard_lru = collections.OrderedDict(
                (k, None) for k in lru)
            lane.shard_next_slot = nxt
            lane.shard_epoch = epoch + 1
            if version is not None:
                # Telemetry only in sharded mode: consistency is the
                # digest-keyed atomic swap, never a version proof.
                lane.table_version = version
        return True

    # ---------------------------------------------- shard rebalance (PR 20)
    def _rebalance_kick(self, dead: int) -> None:
        """The ``_place_locked`` auto-trigger body (disposable daemon
        thread): run the adoption; on failure RE-ARM the kick guard so
        a later placement retries once the race clears."""
        ok = False
        try:
            ok = self.rebalance_shard(dead)
        except Exception as e:  # noqa: BLE001 — dispatcher must survive
            _LOG.warning(
                f"shard {dead} rebalance failed "
                f"({type(e).__name__}: {e}); will retry on next "
                "owner-down placement")
        if not ok:
            with self._lock:
                self._rebalance_kicked.discard(dead)

    def rebalance_shard(self, dead: int) -> bool:
        """Adopt a dead lane's shard onto the survivors (PR 20 — the
        PR-16 'no shard-rebalance on lane loss' remainder).

        Two steps, in an order that makes the window safe: (1) install
        the store's reassignment OVERLAY (``SubjectStore.
        reassign_shard`` — the dead shard's digests spread across the
        survivors by a second content hash), which INSTANTLY re-routes
        the whole pipeline (``_admit`` grouping, dispatcher shard tags,
        placement, the sharded-resolve fast path) because every one of
        those consults ``shard_for``; (2) proactively install the dead
        shard's ENGINE-HOT rows into their adopter lanes' shard tables
        (``core.table_row`` off the live engine table, the
        epoch-guarded ``_install_shard_rows`` swap — 0 recompiles by
        construction, the ``(bucket, capacity)`` keying is untouched).
        Anything not engine-hot re-enters lazily: the adopter's first
        miss pulls it through ``eng._resolve_batch`` — i.e. the subject
        store's warm/cold tiers — exactly the existing PR-16 path.

        Idempotent (the store overlay is the arbiter); counted on
        ``ServingCounters.count_shard_rebalance``. Returns whether THIS
        call installed the overlay. Failback: ``SubjectStore.
        restore_shard`` drops the overlay once the lane returns; its
        own rows re-enter through the same lazy path."""
        from mano_hand_tpu.models import core
        from mano_hand_tpu.serving.subject_store import shard_of

        eng = self._eng
        store = getattr(eng, "_subject_store", None)
        if not self._sharded or store is None:
            return False
        n = len(self.lanes)
        if not 0 <= dead < n:
            raise ValueError(f"shard {dead} out of range [0, {n})")
        with self._lock:
            survivors = [ln.index for ln in self.lanes
                         if ln.index != dead
                         and (ln.breaker is None
                              or ln.breaker.state != health.DOWN)]
        if not survivors:
            return False     # whole fleet down; the ladder/CPU tier
            # is already serving — nothing to adopt onto.
        try:
            if not store.reassign_shard(dead, survivors):
                return False             # someone already adopted it
        except ValueError as e:
            # A survivor raced DOWN / was itself reassigned between
            # the pick and the install; no overlay landed — safe.
            _LOG.warning(f"shard {dead} reassignment rejected: {e}")
            return False
        # Proactive adoption of the ENGINE-HOT rows (everything else
        # flows in lazily via the warm tier): source under one engine
        # lock hold, stage + install outside it (the _adopt_shard
        # pattern). Raw shard_of here — the overlay is live, so
        # _effective_shard already names the adopters, but the rows to
        # MOVE are the ones whose content placement was the dead shard.
        with eng._exe_lock:
            src = eng._table
            owned = [d for d in eng._subject_lru
                     if shard_of(d, n) == dead]
            eslots = {d: eng._subject_slots[d] for d in owned}
        moved = 0
        if src is not None and owned:
            by_owner: dict = {}
            for d in owned:
                by_owner.setdefault(self._effective_shard(d),
                                    []).append(d)
            cap = self._shard_capacity_max()
            for idx, ds in sorted(by_owner.items()):
                rows = {d: core.table_row(src, eslots[d])
                        for d in ds[-cap:]}    # LRU keeps the tail
                for _ in range(4):
                    if self._install_shard_rows(self.lanes[idx], rows):
                        moved += len(rows)
                        break
                    # Epoch race (adopter churn): retry; on exhaustion
                    # the rows re-enter lazily — still correct.
        eng.counters.count_shard_rebalance(rows=moved)
        tr = eng._tracer
        if tr is not None:
            tr.runtime_event("shard_rebalance", shard=dead,
                             survivors=list(survivors), rows=moved)
        _LOG.warning(
            f"shard {dead} rebalanced onto lanes {survivors} "
            f"({moved} hot row(s) adopted eagerly)")
        return True

    def _lane_table(self, lane: Lane):
        """The lane's replica, adopted on first use — the warm-up /
        executable-build entry point. Dispatch correctness does NOT
        rely on this being current: the worker re-validates version +
        slots per batch (``_resolve_for_lane``)."""
        with self._lock:
            tab = lane.table
        if tab is not None:
            return tab
        return self._adopt(lane)[0]

    def broadcast_row(self, slot: int, shaped, grew: bool,
                      version: int, digest: Optional[str] = None) -> None:
        """Mirror one installed subject row into every lane replica —
        called by ``ServingEngine._install_subject`` AFTER the engine
        table swap, still under ``_install_lock`` (the table's only
        mutator, so broadcasts are serialized upstream and need no
        lock of their own). ``version`` is the engine table version
        this row write produced: a replica exactly one version behind
        takes the row as a functional ``table_set_row`` on the lane's
        device — data movement, never a recompile — and every other
        state (no replica while a first adoption may be in flight
        with a PRE-swap read, a growth, a version gap, a lost swap
        race) re-adopts the whole live table through the monotonic
        ``_adopt`` path, so a replica can never publish with a
        silently missing row. Growth additionally rebuilds the lane's
        gathered executables eagerly (warm-up-class, counted like the
        engine's own growth compiles).

        Sharded mode (PR 16): the row lands on its OWNER lane only
        (``shard_of(digest, N)``) through the epoch-guarded shard
        install — one row of data movement total instead of one per
        lane, which is the broadcast-bandwidth half of the sharding
        win."""
        import jax

        from mano_hand_tpu.models import core

        if self._sharded:
            if digest is None:
                return       # kind-only engines never take this path
            owner = self.lanes[self._effective_shard(digest)]
            for _ in range(4):
                if self._install_shard_rows(owner, {digest: shaped},
                                            version=version):
                    return
            # Epoch races kept winning (adopter churn): the row is
            # still served correctly via the engine-snapshot dispatch
            # fallback; the next owner-lane resolve pulls it in.
            return
        for lane in self.lanes:
            with self._lock:
                tab, v = lane.table, lane.table_version
            if tab is None:
                self._adopt(lane)
                continue
            if v >= version and not grew:
                # A concurrent worker-side _adopt already landed this
                # (or a later) version — re-adopting would stage a
                # whole-table transfer just for the monotonic guard to
                # discard it.
                continue
            if grew or tab.capacity <= slot or v != version - 1:
                self._adopt(lane)
                if grew:
                    self._rebuild_stale_gather(lane)
                continue
            new = core.jit_table_set_row(
                tab, slot, jax.device_put(shaped, lane.device))
            stale = False
            with self._lock:
                if lane.table is tab and lane.table_version == v:
                    lane.table, lane.table_version = new, version
                elif lane.table_version < version:
                    # A concurrent adoption swapped a replica we did
                    # not stage from: re-adopt monotonically instead
                    # of publishing over it.
                    stale = True
            if stale:
                self._adopt(lane)

    def _rebuild_stale_gather(self, lane: Lane) -> None:
        """Eagerly rebuild a lane's capacity-stale gathered
        executables (both precision families) after a growth — a
        growth compile must not land inside a latency-sensitive lane
        dispatch (the engine's ``_install_subject`` rule, per lane)."""
        with self._lock:
            tab = lane.table
            cap = None if tab is None else tab.capacity

            def _stale(cache):
                if cap is None:
                    return []
                if self._sharded:
                    # (bucket, capacity) keys: a bucket is stale when
                    # it has entries but none at the new capacity.
                    buckets = {b for (b, _c) in cache}
                    fresh = {b for (b, c) in cache if c == cap}
                    return sorted(buckets - fresh)
                return [b for b, (c, _) in cache.items() if c != cap]

            stale = _stale(lane.gather_exes)
            stale_bf16 = _stale(lane.gather_exes_bf16)
        for b in stale:
            self._gather_executable(lane, b)
        for b in stale_bf16:
            self._gather_executable(lane, b, prec="bf16")

    # ----------------------------------------------------------- executables
    def _full_executable(self, lane: Lane, bucket: int):
        from mano_hand_tpu.serving import engine as engine_mod

        with self._lock:
            exe = lane.exes.get(bucket)
        if exe is not None:
            return exe
        eng = self._eng
        built = None
        lat = eng._get_lattice()
        if lat is not None:
            # Per-lane lattice tier (PR 18): the SAME PR-6 entry the
            # single-device path loads, with its runtime params
            # arguments committed to this lane's device — placement
            # follows the committed leaves, so the backend compile
            # lands on the lane, not the default device. Warmed with
            # host zeros exactly as dispatch passes host batches (a
            # committed-zeros warm would populate a DIFFERENT jit
            # cache entry and pay a second backend compile mid-
            # dispatch). Damage degrades to the counted jit build.
            import jax

            call = lat.get("full", bucket,
                           platform=jax.default_backend())
            if call is not None:
                try:
                    leaves = self._lane_lat_leaves(lane)
                    loaded = (lambda p, s, _c=call, _l=leaves:
                              _c(_l, p, s))
                    jax.block_until_ready(loaded(
                        np.zeros((bucket, eng._n_joints, 3),
                                 eng._dtype),
                        np.zeros((bucket, eng._n_shape), eng._dtype)))
                    eng.counters.count_aot_load()
                    if eng._tracer is not None:
                        eng._tracer.runtime_event(
                            "lattice_load", family="full",
                            bucket=bucket, lane=lane.index)
                    built = loaded
                except Exception as e:  # noqa: BLE001 — degrade
                    eng.counters.count_aot_load_failure()
                    _LOG.warning(
                        f"lane {lane.index}: lattice full/b{bucket} "
                        f"entry failed at execution "
                        f"({type(e).__name__}: {e}); recompiling "
                        f"(counted)")
                    if eng._tracer is not None:
                        eng._tracer.runtime_event(
                            "lattice_load_failed", family="full",
                            bucket=bucket, lane=lane.index)
                    built = None
        if built is None:
            built = engine_mod.build_bucket_executable(
                self._lane_params(lane), bucket, eng._n_joints,
                eng._n_shape, eng._dtype, donate=eng.donate)
            eng.counters.count_compile()
            if eng._tracer is not None:
                eng._tracer.runtime_event("compile", family="full",
                                          bucket=bucket,
                                          lane=lane.index)
        pol = eng._policy
        if pol is not None and pol.chaos is not None:
            built = pol.chaos.wrap(built, on_fault=eng._on_chaos_fault,
                                   lane=lane.index)
        with self._lock:
            exe = lane.exes.setdefault(bucket, built)
        return exe

    def _gather_executable(self, lane: Lane, bucket: int, tab=None,
                           prec: str = "f32"):
        """Returns ``(executable, table)`` — the executable serves ANY
        table of the cache key's capacity (table + index are runtime
        arguments), and the table the caller should dispatch is the
        one it passed in (a version-validated replica from
        ``_resolve_for_lane``) or, for warm-up, the lane's adopted
        replica.

        Family selection (PR 14): the engine's OWN tier predicates
        decide per lane exactly as they do on the single-device path —
        ``_posed_fused_active`` gates the fused Pallas family under
        ``posed_kernel="fused"`` (closing the PR-13 scope bound that
        lanes silently served XLA), and ``prec="bf16"`` selects the
        bf16-tier family into the lane's own bf16 cache. A lane can
        therefore never serve a DIFFERENT kernel or precision family
        than the engine would have — ladder hops and failback preserve
        the request's program family by construction.
        """
        from mano_hand_tpu.serving import engine as engine_mod

        if tab is None:
            tab = self._lane_table(lane)
        cap = tab.capacity
        eng = self._eng
        cache = (lane.gather_exes_bf16 if prec == "bf16"
                 else lane.gather_exes)
        # Sharded lanes key by (bucket, capacity): the engine-snapshot
        # dispatch fallback runs ENGINE-capacity tables through the same
        # cache, and the replicated larger-capacity-wins policy would
        # let one fallback evict the shard-capacity entry — turning
        # every later owner-lane dispatch into a steady recompile.
        key = (bucket, cap) if self._sharded else bucket
        with self._lock:
            entry = cache.get(key)
        if entry is not None and entry[0] == cap:
            return entry[1], tab
        fused = eng._posed_fused_active(cap)
        # Resolved OUTSIDE the lock (a jax backend query).
        interp = eng._resolve_posed_interpret() if fused else False
        built = None
        if prec != "bf16" and not fused:
            # Per-lane lattice tier (PR 18), f32/XLA family only (the
            # PR-6 exclusion: bf16 and fused never enter the lattice —
            # its contract is f32 bit-identity with live jit). The
            # entry's table argument is this lane's replica, already
            # committed to the lane device, so placement and the
            # backend compile pin to the lane; requires the shard
            # capacity among the baked capacities (bake_lattice adds
            # it when the engine's lanes shard — engine.py).
            lat = eng._get_lattice()
            if lat is not None:
                import jax

                call = lat.get("gather", bucket, cap,
                               platform=jax.default_backend())
                if call is not None:
                    try:
                        from mano_hand_tpu.io.export_aot import (
                            table_leaves,
                        )

                        built = (lambda t, idx, p, _c=call:
                                 _c(table_leaves(t), idx, p))
                        jax.block_until_ready(built(
                            tab, np.zeros((bucket,), np.int32),
                            np.zeros((bucket, eng._n_joints, 3),
                                     eng._dtype)))
                        eng.counters.count_aot_load()
                        if eng._tracer is not None:
                            eng._tracer.runtime_event(
                                "lattice_load", family="gather",
                                bucket=bucket, capacity=cap,
                                lane=lane.index)
                    except Exception as e:  # noqa: BLE001 — degrade
                        eng.counters.count_aot_load_failure()
                        _LOG.warning(
                            f"lane {lane.index}: lattice gather/"
                            f"b{bucket}/c{cap} entry failed at "
                            f"execution ({type(e).__name__}: {e}); "
                            f"recompiling (counted)")
                        if eng._tracer is not None:
                            eng._tracer.runtime_event(
                                "lattice_load_failed", family="gather",
                                bucket=bucket, capacity=cap,
                                lane=lane.index)
                        built = None
        if built is None:
            if prec == "bf16":
                family = "gather_fused_bf16" if fused else "gather_bf16"
                built = engine_mod.build_posed_gather_bf16_executable(
                    tab, bucket, eng._n_joints, eng._dtype,
                    donate=eng.donate, fused=fused, interpret=interp)
            elif fused:
                family = "gather_fused"
                built = engine_mod.build_posed_gather_fused_executable(
                    tab, bucket, eng._n_joints, eng._dtype,
                    donate=eng.donate, interpret=interp)
            else:
                family = "gather"
                built = engine_mod.build_posed_gather_executable(
                    tab, bucket, eng._n_joints, eng._dtype,
                    donate=eng.donate)
            eng.counters.count_compile()
            if eng._tracer is not None:
                eng._tracer.runtime_event("compile", family=family,
                                          bucket=bucket, capacity=cap,
                                          lane=lane.index)
        pol = eng._policy
        if pol is not None and pol.chaos is not None:
            built = pol.chaos.wrap(built, on_fault=eng._on_chaos_fault,
                                   lane=lane.index)
        with self._lock:
            cur = cache.get(key)
            if cur is not None and cur[0] == cap:
                return cur[1], tab
            if self._sharded or cur is None or cur[0] < cap:
                cache[key] = (cap, built)
        return built, tab

    def warm(self, buckets: Sequence[int], *, posed: bool) -> None:
        """Build every lane's executables for ``buckets`` up front —
        warm-up is where compile latency belongs, N-lane edition
        (both precision families when a PrecisionPolicy names bf16
        tiers, so ladder hops never pay a bf16 compile mid-outage).
        Sharded lanes additionally pre-build the ENGINE-capacity
        family each bucket — the engine-snapshot dispatch fallback
        (raced install, foreign-shard ladder hop) must cost a table
        transfer, never a mid-traffic compile; the staged full table
        is dropped right after the build, so nothing engine-sized
        stays resident."""
        import jax

        fallback_tab = None
        if posed and self._sharded:
            with self._eng._exe_lock:
                src = self._eng._table
            fallback_tab = src
        for lane in self.lanes:
            staged = (None if fallback_tab is None
                      else jax.device_put(fallback_tab, lane.device))
            for b in buckets:
                if posed:
                    self._gather_executable(lane, b)
                    if staged is not None:
                        self._gather_executable(lane, b, staged)
                    if self._eng._bf16_serving():
                        self._gather_executable(lane, b, prec="bf16")
                        if staged is not None:
                            self._gather_executable(lane, b, staged,
                                                    prec="bf16")
                else:
                    self._full_executable(lane, b)

    # -------------------------------------------------------------- dispatch
    def _resolve_for_lane(self, lane: Lane, reqs):
        """(replica, slots) for one posed batch, PROVEN consistent:
        the slots come from the engine's ``_resolve_batch`` (which
        re-bakes evicted subjects and broadcasts the rows), and the
        replica's version is matched against the engine version the
        slots were validated at in ONE engine-lock hold — an eviction
        REUSES slots, so a replica ahead of the slots' version could
        hold another subject's betas in the same row (the dispatch
        then serves silently wrong vertices; this is the lane
        equivalent of the engine's snapshot-pinning rule, which
        dispatches the immutable ``_resolve_batch`` snapshot
        directly). Install churn makes the validation race; after a
        few retries the fallback pins a per-batch device_put of the
        engine snapshot itself — always correct, paid as one
        full-table transfer under eviction pressure that is already
        re-baking every batch."""
        import jax

        eng = self._eng
        if self._sharded:
            return self._resolve_sharded(lane, reqs)
        digests = [r.subject for r in reqs]
        for _ in range(4):
            _, slots = eng._resolve_batch(reqs)
            with eng._exe_lock:
                v_eng = eng._table_version
                still = [eng._subject_slots.get(d) for d in digests]
            if still != slots:
                continue          # an install/evict raced the resolve
            with self._lock:
                tab, v = lane.table, lane.table_version
            if tab is not None and v == v_eng:
                # The replica derives from exactly the engine table
                # the slots were validated against; both sides are
                # immutable from here (later installs only swap
                # references), so the pair stays correct however the
                # live table moves on.
                return tab, slots
            if tab is None or v < v_eng:
                self._adopt(lane)
            # v > v_eng (a broadcast landed mid-validation): retry —
            # the next round reads a newer consistent pair.
        table, slots = eng._resolve_batch(reqs)
        return jax.device_put(table, lane.device), slots

    def _resolve_sharded(self, lane: Lane, reqs):
        """(shard table, local slots) for one posed batch on its OWNER
        lane. Consistency needs no version proof here: the slot map and
        table swap together (epoch-guarded, one ``_lock`` hold), and a
        digest-keyed row is content-correct whatever the engine's live
        table did since — the worst case of serving an engine-evicted
        subject from its shard row is still bit-exact, because the row
        IS that subject's bake. Missing rows are pulled through the
        engine's ``_resolve_batch`` (which re-bakes evictions and
        counts them) into the shard table, then the read retries once;
        a foreign-shard batch (ladder hop / owner-down placement) or a
        lost install race dispatches a per-batch device_put of the
        engine snapshot — always correct, paid only off the happy
        path."""
        import jax

        from mano_hand_tpu.models import core

        eng = self._eng
        digests = [r.subject for r in reqs]

        def read_local():
            """One-lock-hold (table, slots) read; None unless every
            digest is locally resident."""
            with self._lock:
                tab = lane.table
                if tab is None:
                    return None
                slots = [lane.shard_slots.get(d) for d in digests]
                if any(s is None for s in slots):
                    return None
                for d in digests:
                    lane.shard_lru.move_to_end(d)
                return tab, slots

        # EFFECTIVE ownership (PR 20): after a rebalance the adopter
        # lane owns the dead shard's digests — its fast path must
        # accept them, or every adopted subject pays the snapshot
        # fallback forever.
        if all(self._effective_shard(d) == lane.index for d in digests):
            for attempt in range(2):
                got = read_local()
                if got is not None:
                    # The shard fast path never reaches
                    # _resolve_batch, so the hot-tier hit is counted
                    # HERE (outside the lock) or the drill's hit rate
                    # undercounts every locally-served batch.
                    eng.counters.count_store_hot(len(set(digests)))
                    return got
                if attempt:
                    break
                src, eslots = eng._resolve_batch(reqs)
                rows = {d: core.table_row(src, s)
                        for d, s in zip(digests, eslots)}
                for _ in range(4):
                    if self._install_shard_rows(lane, rows):
                        break
        table, slots = eng._resolve_batch(reqs)
        return jax.device_put(table, lane.device), slots

    def _worker(self, lane: Lane) -> None:
        while True:
            item = lane.q.get()
            if item is _SENTINEL:
                return
            try:
                self._run_batch(lane, item)
            except BaseException as e:  # noqa: BLE001 — futures must not hang
                # Unlike the single dispatcher (where a deterministic
                # failure is engine-fatal), a lane is one of N: poison
                # THIS batch, count it, keep the lane serving — its
                # siblings and the queue behind it must not die with
                # one bad batch.
                self._eng._poison(item[4], e)
                with self._lock:
                    lane.errors += 1
                _LOG.warning(
                    f"lane {lane.index} batch failed "
                    f"({type(e).__name__}: {e}); batch poisoned, "
                    "lane worker continues")

    def _posed_call(self, target: Lane, bucket: int, pose, reqs):
        """One gathered dispatch on ``target``: version-validated
        replica + slots, the capacity-keyed executable of the batch's
        precision family (``_req_prec`` — batches are single-precision
        by the engine's coalesce rule, so request 0 speaks for all),
        and the int32 index built from THOSE slots (never from a
        resolution taken at placement time — the batch may have sat in
        a backlog through an eviction)."""
        from mano_hand_tpu.serving import buckets as bucket_mod

        tab, slots = self._resolve_for_lane(target, reqs)
        exe, tab = self._gather_executable(
            target, bucket, tab, prec=self._eng._req_prec(reqs[0]))
        idx = bucket_mod.subject_index_rows(
            slots, [r.rows for r in reqs], bucket)
        return exe, tab, idx

    def _run_batch(self, lane: Lane, item) -> None:
        from mano_hand_tpu.serving.engine import ServingError

        bucket, pose, shape, posed, reqs, rows = item
        eng = self._eng
        tr = eng._tracer
        n_subjects = (len({r.subject for r in reqs}) if posed else 1)
        with self._lock:
            lane.inflight += 1
        try:
            # Pre-dispatch sweep: the batch arrays are already
            # assembled, so members cannot be dropped individually —
            # but an ALL-dead batch (every member cancelled or
            # expired while queued behind this lane's backlog) must
            # not buy a device dispatch at all.
            now = time.monotonic()
            if all(r.future.cancelled() or eng._is_expired(r, now)
                   for r in reqs):
                for r in reqs:
                    if not eng._skip_cancelled(r):
                        eng._expire(r, "dispatch")
                return
            try:
                if eng._policy is None:
                    if posed:
                        exe, tab, idx = self._posed_call(
                            lane, bucket, pose, reqs)
                        out = np.asarray(exe(tab, idx, pose))
                    else:
                        exe = self._full_executable(lane, bucket)
                        out = np.asarray(exe(pose, shape))
                else:
                    out = self._ladder_dispatch(
                        lane, bucket, pose, shape, posed, reqs)
            except ServingError as e:
                # Supervision + the whole ladder exhausted for THIS
                # batch: its futures get the structured error and the
                # lane lives on — a failed batch is traffic (the
                # engine's _launch contract, per lane).
                with self._lock:
                    lane.errors += 1
                eng._poison(reqs, e)
                return
            eng.counters.count_dispatch(bucket, rows,
                                        requests=len(reqs),
                                        subjects=n_subjects)
            with self._lock:
                lane.dispatched += 1
            if tr is not None:
                for r in reqs:
                    tr.event(r.span, "dispatched", lane=lane.index)
            eng._deliver(reqs, out, bucket)
            with self._lock:
                lane.served_requests += sum(
                    1 for r in reqs
                    if r.future.done() and not r.future.cancelled()
                    and r.future.exception() is None)
        finally:
            with self._lock:
                lane.inflight -= 1
                lane.backlog_batches -= 1
                lane.backlog_rows -= rows

    def _ladder_dispatch(self, lane: Lane, bucket: int, pose, shape,
                         posed: bool, reqs):
        """One batch through the failover LADDER: supervised primary
        on its placed lane, then one supervised attempt per healthy
        sibling (least-loaded first, ``health.failover_ladder``), then
        the engine's CPU degradation tier — every rung inside the
        batch's own deadline budget, with the expired-members sweep
        between rungs (a rung must not buy chip time for results
        nobody will read). Raises ``ServingError`` when every rung is
        exhausted; deterministic failures propagate un-retried, the
        PR-3 contract."""
        from mano_hand_tpu.runtime import supervise
        from mano_hand_tpu.serving.engine import ServingError

        eng = self._eng
        pol = eng._policy
        tr = eng._tracer
        give_up_by = supervise.batch_give_up_by(r.deadline for r in reqs)

        def attempt_on(target: Lane, retries: int):
            # Resolution + executable fetch happen per RUNG, outside
            # the per-attempt deadline (builds are warm-up-class, the
            # engine rule) — and each rung's index is derived from its
            # own validated (replica, slots) pair, never recycled from
            # an earlier rung or the placement-time state.
            if posed:
                exe, tab, idx = self._posed_call(target, bucket, pose,
                                                 reqs)
                fn = lambda: np.asarray(exe(tab, idx, pose))  # noqa: E731
            else:
                exe = self._full_executable(target, bucket)
                fn = lambda: np.asarray(exe(pose, shape))     # noqa: E731
            br = target.breaker

            def on_retry():
                eng.counters.count_retry()
                if tr is not None:
                    tr.runtime_event("retry", bucket=bucket,
                                     lane=target.index)

            def on_kill():
                eng.counters.count_deadline_kill()
                if tr is not None:
                    tr.incident("deadline_kill", bucket=bucket,
                                lane=target.index)
            return supervise.supervised_call(
                fn,
                deadline_s=pol.deadline_s,
                retries=retries,
                backoff_s=pol.backoff_s,
                backoff_cap_s=pol.backoff_cap_s,
                jitter=pol.jitter,
                give_up_by=give_up_by,
                keep_trying=(br.allow_primary if br is not None
                             else None),
                on_retry=on_retry,
                on_deadline_kill=on_kill,
                on_attempt_failure=(br.record_failure
                                    if br is not None else None),
                name=f"lane{target.index}-dispatch-b{bucket}",
            )

        last = None
        attempts = 0
        if lane.breaker is None or lane.breaker.allow_primary():
            try:
                out = attempt_on(lane, pol.retries)
                if lane.breaker is not None:
                    lane.breaker.record_success()
                return out
            except supervise.RetriesExhausted as e:
                last, attempts = e.cause, e.attempts

        def all_expired() -> Optional[ServingError]:
            # The between-rungs deadline sweep (the engine's
            # post-primary boundary, per rung): once every member has
            # expired, no further rung may dispatch.
            now = time.monotonic()
            if not all(r.future.cancelled() or eng._is_expired(r, now)
                       for r in reqs):
                return None
            for r in reqs:
                if not eng._skip_cancelled(r):
                    eng._expire(r, "failover")
            return ServingError(
                f"every request in the batch expired during the lane "
                f"attempts ({attempts}); the ladder stops here — no "
                "caller would read the result",
                phase="failover", kind="expired",
                attempts=attempts, cause=last)

        err = all_expired()
        if err is not None:
            raise err

        # -- middle rung: healthy siblings, least-loaded first --------
        with self._lock:
            backlog = {ln.index: ln.backlog_rows for ln in self.lanes}
        order = health.failover_ladder(
            lane.index, len(self.lanes), backlog,
            allow=lambda i: (self.lanes[i].breaker is None
                             or self.lanes[i].breaker.state
                             != health.DOWN))
        hopped = False
        for j in order:
            sib = self.lanes[j]
            if sib.breaker is not None and not sib.breaker.allow_primary():
                continue
            if not hopped:
                hopped = True
                with self._lock:
                    lane.failovers_out += 1
            with self._lock:
                sib.failovers_in += 1
            if tr is not None:
                tr.incident("lane_failover", bucket=bucket,
                            from_lane=lane.index, to_lane=sib.index)
            try:
                out = attempt_on(sib, 0)   # one supervised try per rung
                if sib.breaker is not None:
                    sib.breaker.record_success()
                return out
            except supervise.RetriesExhausted as e:
                last = e.cause
                attempts += e.attempts
            err = all_expired()
            if err is not None:
                raise err

        # -- last rung: the CPU degradation tier (PR 3, unchanged) ----
        if pol.cpu_fallback:
            eng.counters.count_failover()
            with self._lock:
                lane.cpu_failovers += 1
            if tr is not None:
                tr.incident("failover", bucket=bucket, lane=lane.index,
                            attempts=attempts)
            # THE shared reconstruction (engine.py:_fallback_shape):
            # the pad-row-betas rule must not drift between the
            # single-device failover and the ladder's last rung.
            fb_shape = eng._fallback_shape(reqs, bucket, shape,
                                           posed=posed)
            fb = eng._fallback_executable(bucket)
            try:
                return supervise.call_with_deadline(
                    lambda: np.asarray(fb(pose, fb_shape)),
                    pol.deadline_s,
                    name=f"lane{lane.index}-fallback-b{bucket}")
            except BaseException as e:
                raise ServingError(
                    f"dispatch failed on lane {lane.index}, every "
                    f"sibling rung, AND the CPU fallback "
                    f"({attempts} attempt(s)): {type(e).__name__}: {e}",
                    attempts=attempts, cause=e) from e
        raise ServingError(
            f"dispatch failed: lane {lane.index} "
            + ("unavailable (breaker open)" if last is None
               else f"exhausted after {attempts} attempt(s): "
                    f"{type(last).__name__}: {last}")
            + ", every sibling rung failed or is down, and "
            "cpu_fallback is disabled",
            attempts=attempts, cause=last)

    # ------------------------------------------------------------ telemetry
    def snapshot(self) -> dict:
        """The ``load()["lanes"]`` block: every lane's backlog,
        breaker state, and ladder counters from ONE ``_lock`` hold
        (the torn-telemetry rule — ``assigned_total`` is summed inside
        the same hold, so it always equals the per-lane sum)."""
        with self._lock:
            per = []
            for ln in self.lanes:
                per.append({
                    "lane": ln.index,
                    "device": str(ln.device),
                    "state": (ln.breaker.state if ln.breaker is not None
                              else health.HEALTHY),
                    # Allocated device rows / rows actually resident —
                    # the sharded-vs-replicated memory headline (a
                    # replica's residency IS its capacity; a shard
                    # table holds only its slot-mapped digests).
                    "table_capacity": (ln.table.capacity
                                       if ln.table is not None else 0),
                    "resident_rows": (len(ln.shard_slots)
                                      if self._sharded else
                                      (ln.table.capacity
                                       if ln.table is not None else 0)),
                    "backlog_batches": ln.backlog_batches,
                    "backlog_rows": ln.backlog_rows,
                    "inflight": ln.inflight,
                    "assigned": ln.assigned,
                    "dispatched": ln.dispatched,
                    "served_requests": ln.served_requests,
                    "failovers_out": ln.failovers_out,
                    "failovers_in": ln.failovers_in,
                    "cpu_failovers": ln.cpu_failovers,
                    "errors": ln.errors,
                })
            return {
                "n_lanes": len(self.lanes),
                "n_devices": self.n_devices,
                "sharded": self._sharded,
                "healthy": sum(1 for p in per
                               if p["state"] != health.DOWN),
                "assigned_total": sum(p["assigned"] for p in per),
                "backlog_rows_total": sum(p["backlog_rows"]
                                          for p in per),
                "per_lane": per,
            }

    # ------------------------------------------------------------- lifecycle
    def stop(self, timeout_s: Optional[float] = None) -> None:
        """Drain + stop every lane worker; poison whatever stays
        queued. A wedged worker (hung device RPC) is abandoned
        (daemon) — the engine's final ``_sweep_live`` resolves its
        batch's futures, the PR-3 shutdown contract per lane."""
        with self._lock:
            workers = [(ln, ln.worker) for ln in self.lanes]
        for ln, _ in workers:
            ln.q.put(_SENTINEL)
        join_s = timeout_s if timeout_s is not None else 30.0
        deadline = time.monotonic() + join_s
        for ln, w in workers:
            if w is not None and w.is_alive():
                w.join(max(0.0, deadline - time.monotonic()))
        from mano_hand_tpu.serving.engine import ServingError

        for ln, w in workers:
            while True:
                try:
                    item = ln.q.get_nowait()
                except queue.Empty:
                    break
                if item is _SENTINEL:
                    continue
                self._eng._poison(item[4], ServingError(
                    "serving engine stopped before this batch's lane "
                    "dispatched it", phase="shutdown"))
                with self._lock:
                    # The worker's finally never runs for a drained
                    # item: release its backlog accounting here, or a
                    # restarted engine places around phantom load
                    # forever (and load() reports backlog on idle).
                    ln.backlog_batches -= 1
                    ln.backlog_rows -= item[5]
            if w is not None and w.is_alive():
                # The drain above may have eaten the worker's shutdown
                # sentinel: an abandoned (wedged-RPC) worker that ever
                # unwinds must find one and exit instead of blocking
                # on the empty queue forever (the engine's own
                # re-post-at-stop rule, per lane).
                ln.q.put(_SENTINEL)
