"""Binary glTF 2.0 (GLB) export — viewer-ready meshes, stdlib only.

The reference's only mesh output is Wavefront OBJ
(/root/reference/mano_np.py:181-201; matched byte-for-byte by io/obj.py).
GLB is the modern interchange the OBJ path cannot cover: one binary file
that three.js, Blender, and every glTF viewer load directly, with
normals, correct winding, and — for clips — a morph-target animation so
a fitted motion sequence plays back in any viewer with no tooling.

Writer is pure stdlib (json + struct + numpy buffers), mirroring the
AVI/PNG philosophy (viz/avi.py, viz/png.py); ``read_glb`` parses the
container back for integrity tests.
"""

from __future__ import annotations

import json
import struct
from typing import Optional, Sequence

import numpy as np

_MAGIC = 0x46546C67          # 'glTF'
_CHUNK_JSON = 0x4E4F534A     # 'JSON'
_CHUNK_BIN = 0x004E4942      # 'BIN\0'
_F32 = 5126                  # GL_FLOAT
_U32 = 5125                  # GL_UNSIGNED_INT


def _pad4(b: bytes, fill: bytes) -> bytes:
    return b + fill * (-len(b) % 4)


def export_glb(
    verts: np.ndarray,            # [V, 3] float
    faces: np.ndarray,            # [F, 3] int
    path,
    normals: Optional[np.ndarray] = None,   # [V, 3]; computed if None
    morph_frames: Optional[Sequence[np.ndarray]] = None,  # T x [V, 3]
    fps: float = 30.0,
    vertex_colors: Optional[np.ndarray] = None,  # [V, 3] RGB in [0, 1]
) -> str:
    """Write a mesh (optionally an animated clip) as a GLB file.

    ``morph_frames`` turns the export into a playable animation: each
    frame's vertices become a morph target (displacements from the base
    mesh) driven by a step-less linear weight animation at ``fps`` —
    exactly one target active per frame time. Viewers play it directly;
    the data path is the same `[T, V, 3]` array `fit_sequence` or
    `evaluate_sequence` produce. ``vertex_colors`` writes a float
    ``COLOR_0`` attribute — e.g. ``viz.error_colormap`` output, making a
    fit-error heatmap inspectable as a 3D object in any glTF viewer
    (``cli fit --heatmap err.glb``). Returns the path.
    """
    verts = np.asarray(verts, np.float32)
    faces = np.asarray(faces, np.uint32)
    if verts.ndim != 2 or verts.shape[-1] != 3:
        raise ValueError(f"verts must be [V, 3], got {verts.shape}")
    if faces.ndim != 2 or faces.shape[-1] != 3:
        raise ValueError(f"faces must be [F, 3], got {faces.shape}")
    if normals is None:
        normals = _vertex_normals_np(verts, faces)
    normals = np.asarray(normals, np.float32)
    if vertex_colors is not None:
        vertex_colors = np.asarray(vertex_colors, np.float32)
        if vertex_colors.shape != verts.shape:
            raise ValueError(
                f"vertex_colors must be [V, 3] matching verts, got "
                f"{vertex_colors.shape}"
            )

    buffers: list[bytes] = []
    views = []
    accessors = []

    def add(data: np.ndarray, target=None, minmax=False):
        raw = np.ascontiguousarray(data).tobytes()
        offset = sum(len(b) for b in buffers)
        buffers.append(_pad4(raw, b"\x00"))
        view = {"buffer": 0, "byteOffset": offset, "byteLength": len(raw)}
        if target:
            view["target"] = target
        views.append(view)
        acc = {
            "bufferView": len(views) - 1,
            "componentType": _U32 if data.dtype == np.uint32 else _F32,
            "count": int(data.shape[0] if data.ndim > 1 else data.size),
            "type": {1: "SCALAR", 3: "VEC3"}[
                1 if data.ndim == 1 else data.shape[-1]
            ],
        }
        if minmax:
            acc["min"] = [float(x) for x in data.min(axis=0)]
            acc["max"] = [float(x) for x in data.max(axis=0)]
        accessors.append(acc)
        return len(accessors) - 1

    a_pos = add(verts, target=34962, minmax=True)       # ARRAY_BUFFER
    a_nrm = add(normals, target=34962)
    a_idx = add(faces.reshape(-1), target=34963)        # ELEMENT_ARRAY

    primitive = {
        "attributes": {"POSITION": a_pos, "NORMAL": a_nrm},
        "indices": a_idx,
        "mode": 4,  # TRIANGLES
    }
    if vertex_colors is not None:
        primitive["attributes"]["COLOR_0"] = add(vertex_colors,
                                                 target=34962)
    gltf = {
        "asset": {"version": "2.0", "generator": "mano_hand_tpu"},
        "scene": 0,
        "scenes": [{"nodes": [0]}],
        "nodes": [{"mesh": 0, "name": "hand"}],
        "meshes": [{"primitives": [primitive]}],
    }

    if morph_frames is not None:
        if not fps > 0:
            # arange/fps would put inf/nan keyframe times into the JSON
            # chunk (json.dumps emits bare Infinity — invalid glTF that
            # strict viewers reject with an opaque parse error).
            raise ValueError(f"fps must be > 0, got {fps}")
        frames = [np.asarray(f, np.float32) for f in morph_frames]
        if not frames:
            raise ValueError("morph_frames is empty")
        for f in frames:
            if f.shape != verts.shape:
                raise ValueError(
                    f"morph frame shape {f.shape} != verts {verts.shape}"
                )
        targets = []
        for f in frames:
            targets.append({"POSITION": add(f - verts, target=34962,
                                            minmax=True)})
        primitive["targets"] = targets
        t_frames = len(frames)
        gltf["meshes"][0]["weights"] = [0.0] * t_frames
        # One-hot weight tracks sampled at frame times: LINEAR
        # interpolation cross-fades adjacent frames — smooth playback of
        # the clip without shipping per-frame meshes.
        times = (np.arange(t_frames, dtype=np.float32) / fps)
        a_time = add(times)
        accessors[a_time]["min"] = [float(times.min())]
        accessors[a_time]["max"] = [float(times.max())]
        weights = np.eye(t_frames, dtype=np.float32).reshape(-1)
        a_wts = add(weights)
        gltf["animations"] = [{
            "name": "clip",
            "samplers": [{
                "input": a_time,
                "interpolation": "LINEAR",
                "output": a_wts,
            }],
            "channels": [{
                "sampler": 0,
                "target": {"node": 0, "path": "weights"},
            }],
        }]

    bin_chunk = b"".join(buffers)
    gltf["buffers"] = [{"byteLength": len(bin_chunk)}]
    gltf["bufferViews"] = views
    gltf["accessors"] = accessors

    json_chunk = _pad4(json.dumps(gltf, separators=(",", ":")).encode(),
                       b" ")
    total = 12 + 8 + len(json_chunk) + 8 + len(bin_chunk)
    with open(path, "wb") as f:
        f.write(struct.pack("<III", _MAGIC, 2, total))
        f.write(struct.pack("<II", len(json_chunk), _CHUNK_JSON))
        f.write(json_chunk)
        f.write(struct.pack("<II", len(bin_chunk), _CHUNK_BIN))
        f.write(bin_chunk)
    return str(path)


def _vertex_normals_np(verts: np.ndarray, faces: np.ndarray) -> np.ndarray:
    """Area-weighted vertex normals, pure numpy (export-time only — the
    differentiable JAX version lives in ops/normals.py)."""
    v = verts.astype(np.float64)
    f = faces.astype(np.int64)
    fn = np.cross(v[f[:, 1]] - v[f[:, 0]], v[f[:, 2]] - v[f[:, 0]])
    n = np.zeros_like(v)
    for c in range(3):
        np.add.at(n, f[:, c], fn)
    lens = np.linalg.norm(n, axis=-1, keepdims=True)
    n = np.where(lens > 1e-12, n / np.maximum(lens, 1e-12),
                 np.array([0.0, 0.0, 1.0]))
    return n.astype(np.float32)  # spec wants unit normals — even for
    #   vertices no face references (possible on synthetic assets)


def read_glb(path) -> dict:
    """Parse a GLB container: the glTF JSON dict plus raw chunk sizes.

    For integrity checks (same role as viz/avi.py's ``read_avi_info``);
    not a general loader.
    """
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 12 or data[:4] != b"glTF":
        raise ValueError("not a GLB file (bad magic)")
    magic, version, total = struct.unpack_from("<III", data, 0)
    if total != len(data):
        raise ValueError(
            f"truncated GLB: header says {total} bytes, file has {len(data)}"
        )
    jlen, jtype = struct.unpack_from("<II", data, 12)
    if jtype != _CHUNK_JSON:
        raise ValueError("first GLB chunk is not JSON")
    gltf = json.loads(data[20:20 + jlen].decode())
    out = {"gltf": gltf, "version": version, "json_bytes": jlen}
    off = 20 + jlen
    if off < len(data):
        blen, btype = struct.unpack_from("<II", data, off)
        if btype != _CHUNK_BIN:
            raise ValueError("second GLB chunk is not BIN")
        out["bin_bytes"] = blen
        out["bin"] = data[off + 8:off + 8 + blen]
    return out
