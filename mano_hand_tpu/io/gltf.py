"""Binary glTF 2.0 (GLB) export — viewer-ready meshes, stdlib only.

The reference's only mesh output is Wavefront OBJ
(/root/reference/mano_np.py:181-201; matched byte-for-byte by io/obj.py).
GLB is the modern interchange the OBJ path cannot cover: one binary file
that three.js, Blender, and every glTF viewer load directly, with
normals, correct winding, and — for clips — a morph-target animation so
a fitted motion sequence plays back in any viewer with no tooling.

Writer is pure stdlib (json + struct + numpy buffers), mirroring the
AVI/PNG philosophy (viz/avi.py, viz/png.py); ``read_glb`` parses the
container back for integrity tests.
"""

from __future__ import annotations

import json
import struct
from typing import Optional, Sequence

import numpy as np

_MAGIC = 0x46546C67          # 'glTF'
_CHUNK_JSON = 0x4E4F534A     # 'JSON'
_CHUNK_BIN = 0x004E4942      # 'BIN\0'
_F32 = 5126                  # GL_FLOAT
_U32 = 5125                  # GL_UNSIGNED_INT


def _pad4(b: bytes, fill: bytes) -> bytes:
    return b + fill * (-len(b) % 4)


class _Builder:
    """Shared buffer/view/accessor assembly for both GLB exporters."""

    _TYPES = {1: "SCALAR", 3: "VEC3", 4: "VEC4", 16: "MAT4"}
    _CTYPES = {np.dtype(np.float32): _F32, np.dtype(np.uint32): _U32,
               np.dtype(np.uint8): 5121}

    def __init__(self):
        self.buffers: list[bytes] = []
        self.views = []
        self.accessors = []

    def add(self, data: np.ndarray, target=None, minmax=False):
        data = np.ascontiguousarray(data)
        raw = data.tobytes()
        offset = sum(len(b) for b in self.buffers)
        self.buffers.append(_pad4(raw, b"\x00"))
        view = {"buffer": 0, "byteOffset": offset, "byteLength": len(raw)}
        if target:
            view["target"] = target
        self.views.append(view)
        acc = {
            "bufferView": len(self.views) - 1,
            "componentType": self._CTYPES[data.dtype],
            "count": int(data.shape[0] if data.ndim > 1 else data.size),
            "type": self._TYPES[1 if data.ndim == 1 else data.shape[-1]],
        }
        if minmax:
            acc["min"] = [float(x) for x in data.min(axis=0)]
            acc["max"] = [float(x) for x in data.max(axis=0)]
        self.accessors.append(acc)
        return len(self.accessors) - 1

    def add_times(self, times: np.ndarray):
        """Keyframe-time accessor (scalar min/max required by the spec)."""
        idx = self.add(times)
        self.accessors[idx]["min"] = [float(times.min())]
        self.accessors[idx]["max"] = [float(times.max())]
        return idx

    def write(self, gltf: dict, path) -> str:
        bin_chunk = b"".join(self.buffers)
        gltf["buffers"] = [{"byteLength": len(bin_chunk)}]
        gltf["bufferViews"] = self.views
        gltf["accessors"] = self.accessors
        json_chunk = _pad4(
            json.dumps(gltf, separators=(",", ":")).encode(), b" ")
        total = 12 + 8 + len(json_chunk) + 8 + len(bin_chunk)
        with open(path, "wb") as f:
            f.write(struct.pack("<III", _MAGIC, 2, total))
            f.write(struct.pack("<II", len(json_chunk), _CHUNK_JSON))
            f.write(json_chunk)
            f.write(struct.pack("<II", len(bin_chunk), _CHUNK_BIN))
            f.write(bin_chunk)
        return str(path)


def _check_mesh_args(verts, faces):
    if verts.ndim != 2 or verts.shape[-1] != 3:
        raise ValueError(f"verts must be [V, 3], got {verts.shape}")
    if faces.ndim != 2 or faces.shape[-1] != 3:
        raise ValueError(f"faces must be [F, 3], got {faces.shape}")


def _check_fps(fps):
    if not fps > 0:
        # arange/fps would put inf/nan keyframe times into the JSON
        # chunk (json.dumps emits bare Infinity — invalid glTF that
        # strict viewers reject with an opaque parse error).
        raise ValueError(f"fps must be > 0, got {fps}")


def _check_colors(vertex_colors, verts):
    vertex_colors = np.asarray(vertex_colors, np.float32)
    if vertex_colors.shape != verts.shape:
        raise ValueError(
            f"vertex_colors must be [V, 3] matching verts, got "
            f"{vertex_colors.shape}")
    return vertex_colors


def export_glb(
    verts: np.ndarray,            # [V, 3] float
    faces: np.ndarray,            # [F, 3] int
    path,
    normals: Optional[np.ndarray] = None,   # [V, 3]; computed if None
    morph_frames: Optional[Sequence[np.ndarray]] = None,  # T x [V, 3]
    fps: float = 30.0,
    vertex_colors: Optional[np.ndarray] = None,  # [V, 3] RGB in [0, 1]
) -> str:
    """Write a mesh (optionally an animated clip) as a GLB file.

    ``morph_frames`` turns the export into a playable animation: each
    frame's vertices become a morph target (displacements from the base
    mesh) driven by a step-less linear weight animation at ``fps`` —
    exactly one target active per frame time. Viewers play it directly;
    the data path is the same `[T, V, 3]` array `fit_sequence` or
    `evaluate_sequence` produce. ``vertex_colors`` writes a float
    ``COLOR_0`` attribute — e.g. ``viz.error_colormap`` output, making a
    fit-error heatmap inspectable as a 3D object in any glTF viewer
    (``cli fit --heatmap err.glb``). Returns the path.
    """
    verts = np.asarray(verts, np.float32)
    faces = np.asarray(faces, np.uint32)
    _check_mesh_args(verts, faces)
    if normals is None:
        normals = _vertex_normals_np(verts, faces)
    normals = np.asarray(normals, np.float32)
    if vertex_colors is not None:
        vertex_colors = _check_colors(vertex_colors, verts)

    b = _Builder()
    a_pos = b.add(verts, target=34962, minmax=True)       # ARRAY_BUFFER
    a_nrm = b.add(normals, target=34962)
    a_idx = b.add(faces.reshape(-1), target=34963)        # ELEMENT_ARRAY

    primitive = {
        "attributes": {"POSITION": a_pos, "NORMAL": a_nrm},
        "indices": a_idx,
        "mode": 4,  # TRIANGLES
    }
    if vertex_colors is not None:
        primitive["attributes"]["COLOR_0"] = b.add(vertex_colors,
                                                   target=34962)
    gltf = {
        "asset": {"version": "2.0", "generator": "mano_hand_tpu"},
        "scene": 0,
        "scenes": [{"nodes": [0]}],
        "nodes": [{"mesh": 0, "name": "hand"}],
        "meshes": [{"primitives": [primitive]}],
    }

    if morph_frames is not None:
        _check_fps(fps)
        frames = [np.asarray(f, np.float32) for f in morph_frames]
        if not frames:
            raise ValueError("morph_frames is empty")
        for f in frames:
            if f.shape != verts.shape:
                raise ValueError(
                    f"morph frame shape {f.shape} != verts {verts.shape}"
                )
        targets = []
        for f in frames:
            targets.append({"POSITION": b.add(f - verts, target=34962,
                                              minmax=True)})
        primitive["targets"] = targets
        t_frames = len(frames)
        gltf["meshes"][0]["weights"] = [0.0] * t_frames
        # One-hot weight tracks sampled at frame times: LINEAR
        # interpolation cross-fades adjacent frames — smooth playback of
        # the clip without shipping per-frame meshes.
        a_time = b.add_times(np.arange(t_frames, dtype=np.float32) / fps)
        weights = np.eye(t_frames, dtype=np.float32).reshape(-1)
        a_wts = b.add(weights)
        gltf["animations"] = [{
            "name": "clip",
            "samplers": [{
                "input": a_time,
                "interpolation": "LINEAR",
                "output": a_wts,
            }],
            "channels": [{
                "sampler": 0,
                "target": {"node": 0, "path": "weights"},
            }],
        }]

    return b.write(gltf, path)


def export_glb_skinned(
    verts: np.ndarray,            # [V, 3] shaped REST-pose vertices
    faces: np.ndarray,            # [F, 3] int
    joints_rest: np.ndarray,      # [J, 3] shaped rest-pose joints
    parents: Sequence[int],       # len J, parents[0] == -1 (root)
    lbs_weights: np.ndarray,      # [V, J] skinning weights (rows sum to 1)
    path,
    pose_frames: Optional[np.ndarray] = None,  # [T, J, 3] axis-angle
    trans_frames: Optional[np.ndarray] = None,  # [T, 3] root translation
    fps: float = 30.0,
    normals: Optional[np.ndarray] = None,
    vertex_colors: Optional[np.ndarray] = None,
    max_influences: int = 4,
) -> str:
    """Write a SKINNED GLB: real skeleton, LBS weights, rotation tracks.

    The morph-target path (``export_glb``) ships baked vertices — exact
    (pose correctives included) but frame-count-sized and unposeable
    after export. This writes the model the way engines actually drive
    hands: joint nodes in the MANO hierarchy (node translation = rest
    offset from parent, so glTF's local-rotation compose IS the FK of
    ops/fk.py — reference semantics /root/reference/mano_np.py:96-110),
    inverse bind matrices from the rest joints, per-vertex JOINTS_0/
    WEIGHTS_0, and (optionally) the pose clip as quaternion rotation
    channels at ``fps`` (+ a root translation track). Any glTF engine
    can then retarget, blend, or drive the skeleton live.

    Honest divergence from the exact forward: glTF skinning is plain
    LBS — the pose-corrective blendshapes (mano_np.py:87-91) cannot be
    encoded in a skin, so posed surfaces differ from ``core.forward`` by
    the corrective magnitude (millimeter-scale). Export morph targets
    when exactness beats drivability. glTF caps influences at 4 per set;
    rows are top-``max_influences`` re-normalized (MANO weights
    concentrate on <=4 joints, so the dropped mass is tiny).
    """
    verts = np.asarray(verts, np.float32)
    faces = np.asarray(faces, np.uint32)
    _check_mesh_args(verts, faces)
    joints_rest = np.asarray(joints_rest, np.float32)
    w = np.asarray(lbs_weights, np.float32)
    j = joints_rest.shape[0]
    if joints_rest.shape != (j, 3) or len(parents) != j:
        raise ValueError(
            f"joints_rest {joints_rest.shape} / parents len {len(parents)} "
            "disagree")
    if parents[0] != -1 and parents[0] is not None:
        raise ValueError(f"parents[0] must mark the root, got {parents[0]}")
    if w.shape != (verts.shape[0], j):
        raise ValueError(f"lbs_weights must be [V, {j}], got {w.shape}")
    if not (1 <= max_influences <= 4):
        raise ValueError("max_influences must be in 1..4 (glTF set size)")
    if trans_frames is not None and pose_frames is None:
        # Refuse rather than silently drop the caller's clip (every other
        # bad input here raises; this one must too).
        raise ValueError("trans_frames requires pose_frames (the root "
                         "translation track rides the same keyframes)")
    if normals is None:
        normals = _vertex_normals_np(verts, faces)
    normals = np.asarray(normals, np.float32)

    b = _Builder()
    add = b.add

    # Top-k influence selection, re-normalized (glTF: 4 per attribute set).
    order = np.argsort(-w, axis=1)[:, :max_influences]        # [V, k]
    sel = np.take_along_axis(w, order, axis=1)                # [V, k]
    sel = sel / np.maximum(sel.sum(axis=1, keepdims=True), 1e-12)
    k = max_influences
    joints0 = np.zeros((verts.shape[0], 4), np.uint8)
    weights0 = np.zeros((verts.shape[0], 4), np.float32)
    joints0[:, :k] = order.astype(np.uint8)
    weights0[:, :k] = sel

    a_pos = add(verts, target=34962, minmax=True)
    a_nrm = add(normals, target=34962)
    a_idx = add(faces.reshape(-1), target=34963)
    a_j0 = add(joints0, target=34962)          # uint8 -> UNSIGNED_BYTE
    a_w0 = add(weights0, target=34962)

    primitive = {
        "attributes": {"POSITION": a_pos, "NORMAL": a_nrm,
                       "JOINTS_0": a_j0, "WEIGHTS_0": a_w0},
        "indices": a_idx,
        "mode": 4,
    }
    if vertex_colors is not None:
        primitive["attributes"]["COLOR_0"] = add(
            _check_colors(vertex_colors, verts), target=34962)

    # Joint nodes: local translation = rest offset from parent; the mesh
    # node (0) carries the skin, joints are nodes 1..J in input order.
    nodes = [{"mesh": 0, "skin": 0, "name": "hand"}]
    for jj in range(j):
        par = parents[jj]
        off = (joints_rest[jj] if (par is None or par < 0)
               else joints_rest[jj] - joints_rest[par])
        nodes.append({"name": f"joint_{jj}",
                      "translation": [float(x) for x in off]})
    for jj in range(j):
        par = parents[jj]
        if par is not None and par >= 0:
            nodes[1 + par].setdefault("children", []).append(1 + jj)

    # Inverse bind matrices: rotation-free rest pose -> translate(-p_j),
    # column-major per glTF.
    ibm = np.tile(np.eye(4, dtype=np.float32).reshape(1, 16), (j, 1))
    ibm[:, 12:15] = -joints_rest
    a_ibm = add(ibm)

    gltf = {
        "asset": {"version": "2.0", "generator": "mano_hand_tpu"},
        "scene": 0,
        "scenes": [{"nodes": [0, 1]}],
        "nodes": nodes,
        "meshes": [{"primitives": [primitive]}],
        "skins": [{"inverseBindMatrices": a_ibm,
                   "joints": list(range(1, j + 1)),
                   "skeleton": 1}],
    }

    if pose_frames is not None:
        _check_fps(fps)
        pose_frames = np.asarray(pose_frames, np.float32)
        if pose_frames.ndim != 3 or pose_frames.shape[1:] != (j, 3):
            raise ValueError(
                f"pose_frames must be [T, {j}, 3] axis-angle, got "
                f"{pose_frames.shape}")
        t_frames = pose_frames.shape[0]
        a_time = b.add_times(np.arange(t_frames, dtype=np.float32) / fps)

        # Axis-angle -> unit quaternion [x, y, z, w] per joint track.
        theta = np.linalg.norm(pose_frames, axis=-1, keepdims=True)
        half = 0.5 * theta
        # sin(x)/x, series-guarded at zero like ops/rodrigues.py.
        small = theta < 1e-6
        sinc = np.where(small, 0.5 - theta * theta / 48.0,
                        np.sin(half) / np.maximum(theta, 1e-12))
        quat = np.concatenate(
            [pose_frames * sinc, np.cos(half)], axis=-1
        ).astype(np.float32)                                 # [T, J, 4]

        samplers = []
        channels = []
        for jj in range(j):
            a_rot = add(np.ascontiguousarray(quat[:, jj, :]))
            samplers.append({"input": a_time,
                             "interpolation": "LINEAR",
                             "output": a_rot})
            channels.append({"sampler": len(samplers) - 1,
                             "target": {"node": 1 + jj,
                                        "path": "rotation"}})
        if trans_frames is not None:
            trans_frames = np.asarray(trans_frames, np.float32)
            if trans_frames.shape != (t_frames, 3):
                raise ValueError(
                    f"trans_frames must be [{t_frames}, 3], got "
                    f"{trans_frames.shape}")
            # Root translation composes with the root's rest offset.
            a_tr = add(trans_frames + joints_rest[0])
            samplers.append({"input": a_time,
                             "interpolation": "LINEAR",
                             "output": a_tr})
            channels.append({"sampler": len(samplers) - 1,
                             "target": {"node": 1, "path": "translation"}})
        gltf["animations"] = [{"name": "clip", "samplers": samplers,
                               "channels": channels}]

    return b.write(gltf, path)


def _vertex_normals_np(verts: np.ndarray, faces: np.ndarray) -> np.ndarray:
    """Area-weighted vertex normals, pure numpy (export-time only — the
    differentiable JAX version lives in ops/normals.py)."""
    v = verts.astype(np.float64)
    f = faces.astype(np.int64)
    fn = np.cross(v[f[:, 1]] - v[f[:, 0]], v[f[:, 2]] - v[f[:, 0]])
    n = np.zeros_like(v)
    for c in range(3):
        np.add.at(n, f[:, c], fn)
    lens = np.linalg.norm(n, axis=-1, keepdims=True)
    n = np.where(lens > 1e-12, n / np.maximum(lens, 1e-12),
                 np.array([0.0, 0.0, 1.0]))
    return n.astype(np.float32)  # spec wants unit normals — even for
    #   vertices no face references (possible on synthetic assets)


def read_glb(path) -> dict:
    """Parse a GLB container: the glTF JSON dict plus raw chunk sizes.

    For integrity checks (same role as viz/avi.py's ``read_avi_info``);
    not a general loader.
    """
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 12 or data[:4] != b"glTF":
        raise ValueError("not a GLB file (bad magic)")
    magic, version, total = struct.unpack_from("<III", data, 0)
    if total != len(data):
        raise ValueError(
            f"truncated GLB: header says {total} bytes, file has {len(data)}"
        )
    jlen, jtype = struct.unpack_from("<II", data, 12)
    if jtype != _CHUNK_JSON:
        raise ValueError("first GLB chunk is not JSON")
    gltf = json.loads(data[20:20 + jlen].decode())
    out = {"gltf": gltf, "version": version, "json_bytes": jlen}
    off = 20 + jlen
    if off < len(data):
        blen, btype = struct.unpack_from("<II", data, off)
        if btype != _CHUNK_BIN:
            raise ValueError("second GLB chunk is not BIN")
        out["bin_bytes"] = blen
        out["bin"] = data[off + 8:off + 8 + blen]
    return out
