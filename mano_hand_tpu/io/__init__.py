from mano_hand_tpu.io.obj import (
    export_obj,
    export_obj_pair,
    export_obj_sequence,
    format_obj,
    read_obj,
    restpose_path,
)
from mano_hand_tpu.io.ply import export_ply, read_ply

# Checkpoint backends: io.checkpoints (flat npz, canonical) and
# io.orbax_ckpt (Orbax PyTree checkpoints: sharded/async, optional) are
# imported as submodules on demand; neither is re-exported here to keep
# package import light.

__all__ = [
    "export_obj",
    "export_obj_pair",
    "export_obj_sequence",
    "export_ply",
    "format_obj",
    "read_obj",
    "read_ply",
    "restpose_path",
]
