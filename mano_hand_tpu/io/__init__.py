from mano_hand_tpu.io.obj import (
    export_obj,
    export_obj_pair,
    export_obj_sequence,
    format_obj,
    restpose_path,
)

__all__ = [
    "export_obj",
    "export_obj_pair",
    "export_obj_sequence",
    "format_obj",
    "restpose_path",
]
