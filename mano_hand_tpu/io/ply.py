"""PLY (Stanford polygon) export — binary and ASCII.

The reference only writes Wavefront OBJ (/root/reference/mano_np.py:181-201).
PLY is the other lingua franca of the scan-registration world (most range
scanners and point-cloud tools emit it), and the binary flavor is ~5x
smaller and loads without text parsing — the right interchange format for
the registration pipeline this framework adds (fit_lm ICP terms). Writer
only; scan INPUT is plain arrays (objectives take [N, 3] clouds directly).

Binary is little-endian, float32 positions (+ optional float32 normals),
uchar-count int32 face indices — the layout every PLY reader (MeshLab,
Open3D, trimesh) expects.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Union

import numpy as np

PathLike = Union[str, Path]


def vertex_normals_np(verts: np.ndarray, faces: np.ndarray) -> np.ndarray:
    """Area-weighted unit vertex normals, pure NumPy.

    Same math as ops.normals.vertex_normals (un-normalized face normals
    scatter-added to corners), for writer paths that must not touch a JAX
    device — e.g. MANOModel(backend="np").export_ply on a box where no
    accelerator backend can initialize.
    """
    verts = np.asarray(verts, np.float64).reshape(-1, 3)
    faces = np.asarray(faces).reshape(-1, 3)
    fv = verts[faces]
    fn = np.cross(fv[:, 1] - fv[:, 0], fv[:, 2] - fv[:, 0])
    acc = np.zeros_like(verts)
    np.add.at(acc, faces.reshape(-1), np.repeat(fn, 3, axis=0))
    return acc / np.maximum(
        np.linalg.norm(acc, axis=-1, keepdims=True), 1e-12
    )


def _ply_header(
    n_verts: int,
    n_faces: int,
    with_normals: bool,
    binary: bool,
) -> str:
    fmt = "binary_little_endian" if binary else "ascii"
    lines = [
        "ply",
        f"format {fmt} 1.0",
        "comment mano_hand_tpu export",
        f"element vertex {n_verts}",
        "property float x",
        "property float y",
        "property float z",
    ]
    if with_normals:
        lines += [
            "property float nx",
            "property float ny",
            "property float nz",
        ]
    if n_faces:
        lines += [
            f"element face {n_faces}",
            "property list uchar int vertex_indices",
        ]
    lines.append("end_header")
    return "\n".join(lines) + "\n"


def export_ply(
    verts: np.ndarray,                 # [V, 3]
    faces: Optional[np.ndarray],       # [F, 3] int, or None → point cloud
    path: PathLike,
    normals: Optional[np.ndarray] = None,  # [V, 3]
    binary: bool = True,
) -> Path:
    """Write a mesh (or, with ``faces=None``, a point cloud) as PLY.

    Positions and normals are written float32 — PLY readers assume it,
    and float32 already carries the full on-chip precision. Face indices
    are int32 with the standard uchar list count (3).
    """
    path = Path(path)
    verts = np.asarray(verts, dtype="<f4").reshape(-1, 3)
    if normals is not None:
        normals = np.asarray(normals, dtype="<f4").reshape(-1, 3)
        if normals.shape != verts.shape:
            raise ValueError(
                f"normals shape {normals.shape} != verts {verts.shape}"
            )
        vdata = np.concatenate([verts, normals], axis=1)
    else:
        vdata = verts
    if faces is not None:
        faces = np.asarray(faces, dtype="<i4").reshape(-1, 3)
        if faces.size and (
            faces.min() < 0 or faces.max() >= verts.shape[0]
        ):
            raise ValueError(
                f"face indices out of range [0, {verts.shape[0]})"
            )
    n_faces = 0 if faces is None else faces.shape[0]
    header = _ply_header(
        verts.shape[0], n_faces, normals is not None, binary
    )
    if binary:
        with open(path, "wb") as fp:
            fp.write(header.encode("ascii"))
            fp.write(vdata.tobytes())
            if faces is not None:
                # Per row: uchar 3 then three int32s — a structured array
                # writes it in one contiguous block.
                rec = np.empty(
                    n_faces,
                    dtype=[("n", "u1"), ("idx", "<i4", (3,))],
                )
                rec["n"] = 3
                rec["idx"] = faces
                fp.write(rec.tobytes())
    else:
        with open(path, "w") as fp:
            fp.write(header)
            # %.9g: the shortest format that round-trips float32 exactly
            # (%g keeps 6 significant digits and would make ascii and
            # binary exports of the same mesh disagree at ~1e-6).
            fp.write(
                "\n".join(
                    " ".join("%.9g" % x for x in row) for row in vdata
                )
            )
            fp.write("\n")
            if faces is not None and n_faces:
                fp.write(
                    "\n".join(
                        "3 %d %d %d" % tuple(row) for row in faces
                    )
                )
                fp.write("\n")
    return path
