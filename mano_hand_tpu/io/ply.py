"""PLY (Stanford polygon) I/O — binary and ASCII.

The reference only writes Wavefront OBJ (/root/reference/mano_np.py:181-201).
PLY is the other lingua franca of the scan-registration world (most range
scanners and point-cloud tools emit it), and the binary flavor is ~5x
smaller and loads without text parsing — the right interchange format for
the registration pipeline this framework adds (fit_lm ICP terms).

``export_ply`` writes little-endian binary (or ASCII), float32 positions
(+ optional float32 normals), uchar-count int32 face indices — the layout
every PLY reader (MeshLab, Open3D, trimesh) expects. ``read_ply`` loads
scanner/tool output back: both byte orders, float/double coordinates,
extra vertex properties (colors etc.) skipped by offset, faces optional —
so `cli fit --data-term points scan.ply` consumes real scans directly.
"""

from __future__ import annotations

from pathlib import Path
from typing import NamedTuple, Optional, Union

import numpy as np

PathLike = Union[str, Path]


def vertex_normals_np(verts: np.ndarray, faces: np.ndarray) -> np.ndarray:
    """Area-weighted unit vertex normals, pure NumPy.

    Same math as ops.normals.vertex_normals (un-normalized face normals
    scatter-added to corners), for writer paths that must not touch a JAX
    device — e.g. MANOModel(backend="np").export_ply on a box where no
    accelerator backend can initialize.
    """
    verts = np.asarray(verts, np.float64).reshape(-1, 3)
    faces = np.asarray(faces).reshape(-1, 3)
    fv = verts[faces]
    fn = np.cross(fv[:, 1] - fv[:, 0], fv[:, 2] - fv[:, 0])
    acc = np.zeros_like(verts)
    np.add.at(acc, faces.reshape(-1), np.repeat(fn, 3, axis=0))
    return acc / np.maximum(
        np.linalg.norm(acc, axis=-1, keepdims=True), 1e-12
    )


class PlyMesh(NamedTuple):
    """What ``read_ply`` returns. ``faces`` / ``normals`` are None when the
    file has no face element / no nx,ny,nz properties (point clouds)."""

    verts: np.ndarray                  # [V, 3] float
    faces: Optional[np.ndarray]        # [F, 3] int32 or None
    normals: Optional[np.ndarray]      # [V, 3] float or None


# PLY scalar type names (both the 1.0-spec names and the C-style aliases
# tools emit) → numpy dtype codes, endianness applied at parse time.
_PLY_TYPES = {
    "char": "i1", "int8": "i1", "uchar": "u1", "uint8": "u1",
    "short": "i2", "int16": "i2", "ushort": "u2", "uint16": "u2",
    "int": "i4", "int32": "i4", "uint": "u4", "uint32": "u4",
    "float": "f4", "float32": "f4", "double": "f8", "float64": "f8",
}


def _parse_faces_loop(body, offset, count, props, idx_prop, bo, out):
    """General (mixed-size lists / extra scalars) face parse; returns the
    advanced offset. Dtypes hoisted — the loop body is pure offset math."""
    specs = []
    for p, spec in props:
        if isinstance(spec, tuple):
            _, cnt_t, item_t = spec
            specs.append((p, np.dtype(bo + cnt_t), np.dtype(bo + item_t)))
        else:
            specs.append((p, None, np.dtype(bo + spec)))
    for _ in range(count):
        for p, cnt_d, item_d in specs:
            if cnt_d is None:
                offset += item_d.itemsize
                continue
            n = int(np.frombuffer(body, cnt_d, count=1, offset=offset)[0])
            offset += cnt_d.itemsize
            items = np.frombuffer(body, item_d, count=n, offset=offset)
            offset += item_d.itemsize * n
            if p == idx_prop:
                out.append(items)
    return offset


def read_ply(path: PathLike) -> PlyMesh:
    """Load a PLY mesh or point cloud (binary either endianness, or ASCII).

    Tolerant of what scanners actually write: extra vertex properties
    (colors, quality, ...) are skipped; the face list count may be any
    integer type; non-triangle faces are rejected with a clear error
    (MANO-side consumers are triangle-only). Only list properties named
    ``vertex_indices``/``vertex_index`` are honored on faces.
    """
    blob = Path(path).read_bytes()
    marker = b"end_header"
    idx = blob.find(marker)
    if not blob.startswith(b"ply") or idx < 0:
        raise ValueError(f"{path}: not a PLY file")
    body = blob[blob.index(b"\n", idx) + 1:]
    header = blob[:idx].decode("ascii", "replace").splitlines()

    fmt = None
    elements = []  # (name, count, [(prop_name, dtype_code | list spec)])
    for line in header[1:]:
        parts = line.split()
        if not parts or parts[0] == "comment":
            continue
        if parts[0] == "format":
            fmt = parts[1]
        elif parts[0] == "element":
            elements.append((parts[1], int(parts[2]), []))
        elif parts[0] == "property":
            if not elements:
                raise ValueError(f"{path}: property before any element")
            if parts[1] == "list":
                elements[-1][2].append(
                    (parts[4], ("list", _PLY_TYPES[parts[2]],
                                _PLY_TYPES[parts[3]]))
                )
            else:
                elements[-1][2].append((parts[2], _PLY_TYPES[parts[1]]))
    if fmt not in ("ascii", "binary_little_endian", "binary_big_endian"):
        raise ValueError(f"{path}: unsupported format {fmt!r}")
    bo = ">" if fmt == "binary_big_endian" else "<"

    verts = faces = normals = None
    offset = 0
    ascii_rows = (
        body.decode("ascii", "replace").split("\n") if fmt == "ascii"
        else None
    )
    row_cursor = 0
    for name, count, props in elements:
        is_vertex = name == "vertex"
        is_face = name == "face"
        if is_vertex:
            if any(isinstance(d, tuple) for _, d in props):
                raise ValueError(f"{path}: list property on vertex element")
            rec = np.dtype([(p, bo + d) for p, d in props])
            if fmt == "ascii":
                rows = ascii_rows[row_cursor:row_cursor + count]
                row_cursor += count
                data = np.loadtxt(
                    rows, dtype=np.float64, ndmin=2
                ) if count else np.zeros((0, len(props)))
                if data.shape[0] != count:
                    # loadtxt silently skips blank/'#' lines; rows were
                    # sliced BY count, so a skip desyncs every later
                    # element block — fail here with the real cause.
                    raise ValueError(
                        f"{path}: vertex element declares {count} rows but "
                        f"{data.shape[0]} parsed (blank or comment line "
                        "inside the vertex block?)"
                    )
                cols = {p: data[:, i] for i, (p, _) in enumerate(props)}
            else:
                data = np.frombuffer(
                    body, rec, count=count, offset=offset
                )
                offset += rec.itemsize * count
                cols = {p: data[p] for p, _ in props}
            for need in ("x", "y", "z"):
                if need not in cols:
                    raise ValueError(f"{path}: vertex missing '{need}'")
            verts = np.stack(
                [cols["x"], cols["y"], cols["z"]], axis=1
            ).astype(np.float64)
            if all(k in cols for k in ("nx", "ny", "nz")):
                normals = np.stack(
                    [cols["nx"], cols["ny"], cols["nz"]], axis=1
                ).astype(np.float64)
        elif is_face:
            out = []
            lists = [
                (p, spec) for p, spec in props if isinstance(spec, tuple)
            ]
            idx_prop = next(
                (p for p, _ in lists
                 if p in ("vertex_indices", "vertex_index")), None
            )
            if fmt == "ascii":
                rows = ascii_rows[row_cursor:row_cursor + count]
                row_cursor += count
                for i, r in enumerate(rows):
                    vals = r.split()
                    if not vals or vals[0].startswith("#"):
                        # Same scanner artifact as the vertex-block check:
                        # a blank/comment row would otherwise die below as
                        # an int() parse error with no file/element
                        # context.
                        raise ValueError(
                            f"{path}: blank or comment line inside the "
                            f"face element (row {i} of {count})"
                        )
                    # Per-row: scalars and lists in property order; pick
                    # the vertex-index list, skip everything else.
                    pos = 0
                    for p, spec in props:
                        if isinstance(spec, tuple):
                            n = int(vals[pos])
                            items = vals[pos + 1:pos + 1 + n]
                            pos += 1 + n
                            if p == idx_prop:
                                out.append([int(v) for v in items])
                        else:
                            pos += 1
            elif (count and len(props) == 1 and idx_prop is not None):
                # Fast path — the layout every mesh tool (and export_ply)
                # writes: one list property, uniform triangle counts. One
                # vectorized frombuffer instead of ~4 tiny calls per face
                # (a 10^5-face scan loads in ms, not seconds). Falls back
                # to the general loop below on mixed-size lists.
                _, cnt_t, item_t = props[0][1]
                n0 = int(np.frombuffer(
                    body, np.dtype(bo + cnt_t), count=1, offset=offset
                )[0])
                rec = np.dtype([
                    ("n", bo + cnt_t), ("idx", bo + item_t, (n0,))
                ])
                try:
                    data = np.frombuffer(
                        body, rec, count=count, offset=offset
                    )
                except ValueError:   # mixed counts shrank the tail
                    data = None
                if data is not None and (data["n"] == n0).all():
                    offset += rec.itemsize * count
                    if n0 != 3:
                        raise ValueError(
                            f"{path}: non-triangle faces "
                            "(triangulate first)"
                        )
                    out = list(data["idx"])
                else:
                    offset = _parse_faces_loop(
                        body, offset, count, props, idx_prop, bo, out
                    )
            else:
                offset = _parse_faces_loop(
                    body, offset, count, props, idx_prop, bo, out
                )
            if idx_prop is not None:
                if any(len(f) != 3 for f in out):
                    raise ValueError(
                        f"{path}: non-triangle faces (triangulate first)"
                    )
                faces = np.asarray(out, np.int32).reshape(-1, 3)
        else:
            # Unknown element: skip its data so later elements stay aligned.
            if fmt == "ascii":
                row_cursor += count
            else:
                if any(isinstance(d, tuple) for _, d in props):
                    raise ValueError(
                        f"{path}: cannot skip binary list element {name!r}"
                    )
                rec = np.dtype([(p, bo + d) for p, d in props])
                offset += rec.itemsize * count
    if verts is None:
        raise ValueError(f"{path}: no vertex element")
    return PlyMesh(verts=verts, faces=faces, normals=normals)


def _ply_header(
    n_verts: int,
    n_faces: int,
    with_normals: bool,
    binary: bool,
) -> str:
    fmt = "binary_little_endian" if binary else "ascii"
    lines = [
        "ply",
        f"format {fmt} 1.0",
        "comment mano_hand_tpu export",
        f"element vertex {n_verts}",
        "property float x",
        "property float y",
        "property float z",
    ]
    if with_normals:
        lines += [
            "property float nx",
            "property float ny",
            "property float nz",
        ]
    if n_faces:
        lines += [
            f"element face {n_faces}",
            "property list uchar int vertex_indices",
        ]
    lines.append("end_header")
    return "\n".join(lines) + "\n"


def export_ply(
    verts: np.ndarray,                 # [V, 3]
    faces: Optional[np.ndarray],       # [F, 3] int, or None → point cloud
    path: PathLike,
    normals: Optional[np.ndarray] = None,  # [V, 3]
    binary: bool = True,
) -> Path:
    """Write a mesh (or, with ``faces=None``, a point cloud) as PLY.

    Positions and normals are written float32 — PLY readers assume it,
    and float32 already carries the full on-chip precision. Face indices
    are int32 with the standard uchar list count (3).
    """
    path = Path(path)
    verts = np.asarray(verts, dtype="<f4").reshape(-1, 3)
    if normals is not None:
        normals = np.asarray(normals, dtype="<f4").reshape(-1, 3)
        if normals.shape != verts.shape:
            raise ValueError(
                f"normals shape {normals.shape} != verts {verts.shape}"
            )
        vdata = np.concatenate([verts, normals], axis=1)
    else:
        vdata = verts
    if faces is not None:
        faces = np.asarray(faces, dtype="<i4").reshape(-1, 3)
        if faces.size and (
            faces.min() < 0 or faces.max() >= verts.shape[0]
        ):
            raise ValueError(
                f"face indices out of range [0, {verts.shape[0]})"
            )
    n_faces = 0 if faces is None else faces.shape[0]
    header = _ply_header(
        verts.shape[0], n_faces, normals is not None, binary
    )
    if binary:
        with open(path, "wb") as fp:
            fp.write(header.encode("ascii"))
            fp.write(vdata.tobytes())
            if faces is not None:
                # Per row: uchar 3 then three int32s — a structured array
                # writes it in one contiguous block.
                rec = np.empty(
                    n_faces,
                    dtype=[("n", "u1"), ("idx", "<i4", (3,))],
                )
                rec["n"] = 3
                rec["idx"] = faces
                fp.write(rec.tobytes())
    else:
        with open(path, "w") as fp:
            fp.write(header)
            # %.9g: the shortest format that round-trips float32 exactly
            # (%g keeps 6 significant digits and would make ascii and
            # binary exports of the same mesh disagree at ~1e-6).
            fp.write(
                "\n".join(
                    " ".join("%.9g" % x for x in row) for row in vdata
                )
            )
            fp.write("\n")
            if faces is not None and n_faces:
                fp.write(
                    "\n".join(
                        "3 %d %d %d" % tuple(row) for row in faces
                    )
                )
                fp.write("\n")
    return path
