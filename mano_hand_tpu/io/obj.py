"""Wavefront OBJ export, format-compatible with the reference
(/root/reference/mano_np.py:181-201): ``v %f %f %f`` lines then 1-indexed
``f %d %d %d`` lines, and the twin ``<stem>_restpose.obj`` file.

Vectorized formatting (one join, one write) instead of a per-line Python
loop; an optional native writer (mano_hand_tpu.io.native) accelerates large
sequence dumps.
"""

from __future__ import annotations

from pathlib import Path
from typing import Union

import numpy as np

PathLike = Union[str, Path]


def format_obj(verts: np.ndarray, faces: np.ndarray) -> str:
    """Build the OBJ text for one mesh. Matches the reference's '%f'/'%d'
    formatting (6-decimal fixed point, 1-indexed faces)."""
    verts = np.asarray(verts, dtype=np.float64).reshape(-1, 3)
    faces = np.asarray(faces).reshape(-1, 3) + 1
    v_lines = "\n".join("v %f %f %f" % (x, y, z) for x, y, z in verts)
    f_lines = "\n".join("f %d %d %d" % (a, b, c) for a, b, c in faces)
    return v_lines + "\n" + f_lines + "\n"


def export_obj(verts: np.ndarray, faces: np.ndarray, path: PathLike) -> None:
    """Write a single mesh as OBJ."""
    with open(path, "w") as fp:
        fp.write(format_obj(verts, faces))


def restpose_path(path: PathLike) -> Path:
    """Derive the '<stem>_restpose.obj' twin path. Like the reference
    (mano_np.py:196), the path must contain '.obj'; unlike it, we raise a
    clear error instead of str.index's ValueError."""
    s = str(path)
    if ".obj" not in s:
        raise ValueError(f"OBJ path must contain '.obj', got {s!r}")
    return Path(s[: s.index(".obj")] + "_restpose.obj")


def export_obj_pair(
    verts: np.ndarray,
    rest_verts: np.ndarray,
    faces: np.ndarray,
    path: PathLike,
) -> tuple[Path, Path]:
    """Write the posed mesh at ``path`` and the rest-pose mesh at the
    ``_restpose`` twin, exactly as the reference's export_obj does
    (mano_np.py:190-201). Returns both paths."""
    path = Path(path)
    rp = restpose_path(path)
    export_obj(verts, faces, path)
    export_obj(rest_verts, faces, rp)
    return path, rp


def export_obj_sequence(
    verts_seq: np.ndarray,  # [T, V, 3]
    faces: np.ndarray,
    directory: PathLike,
    stem: str = "frame",
) -> list[Path]:
    """Dump an animation as frame_%05d.obj files (the batch analogue of the
    reference's per-frame viewer loop, /root/reference/data_explore.py:12-15).
    """
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    paths = []
    for t, verts in enumerate(np.asarray(verts_seq)):
        p = directory / f"{stem}_{t:05d}.obj"
        export_obj(verts, faces, p)
        paths.append(p)
    return paths
