"""Wavefront OBJ export, format-compatible with the reference
(/root/reference/mano_np.py:181-201): ``v %f %f %f`` lines then 1-indexed
``f %d %d %d`` lines, and the twin ``<stem>_restpose.obj`` file.

Vectorized formatting (one join, one write) instead of a per-line Python
loop; an optional native writer (mano_hand_tpu.io.native) accelerates large
sequence dumps.
"""

from __future__ import annotations

from pathlib import Path
from typing import Union

import numpy as np

PathLike = Union[str, Path]


def format_obj(
    verts: np.ndarray,
    faces: np.ndarray,
    normals: np.ndarray | None = None,
) -> str:
    """Build the OBJ text for one mesh. Matches the reference's '%f'/'%d'
    formatting (6-decimal fixed point, 1-indexed faces).

    With ``normals`` ([V, 3], e.g. from ops.vertex_normals), emits ``vn``
    lines and ``f a//a`` face refs — per-vertex normals share the vertex
    index. The reference never writes normals (its viewer recomputes
    them); plain calls stay byte-identical to it.
    """
    verts = np.asarray(verts, dtype=np.float64).reshape(-1, 3)
    faces = np.asarray(faces).reshape(-1, 3) + 1
    v_lines = "\n".join("v %f %f %f" % (x, y, z) for x, y, z in verts)
    if normals is None:
        f_lines = "\n".join("f %d %d %d" % (a, b, c) for a, b, c in faces)
        return v_lines + "\n" + f_lines + "\n"
    normals = np.asarray(normals, dtype=np.float64).reshape(-1, 3)
    if normals.shape != verts.shape:
        raise ValueError(
            f"normals shape {normals.shape} != verts {verts.shape}"
        )
    n_lines = "\n".join("vn %f %f %f" % (x, y, z) for x, y, z in normals)
    f_lines = "\n".join(
        "f %d//%d %d//%d %d//%d" % (a, a, b, b, c, c)
        for a, b, c in faces
    )
    return v_lines + "\n" + n_lines + "\n" + f_lines + "\n"


def export_obj(
    verts: np.ndarray, faces: np.ndarray, path: PathLike,
    use_native: bool | None = None,
    normals: np.ndarray | None = None,
) -> None:
    """Write a single mesh as OBJ.

    Uses the C++ serializer (io/native.py) when it is already built —
    output is byte-identical, so the switch is transparent. A single-mesh
    write never triggers a compile (a subprocess `make` would dwarf the
    millisecond write); ``use_native=True`` forces (and builds) the native
    path, ``False`` forces Python. ``normals`` adds ``vn``/``f a//a``
    records (Python path only — the native writer speaks the reference's
    normal-free dialect).
    """
    if normals is not None:
        if use_native:
            raise ValueError("native objio does not write normals")
        with open(path, "w") as fp:
            fp.write(format_obj(verts, faces, normals))
        return
    if use_native is not False:
        from mano_hand_tpu.io import native

        if native.available(build_if_needed=bool(use_native)):
            native.write_obj(verts, faces, path)
            return
        if use_native:
            raise RuntimeError("native objio requested but unavailable")
    with open(path, "w") as fp:
        fp.write(format_obj(verts, faces))


def restpose_path(path: PathLike) -> Path:
    """Derive the '<stem>_restpose.obj' twin path. Like the reference
    (mano_np.py:196), the path must contain '.obj'; unlike it, we raise a
    clear error instead of str.index's ValueError."""
    s = str(path)
    if ".obj" not in s:
        raise ValueError(f"OBJ path must contain '.obj', got {s!r}")
    return Path(s[: s.index(".obj")] + "_restpose.obj")


def export_obj_pair(
    verts: np.ndarray,
    rest_verts: np.ndarray,
    faces: np.ndarray,
    path: PathLike,
) -> tuple[Path, Path]:
    """Write the posed mesh at ``path`` and the rest-pose mesh at the
    ``_restpose`` twin, exactly as the reference's export_obj does
    (mano_np.py:190-201). Returns both paths."""
    path = Path(path)
    rp = restpose_path(path)
    export_obj(verts, faces, path)
    export_obj(rest_verts, faces, rp)
    return path, rp


def export_obj_sequence(
    verts_seq: np.ndarray,  # [T, V, 3]
    faces: np.ndarray,
    directory: PathLike,
    stem: str = "frame",
    use_native: bool | None = None,
) -> list[Path]:
    """Dump an animation as frame_%05d.obj files (the batch analogue of the
    reference's per-frame viewer loop, /root/reference/data_explore.py:12-15).

    The native sequence writer formats all frames in C++ (one call, no
    per-frame Python overhead); a sequence dump is the case where the
    one-off build pays for itself, so this path builds on demand.
    """
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    verts_seq = np.asarray(verts_seq)
    paths = [
        directory / f"{stem}_{t:05d}.obj" for t in range(verts_seq.shape[0])
    ]
    if use_native is not False:
        from mano_hand_tpu.io import native

        if native.available():
            native.write_obj_sequence(verts_seq, faces, directory, stem)
            return paths
        if use_native:
            raise RuntimeError("native objio requested but unavailable")
    for p, verts in zip(paths, verts_seq):
        export_obj(verts, faces, p, use_native=False)
    return paths


def read_obj(path: PathLike):
    """Parse a Wavefront OBJ into a ``ply.PlyMesh`` (verts, faces, normals).

    The read half of the reference's only export format
    (/root/reference/mano_np.py:181-201) — so meshes written by this
    package, the reference, or any DCC tool round-trip as fit targets
    (``cli fit hand.obj``). Handles the real-world dialect: ``v`` with
    optional per-vertex color columns (ignored), ``f`` with ``v``,
    ``v/vt``, ``v//vn`` or ``v/vt/vn`` references (vertex index taken,
    negative = relative from the end), polygons fan-triangulated,
    ``vn`` lines returned only when they map 1:1 onto vertices (the
    layout this package writes; OBJ's general per-corner normal
    indexing has no per-vertex equivalent).
    """
    from mano_hand_tpu.io.ply import PlyMesh

    verts: list[list[float]] = []
    normals: list[list[float]] = []
    faces: list[list[int]] = []
    vn_identity = True
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        for ln, raw in enumerate(fh, 1):
            parts = raw.split()
            if not parts or parts[0].startswith("#"):
                continue
            tag = parts[0]
            if tag in ("v", "vn"):
                if len(parts) < 4:
                    raise ValueError(
                        f"{path}:{ln}: '{tag}' line needs 3 components: "
                        f"{raw.rstrip()!r}"
                    )
                try:
                    xyz = [float(x) for x in parts[1:4]]
                except ValueError:
                    raise ValueError(
                        f"{path}:{ln}: bad '{tag}' component: "
                        f"{raw.rstrip()!r}"
                    ) from None
                (verts if tag == "v" else normals).append(xyz)
            elif tag == "f":
                if len(parts) < 4:
                    raise ValueError(
                        f"{path}:{ln}: face line needs >= 3 vertices: "
                        f"{raw.rstrip()!r}"
                    )
                idx = []
                for ref in parts[1:]:
                    fields = ref.split("/")
                    try:
                        i = int(fields[0])
                    except ValueError:
                        raise ValueError(
                            f"{path}:{ln}: bad face reference {ref!r}"
                        ) from None
                    # OBJ is 1-indexed; negative counts from the end of
                    # the vertices seen SO FAR (the spec's streaming rule).
                    vi = i - 1 if i > 0 else len(verts) + i
                    idx.append(vi)
                    # Track whether vn references are the IDENTITY map
                    # onto vertices; general per-corner vn indexing has
                    # no per-vertex equivalent, so anything else means
                    # "no normals" rather than silently mis-associated
                    # ones (a DCC's vn order need not match v order).
                    if len(fields) == 3 and fields[2]:
                        try:
                            ni = int(fields[2])
                        except ValueError:
                            raise ValueError(
                                f"{path}:{ln}: bad face reference {ref!r}"
                            ) from None
                        ni = ni - 1 if ni > 0 else len(normals) + ni
                        if ni != vi:
                            vn_identity = False
                # Fan-triangulate polygons (quads are common DCC output).
                for k in range(1, len(idx) - 1):
                    faces.append([idx[0], idx[k], idx[k + 1]])
    if not verts:
        raise ValueError(f"{path}: no vertex lines — not an OBJ mesh?")
    v = np.asarray(verts, np.float64)
    f = np.asarray(faces, np.int32) if faces else None
    if f is not None and (f.min() < 0 or f.max() >= len(verts)):
        raise ValueError(
            f"{path}: face index out of range (0..{len(verts) - 1} after "
            "1-indexed conversion)"
        )
    n = (
        np.asarray(normals, np.float64)
        if len(normals) == len(verts) and vn_identity else None
    )
    return PlyMesh(verts=v, faces=f, normals=n)
