"""Checkpointing for fitted parameters and pose banks.

The reference's only persistence is the asset pickle and OBJ export
(SURVEY.md §5 "checkpoint/resume"); the fitting subsystem adds recovered
(theta, beta) that are worth saving/restoring. Format: flat ``.npz`` —
host-portable, no pickle execution on load.
"""

from __future__ import annotations

from pathlib import Path
from typing import Mapping, Union

import numpy as np

PathLike = Union[str, Path]


def _npz_path(path: PathLike) -> Path:
    # np.savez appends ".npz" to suffix-less paths; normalize up front so the
    # returned path is always the file that exists on disk.
    path = Path(path)
    return path if path.suffix == ".npz" else path.with_suffix(
        path.suffix + ".npz"
    )


def result_fields(result) -> dict:
    """Fitting-result NamedTuple (FitResult, LMResult, ...) -> dict of its
    non-None fields. The single field-extraction policy shared by the npz
    and Orbax checkpoint backends."""
    if hasattr(result, "_asdict"):
        fields = result._asdict()
    else:
        fields = {k: getattr(result, k)
                  for k in ("pose", "shape", "final_loss", "loss_history",
                            "pca")
                  if hasattr(result, k)}
    return {k: v for k, v in fields.items() if v is not None}


def save_fit_result(result, path: PathLike) -> Path:
    """Persist a fitting result NamedTuple (FitResult, LMResult, ...).

    Every non-None field is saved generically, so solver-specific extras
    (e.g. LMResult.damping_history) survive the round-trip instead of
    being silently dropped.
    """
    path = _npz_path(path)
    arrays = {k: np.asarray(v) for k, v in result_fields(result).items()}
    np.savez(path, **arrays)
    return path


def load_fit_result(path: PathLike) -> dict:
    """Load a saved fit as a dict of numpy arrays."""
    return load_arrays(path)


def save_arrays(path: PathLike, **arrays: Mapping[str, np.ndarray]) -> Path:
    """Generic named-array checkpoint (pose banks, targets, ...)."""
    path = _npz_path(path)
    np.savez(path, **{k: np.asarray(v) for k, v in arrays.items()})
    return path


def load_arrays(path: PathLike) -> dict:
    # Mirror the save-side .npz normalization so save_*(x, "ckpt") /
    # load_*("ckpt") round-trips; a literal existing path still wins.
    path = Path(path)
    if not path.exists():
        path = _npz_path(path)
    with np.load(path) as z:
        return {k: z[k] for k in z.files}
