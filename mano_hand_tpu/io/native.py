"""ctypes binding for the native OBJ serializer (native/objio.cpp).

Builds the shared library on demand with g++ (no pybind11 on this image;
the C ABI + ctypes keeps the binding dependency-free). Every entry point
degrades gracefully to the pure-Python writer when no compiler is
available, so the native layer is an accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import Optional

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "build" / "libmanoio.so"
_lib: Optional[ctypes.CDLL] = None
_tried = False


def build(force: bool = False) -> bool:
    """Compile the native library. Returns True on success."""
    if _LIB_PATH.exists() and not force:
        return True
    try:
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            check=True, capture_output=True, timeout=120,
        )
        return _LIB_PATH.exists()
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def load(build_if_needed: bool = True) -> Optional[ctypes.CDLL]:
    """Load (optionally building) the native library; None if unavailable."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if not build_if_needed and not _LIB_PATH.exists():
        return None
    # The failed-build latch only suppresses rebuild *attempts*; if the
    # library has appeared since (manual make, build(force=True)), load it.
    if _tried and not _LIB_PATH.exists():
        return None
    _tried = True
    if not build():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        return None
    lib.mano_write_obj.restype = ctypes.c_int
    lib.mano_write_obj.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
    ]
    lib.mano_write_obj_sequence.restype = ctypes.c_int
    lib.mano_write_obj_sequence.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
    ]
    _lib = lib
    return _lib


def available(build_if_needed: bool = True) -> bool:
    return load(build_if_needed) is not None


def _as_c(verts, faces):
    verts = np.ascontiguousarray(verts, dtype=np.float64).reshape(-1, 3)
    faces = np.ascontiguousarray(faces, dtype=np.int32).reshape(-1, 3)
    return (
        verts,
        faces,
        verts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        faces.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )


def write_obj(verts, faces, path) -> None:
    """Native single-mesh OBJ write; raises RuntimeError on failure."""
    lib = load()
    if lib is None:
        raise RuntimeError("native objio unavailable (no compiler?)")
    verts, faces, vp, fp = _as_c(verts, faces)
    rc = lib.mano_write_obj(
        str(path).encode(), vp, verts.shape[0], fp, faces.shape[0]
    )
    if rc != 0:
        raise RuntimeError(f"mano_write_obj failed with code {rc} for {path}")


def write_obj_sequence(verts_seq, faces, directory, stem="frame") -> int:
    """Native animation dump; returns the number of frames written."""
    lib = load()
    if lib is None:
        raise RuntimeError("native objio unavailable (no compiler?)")
    verts_seq = np.ascontiguousarray(verts_seq, dtype=np.float64)
    t, v = verts_seq.shape[0], verts_seq.shape[1]
    faces = np.ascontiguousarray(faces, dtype=np.int32).reshape(-1, 3)
    Path(directory).mkdir(parents=True, exist_ok=True)
    rc = lib.mano_write_obj_sequence(
        str(directory).encode(), stem.encode(),
        verts_seq.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), t, v,
        faces.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), faces.shape[0],
    )
    if rc < 0:
        raise RuntimeError(f"mano_write_obj_sequence failed with code {rc}")
    return rc
