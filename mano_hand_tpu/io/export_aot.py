"""Ahead-of-time export of the compiled forward (``jax.export``).

The deployment story: compile the MANO forward ONCE, serialize the
StableHLO artifact — parameters baked in as constants — and serve it from
a process that never imports this package (only jax), on CPU or TPU,
with a symbolic batch dimension so one artifact covers every batch size.
The reference has no serving/deployment path at all (its only persisted
artifacts are the asset pickle and OBJ meshes,
/root/reference/dump_model.py:20-21, /root/reference/mano_np.py:181-201);
torch-ecosystem MANO layers need the full python stack at inference time.

Artifact layout: a small self-describing container —
``MANOAOT1`` magic + uint32 header length + JSON header (shapes, dims,
keypoint spec, platforms) + the ``jax.export`` blob. One file, no
sidecars.

Typical use::

    save_forward(params, "mano_fwd.jaxexp", tip_vertex_ids="smplx")
    ...                                   # later, anywhere:
    fwd = load_forward("mano_fwd.jaxexp")
    out = fwd(pose_b16x3, shape_b10)      # {"verts": ..., "keypoints": ...}
"""

from __future__ import annotations

import hashlib
import json
import struct
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import export as jax_export

import numpy as np

from mano_hand_tpu.assets.schema import ARRAY_FIELDS, ManoParams
from mano_hand_tpu.models import core
from mano_hand_tpu.ops.common import DEFAULT_PRECISION

_MAGIC = b"MANOAOT1"


def params_digest(params: ManoParams, n_hex: int = 16) -> str:
    """Content digest of a parameter set (hex, ``n_hex`` chars).

    Keys the serving engine's persistent per-bucket artifact cache
    (serving/engine.py): artifacts bake parameters in as constants, so a
    cache file is only reusable for the EXACT parameter values — the
    digest covers every array leaf's bytes plus dtype/shape and the
    static metadata (parents/side). Two assets differing anywhere get
    different artifact names instead of silently serving each other's
    meshes.
    """
    h = hashlib.sha256()
    for name in ARRAY_FIELDS:
        a = np.ascontiguousarray(np.asarray(getattr(params, name)))
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(repr(params.parents).encode())
    h.update(params.side.encode())
    return h.hexdigest()[:n_hex]


def export_forward(
    params: ManoParams,
    *,
    batch: Union[str, int] = "b",
    tip_vertex_ids=None,
    keypoint_order: str = "mano",
    fused: bool = True,
    precision=DEFAULT_PRECISION,
    platforms: Optional[Sequence[str]] = None,
) -> bytes:
    """Serialize the batched forward as a self-contained AOT artifact.

    ``batch`` is a symbolic dimension name (default: any batch size) or a
    concrete int to pin it. Parameters ride inside the artifact as
    constants — the consumer needs nothing but jax. ``tip_vertex_ids`` /
    ``keypoint_order`` bake the extended-keypoint selection
    (``core.keypoints``) into the artifact so detectors downstream get
    the 21-point set directly. ``platforms`` defaults to ("cpu", "tpu"):
    one artifact serves both (cross-platform lowering is a jax.export
    feature; no TPU is needed at export time).
    """
    tips = core.resolve_tip_ids(tip_vertex_ids, params.v_template.shape[0])
    if keypoint_order not in ("mano", "openpose"):
        raise ValueError(
            f"keypoint_order must be 'mano' or 'openpose', "
            f"got {keypoint_order!r}"
        )
    dtype = params.v_template.dtype
    n_joints = params.j_regressor.shape[0]
    n_shape = params.shape_basis.shape[-1]

    def fn(pose, shape):
        out = core.forward_batched(
            params, pose, shape, precision=precision, fused=fused
        )
        return {
            "verts": out.verts,
            "keypoints": core.keypoints(out, tips, keypoint_order),
        }

    if isinstance(batch, str):
        (b,) = jax_export.symbolic_shape(batch)
    else:
        b = int(batch)
    in_avals = (
        jax.ShapeDtypeStruct((b, n_joints, 3), dtype),
        jax.ShapeDtypeStruct((b, n_shape), dtype),
    )
    platforms = tuple(platforms) if platforms else ("cpu", "tpu")
    exported = jax_export.export(jax.jit(fn), platforms=platforms)(*in_avals)
    blob = bytes(exported.serialize())

    header = json.dumps({
        "n_joints": n_joints,
        "n_shape": n_shape,
        "n_verts": params.v_template.shape[0],
        "dtype": str(dtype),
        "batch": batch if isinstance(batch, int) else None,
        "tip_vertex_ids": list(tips) if tips else None,
        "keypoint_order": keypoint_order,
        "platforms": list(platforms),
    }).encode()
    return _MAGIC + struct.pack("<I", len(header)) + header + blob


def save_forward(params: ManoParams, path, **kw) -> str:
    """``export_forward`` to a file; returns the path."""
    data = export_forward(params, **kw)
    with open(path, "wb") as f:
        f.write(data)
    return str(path)


class AotForward:
    """A deserialized forward artifact: callable, with its metadata.

    ``fwd(pose[B, J, 3], shape[B, S]) -> {"verts": [B, V, 3],
    "keypoints": [B, K, 3]}``. ``meta`` is the export-time header dict.
    """

    def __init__(self, meta: dict, exported):
        self.meta = meta
        self._exported = exported
        # exported.call re-traces per invocation; jit it once so serving
        # calls after the first pay only dispatch (measured ~2x per-call
        # latency on the hot path otherwise).
        self._call = jax.jit(exported.call)

    @property
    def platforms(self):
        return tuple(self.meta["platforms"])

    @property
    def n_keypoints(self) -> int:
        tips = self.meta["tip_vertex_ids"]
        return self.meta["n_joints"] + (len(tips) if tips else 0)

    def __call__(self, pose, shape):
        return self._call(jnp.asarray(pose), jnp.asarray(shape))

    def __repr__(self):
        m = self.meta
        return (
            f"AotForward(verts={m['n_verts']}, joints={m['n_joints']}, "
            f"keypoints={self.n_keypoints}, "
            f"batch={m['batch'] or 'symbolic'}, "
            f"platforms={m['platforms']})"
        )


def load_forward(src) -> AotForward:
    """Load an artifact from a path or raw bytes; no model assets needed."""
    if isinstance(src, (bytes, bytearray)):
        data = bytes(src)
    else:
        with open(src, "rb") as f:
            data = f.read()
    if data[: len(_MAGIC)] != _MAGIC:
        raise ValueError(
            "not a MANO AOT artifact (bad magic); expected a file written "
            "by save_forward/export_forward"
        )
    off = len(_MAGIC)
    if len(data) < off + 4:
        raise ValueError("truncated MANO AOT artifact (no header length)")
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    if len(data) < off + hlen:
        raise ValueError("truncated MANO AOT artifact (incomplete header)")
    meta = json.loads(data[off:off + hlen].decode())
    blob = data[off + hlen:]
    return AotForward(meta, jax_export.deserialize(bytearray(blob)))
