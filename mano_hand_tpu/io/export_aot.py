"""Ahead-of-time export of the compiled forward (``jax.export``).

The deployment story: compile the MANO forward ONCE, serialize the
StableHLO artifact — parameters baked in as constants — and serve it from
a process that never imports this package (only jax), on CPU or TPU,
with a symbolic batch dimension so one artifact covers every batch size.
The reference has no serving/deployment path at all (its only persisted
artifacts are the asset pickle and OBJ meshes,
/root/reference/dump_model.py:20-21, /root/reference/mano_np.py:181-201);
torch-ecosystem MANO layers need the full python stack at inference time.

Artifact layout: a small self-describing container —
``MANOAOT1`` magic + uint32 header length + JSON header (shapes, dims,
keypoint spec, platforms) + the ``jax.export`` blob. One file, no
sidecars.

Typical use::

    save_forward(params, "mano_fwd.jaxexp", tip_vertex_ids="smplx")
    ...                                   # later, anywhere:
    fwd = load_forward("mano_fwd.jaxexp")
    out = fwd(pose_b16x3, shape_b10)      # {"verts": ..., "keypoints": ...}
"""

from __future__ import annotations

import hashlib
import json
import struct
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import export as jax_export

import numpy as np

from mano_hand_tpu.assets.schema import ARRAY_FIELDS, ManoParams
from mano_hand_tpu.models import core
from mano_hand_tpu.ops.common import DEFAULT_PRECISION

_MAGIC = b"MANOAOT1"


def params_digest(params: ManoParams, n_hex: int = 16) -> str:
    """Content digest of a parameter set (hex, ``n_hex`` chars).

    Keys the serving engine's persistent per-bucket artifact cache
    (serving/engine.py): artifacts bake parameters in as constants, so a
    cache file is only reusable for the EXACT parameter values — the
    digest covers every array leaf's bytes plus dtype/shape and the
    static metadata (parents/side). Two assets differing anywhere get
    different artifact names instead of silently serving each other's
    meshes.
    """
    h = hashlib.sha256()
    for name in ARRAY_FIELDS:
        a = np.ascontiguousarray(np.asarray(getattr(params, name)))
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(repr(params.parents).encode())
    h.update(params.side.encode())
    return h.hexdigest()[:n_hex]


def export_forward(
    params: ManoParams,
    *,
    batch: Union[str, int] = "b",
    tip_vertex_ids=None,
    keypoint_order: str = "mano",
    fused: bool = True,
    precision=DEFAULT_PRECISION,
    platforms: Optional[Sequence[str]] = None,
) -> bytes:
    """Serialize the batched forward as a self-contained AOT artifact.

    ``batch`` is a symbolic dimension name (default: any batch size) or a
    concrete int to pin it. Parameters ride inside the artifact as
    constants — the consumer needs nothing but jax. ``tip_vertex_ids`` /
    ``keypoint_order`` bake the extended-keypoint selection
    (``core.keypoints``) into the artifact so detectors downstream get
    the 21-point set directly. ``platforms`` defaults to ("cpu", "tpu"):
    one artifact serves both (cross-platform lowering is a jax.export
    feature; no TPU is needed at export time).
    """
    tips = core.resolve_tip_ids(tip_vertex_ids, params.v_template.shape[0])
    if keypoint_order not in ("mano", "openpose"):
        raise ValueError(
            f"keypoint_order must be 'mano' or 'openpose', "
            f"got {keypoint_order!r}"
        )
    dtype = params.v_template.dtype
    n_joints = params.j_regressor.shape[0]
    n_shape = params.shape_basis.shape[-1]

    def fn(pose, shape):
        out = core.forward_batched(
            params, pose, shape, precision=precision, fused=fused
        )
        return {
            "verts": out.verts,
            "keypoints": core.keypoints(out, tips, keypoint_order),
        }

    if isinstance(batch, str):
        (b,) = jax_export.symbolic_shape(batch)
    else:
        b = int(batch)
    in_avals = (
        jax.ShapeDtypeStruct((b, n_joints, 3), dtype),
        jax.ShapeDtypeStruct((b, n_shape), dtype),
    )
    platforms = tuple(platforms) if platforms else ("cpu", "tpu")
    exported = jax_export.export(jax.jit(fn), platforms=platforms)(*in_avals)
    blob = bytes(exported.serialize())

    header = json.dumps({
        "n_joints": n_joints,
        "n_shape": n_shape,
        "n_verts": params.v_template.shape[0],
        "dtype": str(dtype),
        "batch": batch if isinstance(batch, int) else None,
        "tip_vertex_ids": list(tips) if tips else None,
        "keypoint_order": keypoint_order,
        "platforms": list(platforms),
        # Provenance guard (PR 6): a consumer that KNOWS which parameter
        # set it wants can detect an artifact baked from a different one
        # (same filename, wrong constants) instead of silently serving
        # another asset's meshes — see ServingEngine._executable.
        "params_digest": params_digest(params),
    }).encode()
    return _MAGIC + struct.pack("<I", len(header)) + header + blob


def save_forward(params: ManoParams, path, **kw) -> str:
    """``export_forward`` to a file; returns the path."""
    data = export_forward(params, **kw)
    with open(path, "wb") as f:
        f.write(data)
    return str(path)


class AotForward:
    """A deserialized forward artifact: callable, with its metadata.

    ``fwd(pose[B, J, 3], shape[B, S]) -> {"verts": [B, V, 3],
    "keypoints": [B, K, 3]}``. ``meta`` is the export-time header dict.
    """

    def __init__(self, meta: dict, exported):
        self.meta = meta
        self._exported = exported
        # exported.call re-traces per invocation; jit it once so serving
        # calls after the first pay only dispatch (measured ~2x per-call
        # latency on the hot path otherwise).
        self._call = jax.jit(exported.call)

    @property
    def platforms(self):
        return tuple(self.meta["platforms"])

    @property
    def n_keypoints(self) -> int:
        tips = self.meta["tip_vertex_ids"]
        return self.meta["n_joints"] + (len(tips) if tips else 0)

    def __call__(self, pose, shape):
        return self._call(jnp.asarray(pose), jnp.asarray(shape))

    def __repr__(self):
        m = self.meta
        return (
            f"AotForward(verts={m['n_verts']}, joints={m['n_joints']}, "
            f"keypoints={self.n_keypoints}, "
            f"batch={m['batch'] or 'symbolic'}, "
            f"platforms={m['platforms']})"
        )


def _split_container(data: bytes):
    """(meta, blob) of a ``_MAGIC`` container; ValueError on damage."""
    if data[: len(_MAGIC)] != _MAGIC:
        raise ValueError(
            "not a MANO AOT artifact (bad magic); expected a file written "
            "by save_forward/export_forward"
        )
    off = len(_MAGIC)
    if len(data) < off + 4:
        raise ValueError("truncated MANO AOT artifact (no header length)")
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    if len(data) < off + hlen:
        raise ValueError("truncated MANO AOT artifact (incomplete header)")
    meta = json.loads(data[off:off + hlen].decode())
    return meta, data[off + hlen:]


def load_forward(src) -> AotForward:
    """Load an artifact from a path or raw bytes; no model assets needed."""
    if isinstance(src, (bytes, bytearray)):
        data = bytes(src)
    else:
        with open(src, "rb") as f:
            data = f.read()
    meta, blob = _split_container(data)
    return AotForward(meta, jax_export.deserialize(bytearray(blob)))


# --------------------------------------------------------------------------
# The executable lattice (PR 6): EVERY program the serving engine can
# reach — (bucket x kind {full, pose-only gathered} x table capacity x
# platform, plus the PR-3 CPU-failover tier) — pre-baked as versioned
# artifacts keyed by params_digest, so a restarted process boots with
# ZERO re-traces instead of a recompile storm.
#
# Unlike ``export_forward`` (constants baked in; a consumer needs only
# jax), lattice entries keep the parameters / subject table as runtime
# ARGUMENTS — the engine's bit-identity policy (constant-baking changes
# XLA's float folding). The pytree containers (ManoParams, SubjectTable)
# are not export-serializable, so entries use a FLAT-LEAF calling
# convention: a plain tuple of the array leaves in a fixed order, with
# the static aux data (parents, side) baked at trace time and guarded by
# the digest. Measured on CPU: a deserialized entry's results are
# f32 BIT-identical to the live jitted program (pinned in
# tests/test_coldstart.py).
#
# Manifest format (``lattice.json``, documented in README "Cold start &
# persistence"):
#
#     {"schema": 1,                 # LATTICE_SCHEMA_VERSION
#      "params_digest": "<hex16>",  # params_digest() of the asset
#      "dtype": "float32", "n_joints": 16, "n_shape": 10,
#      "entries": {"full/b8":        {"file": ..., "sha256": ...,
#                                     "bucket": 8, "platforms": [...]},
#                  "gather/b8/c16":  {..., "capacity": 16},
#                  "cpu/b8":         {...}}}
#
# Versioning rule: ``schema`` bumps on ANY incompatible change (calling
# convention, key layout, checksum scheme). A loader seeing a different
# schema — or a different params_digest, or a damaged entry — must
# DEGRADE to a counted recompile (structured telemetry, never a crash,
# never a silently-wrong executable); only same-schema, same-digest,
# checksum-clean entries are served.

LATTICE_SCHEMA_VERSION = 1
LATTICE_MANIFEST = "lattice.json"

# SubjectTable leaves in lattice calling-convention order.
_TABLE_FIELDS = ("v_shaped", "joints", "shape", "pose_basis", "lbs_weights")


def params_leaves(params: ManoParams):
    """A ManoParams' array leaves as the flat tuple lattice ``full``/
    ``cpu`` entries take (ARRAY_FIELDS order; parents/side ride as
    static aux at bake, guarded by the digest)."""
    return tuple(jnp.asarray(getattr(params, n)) for n in ARRAY_FIELDS)


def table_leaves(table):
    """A SubjectTable's array leaves as the flat tuple lattice ``gather``
    entries take (fixed order; parents ride as static aux at bake)."""
    return tuple(jnp.asarray(getattr(table, n)) for n in _TABLE_FIELDS)


def _avals(leaves):
    return tuple(
        jax.ShapeDtypeStruct(np.asarray(a).shape, np.asarray(a).dtype)
        for a in leaves)


def _pack(kind: str, params: ManoParams, extra: dict, exported) -> bytes:
    header = json.dumps({
        "program": kind,
        "schema": LATTICE_SCHEMA_VERSION,
        "params_digest": params_digest(params),
        "n_joints": params.j_regressor.shape[0],
        "n_shape": params.shape_basis.shape[-1],
        "dtype": str(params.v_template.dtype),
        **extra,
    }).encode()
    blob = bytes(exported.serialize())
    return _MAGIC + struct.pack("<I", len(header)) + header + blob


def export_serve_full(
    params: ManoParams, bucket: int, *,
    platforms: Sequence[str] = ("cpu", "tpu"),
    precision=DEFAULT_PRECISION,
) -> bytes:
    """One ``full`` lattice entry: the bucketed full forward with params
    as runtime arguments — the SAME program family as the engine's live
    ``build_bucket_executable`` jit, so a lattice-served bucket stays
    bit-identical to the direct path. Call convention:
    ``call(params_leaves, pose[b, J, 3], shape[b, S]) -> verts``."""
    import dataclasses

    from mano_hand_tpu.models import core

    dtype = params.v_template.dtype
    n_j = params.j_regressor.shape[0]
    n_s = params.shape_basis.shape[-1]

    def fn(leaves, pose, shape):
        q = dataclasses.replace(
            params, **{n: x for n, x in zip(ARRAY_FIELDS, leaves)})
        return core.forward_batched(q, pose, shape,
                                    precision=precision).verts

    exported = jax_export.export(
        jax.jit(fn), platforms=tuple(platforms))(
        _avals(params_leaves(params)),
        jax.ShapeDtypeStruct((bucket, n_j, 3), dtype),
        jax.ShapeDtypeStruct((bucket, n_s), dtype))
    return _pack("serve_full", params,
                 {"bucket": int(bucket), "platforms": list(platforms)},
                 exported)


def export_serve_gather(
    params: ManoParams, bucket: int, capacity: int, *,
    platforms: Sequence[str] = ("cpu", "tpu"),
    precision=DEFAULT_PRECISION,
) -> bytes:
    """One ``gather`` lattice entry: the mixed-subject pose-only program
    (core.forward_posed_gather) at (bucket, table capacity), table and
    index as runtime arguments. Call convention:
    ``call(table_leaves, idx[b] int32, pose[b, J, 3]) -> verts``."""
    import dataclasses

    from mano_hand_tpu.models import core

    dtype = params.v_template.dtype
    n_j = params.j_regressor.shape[0]
    table = core.subject_table(params, capacity)

    def fn(leaves, idx, pose):
        t = dataclasses.replace(
            table, **{n: x for n, x in zip(_TABLE_FIELDS, leaves)})
        return core.forward_posed_gather(t, idx, pose,
                                         precision=precision).verts

    exported = jax_export.export(
        jax.jit(fn), platforms=tuple(platforms))(
        _avals(table_leaves(table)),
        jax.ShapeDtypeStruct((bucket,), np.int32),
        jax.ShapeDtypeStruct((bucket, n_j, 3), dtype))
    return _pack("serve_gather", params,
                 {"bucket": int(bucket), "capacity": int(capacity),
                  "platforms": list(platforms)},
                 exported)


def _entry_name(digest: str, key: str) -> str:
    return f"lat_{digest}_{key.replace('/', '_')}.jaxexp"


def bake_lattice(
    params: ManoParams,
    out_dir,
    *,
    buckets: Sequence[int],
    capacities: Sequence[int] = (),
    platforms: Sequence[str] = ("cpu", "tpu"),
    cpu_fallback: bool = True,
    log=None,
) -> dict:
    """Pre-bake the full executable lattice into ``out_dir``; returns the
    manifest dict (also written as ``lattice.json``).

    Entries: ``full/b{B}`` for every bucket; ``gather/b{B}/c{C}`` for
    every (bucket, capacity) pair; ``cpu/b{B}`` (the PR-3 failover tier,
    platforms=("cpu",)) when ``cpu_fallback``. Baking is trace + lower +
    serialize — no backend compile — so it is warm-up-class host work.
    Every write is atomic (temp + rename) and the manifest lands LAST,
    so a process killed mid-bake leaves either no manifest (no lattice —
    the engine jit-compiles as before) or a complete, checksummed one.
    """
    import os
    from pathlib import Path

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    digest = params_digest(params)
    # MERGE into an existing same-schema, same-digest manifest: two
    # engines with different bucket/capacity configs sharing one
    # aot_dir (or a drill beside a production engine) must union their
    # entries, not clobber each other's. Any other manifest (different
    # digest, different schema, unreadable) is replaced wholesale.
    entries = {}
    prior = out_dir / LATTICE_MANIFEST
    if prior.exists():
        try:
            old = json.loads(prior.read_text())
            if (old.get("schema") == LATTICE_SCHEMA_VERSION
                    and old.get("params_digest") == digest):
                entries = dict(old.get("entries") or {})
        except (OSError, ValueError):
            pass

    def emit(key: str, data: bytes, meta: dict):
        name = _entry_name(digest, key)
        tmp = out_dir / f"{name}.tmp{os.getpid()}"
        tmp.write_bytes(data)
        os.replace(tmp, out_dir / name)
        entries[key] = {
            "file": name,
            "sha256": hashlib.sha256(data).hexdigest(),
            **meta,
        }
        if log:
            log(f"lattice: baked {key} ({len(data)} bytes)")

    for b in buckets:
        emit(f"full/b{b}",
             export_serve_full(params, b, platforms=platforms),
             {"bucket": int(b), "platforms": list(platforms)})
        for c in capacities:
            emit(f"gather/b{b}/c{c}",
                 export_serve_gather(params, b, c, platforms=platforms),
                 {"bucket": int(b), "capacity": int(c),
                  "platforms": list(platforms)})
        if cpu_fallback:
            emit(f"cpu/b{b}",
                 export_serve_full(params, b, platforms=("cpu",)),
                 {"bucket": int(b), "platforms": ["cpu"]})

    manifest = {
        "schema": LATTICE_SCHEMA_VERSION,
        "params_digest": digest,
        "dtype": str(params.v_template.dtype),
        "n_joints": int(params.j_regressor.shape[0]),
        "n_shape": int(params.shape_basis.shape[-1]),
        "entries": entries,
    }
    tmp = out_dir / f"{LATTICE_MANIFEST}.tmp{os.getpid()}"
    tmp.write_text(json.dumps(manifest, indent=1, sort_keys=True))
    os.replace(tmp, out_dir / LATTICE_MANIFEST)
    return manifest


class ExecutableLattice:
    """Boot-time view of a baked lattice directory.

    ``get(kind, bucket, capacity)`` returns the jitted deserialized
    program, or None when the entry is absent or DAMAGED — a truncated,
    corrupted, checksum- or digest-mismatched entry is reported through
    ``on_failure`` (the engine counts it as ``aot_load_failures``) and
    the caller falls back to a jit compile; a bad entry can never crash
    boot or serve silently-wrong results (the checksum covers the whole
    file; the header digest re-checks provenance after the checksum).
    Deserialized programs are cached, so a warm entry is a dict hit.

    Thread-safe (PR 18): N lanes boot concurrently against ONE lattice
    object. The read/deserialize work runs OUTSIDE the lock (it is the
    slow part); publication is first-wins, so two threads racing the
    same key both get the same jitted wrapper back and the loser's
    unwarmed duplicate is discarded before it costs a compile (jit is
    lazy).
    """

    def __init__(self, directory, manifest: dict, on_failure=None):
        import threading
        from pathlib import Path

        self.dir = Path(directory)
        self.manifest = manifest
        self._on_failure = on_failure
        self._lock = threading.Lock()
        self._cache: dict = {}
        self._bad: set = set()

    @staticmethod
    def key_of(kind: str, bucket: int, capacity=None) -> str:
        if kind == "gather":
            return f"gather/b{bucket}/c{capacity}"
        return f"{kind}/b{bucket}"

    def __contains__(self, key: str) -> bool:
        return key in self.manifest.get("entries", {})

    def _fail(self, key: str, reason: str):
        import warnings

        with self._lock:
            self._bad.add(key)
        if self._on_failure is not None:
            self._on_failure(key, reason)
        warnings.warn(
            f"lattice entry {key}: {reason}; degrading to a jit "
            "recompile (counted)")
        return None

    def get(self, kind: str, bucket: int, capacity=None, platform=None):
        """``platform`` (e.g. ``jax.default_backend()``) additionally
        requires the entry to have been lowered for that backend — an
        entry baked for other platforms is a counted degrade, not a
        call-time crash in the middle of boot."""
        key = self.key_of(kind, bucket, capacity)
        with self._lock:
            if key in self._cache:
                return self._cache[key]
            if key in self._bad:
                return None
        ent = self.manifest.get("entries", {}).get(key)
        if ent is None:
            return None        # never baked: a plain miss, not a failure
        path = self.dir / ent["file"]
        try:
            data = path.read_bytes()
        except OSError as e:
            return self._fail(key, f"unreadable ({e})")
        got = hashlib.sha256(data).hexdigest()
        if got != ent["sha256"]:
            return self._fail(
                key, "checksum mismatch (truncated or corrupted entry)")
        try:
            meta, blob = _split_container(data)
        except ValueError as e:
            return self._fail(key, str(e))
        if meta.get("schema") != self.manifest.get("schema"):
            return self._fail(
                key, f"entry schema {meta.get('schema')} != manifest "
                     f"{self.manifest.get('schema')}")
        if meta.get("params_digest") != self.manifest.get("params_digest"):
            return self._fail(
                key, "entry params_digest does not match the manifest "
                     "(artifact baked from a different parameter set)")
        if platform is not None and platform not in (
                meta.get("platforms") or ()):
            return self._fail(
                key, f"entry was lowered for {meta.get('platforms')}, "
                     f"not the running backend {platform!r}")
        try:
            call = jax.jit(jax_export.deserialize(bytearray(blob)).call)
        except Exception as e:  # noqa: BLE001 — degrade, never crash boot
            return self._fail(key, f"deserialize failed "
                                   f"({type(e).__name__}: {e})")
        with self._lock:
            return self._cache.setdefault(key, call)


def load_lattice(aot_dir, params_or_digest, *, on_failure=None):
    """Open ``aot_dir``'s lattice for the given parameter set.

    Returns None when no manifest exists (no lattice was ever baked —
    not a fault) AND when the manifest is unusable (unparseable, wrong
    schema version, or baked for a different ``params_digest``): those
    report through ``on_failure("<manifest>", reason)`` and the engine
    boots latticeless — a counted recompile storm beats wrong results.
    """
    from pathlib import Path

    path = Path(aot_dir) / LATTICE_MANIFEST
    if not path.exists():
        return None
    digest = (params_or_digest if isinstance(params_or_digest, str)
              else params_digest(params_or_digest))

    def fail(reason):
        import warnings

        if on_failure is not None:
            on_failure("<manifest>", reason)
        warnings.warn(f"lattice manifest {path}: {reason}; booting "
                      "without the lattice (counted)")
        return None

    try:
        manifest = json.loads(path.read_text())
    except (OSError, ValueError) as e:
        return fail(f"unreadable ({type(e).__name__}: {e})")
    if manifest.get("schema") != LATTICE_SCHEMA_VERSION:
        return fail(f"schema {manifest.get('schema')} != supported "
                    f"{LATTICE_SCHEMA_VERSION} (versioning rule: bump = "
                    "re-bake)")
    if manifest.get("params_digest") != digest:
        return fail(f"params_digest {manifest.get('params_digest')} does "
                    f"not match this parameter set ({digest})")
    return ExecutableLattice(aot_dir, manifest, on_failure=on_failure)
