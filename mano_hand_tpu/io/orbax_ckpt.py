"""Optional Orbax-backed checkpointing for fit results and pose banks.

The flat ``.npz`` format (io/checkpoints.py) is the canonical, dependency-
light path. This module layers the JAX-ecosystem-native alternative on top:
Orbax writes sharded arrays without device->host gathering first, supports
async saves that overlap training steps, and restores directly onto a
``jax.sharding.Mesh`` — the right checkpoint story once fitting runs
multi-chip (SURVEY.md §5 "checkpoint/resume": the reference has only the
asset pickle, /root/reference/dump_model.py:20-21).

Import is deferred and failure-tolerant: everything raises a clear error at
call time when orbax is absent, so the core package never depends on it.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional, Union

import numpy as np

PathLike = Union[str, Path]


def available() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


def _ocp():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except ImportError as e:  # pragma: no cover - orbax is in this image
        raise ImportError(
            "orbax-checkpoint is not installed; use "
            "mano_hand_tpu.io.checkpoints (npz) instead"
        ) from e


def _as_tree(result) -> dict:
    """A fit result (NamedTuple) or plain mapping -> a PyTree of arrays.

    Shares the field-extraction policy with the npz backend
    (io.checkpoints.result_fields) so the two never drift.
    """
    from mano_hand_tpu.io.checkpoints import result_fields

    if isinstance(result, dict):
        return {k: v for k, v in result.items() if v is not None}
    return result_fields(result)


_ASYNC_CKPTR = None  # one long-lived AsyncCheckpointer; created on demand


def _async_ckptr():
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        ocp = _ocp()
        _ASYNC_CKPTR = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _ASYNC_CKPTR


def save(result, path: PathLike, *, async_save: bool = False) -> Path:
    """Persist a fit result / array dict as an Orbax PyTree checkpoint.

    ``async_save=True`` returns after scheduling the write on ONE reused
    background checkpointer; a subsequent ``save`` first joins the
    in-flight write (Orbax serializes saves on the same checkpointer), and
    ``wait()`` joins explicitly — use async to overlap checkpointing with
    the next fitting batch, and call ``wait()`` before process exit.
    """
    ocp = _ocp()
    path = Path(path).absolute()
    if async_save:
        ckptr = _async_ckptr()
        ckptr.save(path, _as_tree(result), force=True)
    else:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, _as_tree(result), force=True)
        ckptr.wait_until_finished()
    return path


def wait() -> None:
    """Join all outstanding async saves."""
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


# --------------------------------------------------------------------------
# Warm-state persistence (PR 6): a (meta, arrays) state pair — JSON-able
# metadata plus a flat dict of numpy arrays — written either through
# Orbax (the JAX-ecosystem-native path: sharded, async-capable) or a
# pickle fallback when orbax is absent, so the SubjectTable checkpoint
# (serving/engine.py:checkpoint_subjects) works on every install. The
# two layouts are self-describing: the loader detects which backend
# wrote a directory, so a checkpoint travels between installs.

_STATE_META = "state_meta.json"
_STATE_ARRAYS = "arrays"          # orbax PyTree subdirectory
_STATE_PICKLE = "state.pkl"       # pickle-fallback single file


def save_state(meta: dict, arrays: dict, path: PathLike,
               *, backend: Optional[str] = None) -> Path:
    """Persist ``(meta, arrays)`` into directory ``path``.

    ``backend``: None auto-selects (orbax when importable, else pickle);
    ``"orbax"`` / ``"pickle"`` force one (tests pin the fallback this
    way). Writes are crash-safe at the directory level: the meta file
    lands LAST, so a half-written checkpoint is detected as absent by
    ``load_state`` rather than restored half-blank.
    """
    import json
    import os

    path = Path(path).absolute()
    if backend is None:
        backend = "orbax" if available() else "pickle"
    if backend not in ("orbax", "pickle"):
        raise ValueError(f"backend must be 'orbax' or 'pickle', "
                         f"got {backend!r}")
    path.mkdir(parents=True, exist_ok=True)
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    # Zero-size arrays ride in the meta sidecar as (shape, dtype):
    # orbax/tensorstore refuses empty tensors, and an empty leaf carries
    # no bytes anyway. Applied to both backends so the layouts agree.
    empty = {k: [list(v.shape), str(v.dtype)]
             for k, v in arrays.items() if v.size == 0}
    arrays = {k: v for k, v in arrays.items() if v.size > 0}
    meta = {**meta, "_empty_arrays": empty}
    if backend == "orbax":
        if arrays:
            ocp = _ocp()
            ckptr = ocp.StandardCheckpointer()
            ckptr.save(path / _STATE_ARRAYS, arrays, force=True)
            ckptr.wait_until_finished()
        else:
            # Every array was empty this time: a STALE arrays/ dir from
            # a previous checkpoint at this path must not be restored
            # against the new meta (load_state keys off its existence).
            import shutil

            shutil.rmtree(path / _STATE_ARRAYS, ignore_errors=True)
    else:
        import pickle

        tmp = path / f"{_STATE_PICKLE}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(arrays, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path / _STATE_PICKLE)
    tmp = path / f"{_STATE_META}.tmp{os.getpid()}"
    tmp.write_text(json.dumps({**meta, "backend": backend},
                              indent=1, sort_keys=True))
    os.replace(tmp, path / _STATE_META)
    return path


def load_state(path: PathLike):
    """Restore a ``save_state`` checkpoint: ``(meta, arrays)`` with
    host-resident numpy arrays. Raises FileNotFoundError when ``path``
    holds no complete checkpoint (no meta file — including the killed-
    mid-write case, whose meta never landed)."""
    import json
    import pickle

    path = Path(path).absolute()
    meta_path = path / _STATE_META
    if not meta_path.exists():
        raise FileNotFoundError(
            f"no complete checkpoint at {path} (missing {_STATE_META})")
    meta = json.loads(meta_path.read_text())
    backend = meta.get("backend", "pickle")
    if backend == "orbax":
        arrays_dir = path / _STATE_ARRAYS
        if arrays_dir.exists():
            ocp = _ocp()
            restored = ocp.StandardCheckpointer().restore(arrays_dir)
            arrays = {k: np.asarray(v) for k, v in restored.items()}
        else:
            arrays = {}     # every array was empty (meta sidecar only)
    else:
        with open(path / _STATE_PICKLE, "rb") as f:
            arrays = pickle.load(f)
        arrays = {k: np.asarray(v) for k, v in arrays.items()}
    for k, (shape, dtype) in (meta.pop("_empty_arrays", None) or {}).items():
        arrays[k] = np.zeros(shape, dtype)
    return meta, arrays


# --------------------------------------------------------------------------
# Row pages (PR 16): the cold tier of the tiered subject store. One
# directory per subject digest holding that subject's baked table row —
# a (meta, arrays) state pair, so the crash-safety (meta lands LAST) and
# backend-portability of save_state carry over unchanged. Pages are
# content-verified at load: the meta records a sha256 per array, and the
# "shape" array is the digest preimage itself, so the STORE re-derives
# the digest from the bytes — a damaged page is detected, not served.

_ROW_PAGE_PREFIX = "row-"


def row_page_path(digest: str, root: PathLike) -> Path:
    return Path(root).absolute() / f"{_ROW_PAGE_PREFIX}{digest}"


def save_row_page(digest: str, arrays: dict, root: PathLike,
                  *, backend: Optional[str] = None) -> Path:
    """Write one subject's baked row as a verifiable cold page."""
    import hashlib

    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    meta = {
        "kind": "subject_row_page",
        "digest": digest,
        "row_sha256": {
            k: hashlib.sha256(np.ascontiguousarray(v).tobytes()).hexdigest()
            for k, v in arrays.items()},
    }
    return save_state(meta, arrays, row_page_path(digest, root),
                      backend=backend)


def load_row_page(digest: str, root: PathLike):
    """Restore one cold page as ``(meta, arrays)``. Raises on a missing
    or unreadable page; CONTENT verification against ``meta["row_sha256"]``
    is the caller's job (serving/subject_store.py does it, so damage
    degrades to a counted re-bake there, never an exception here)."""
    return load_state(row_page_path(digest, root))


def list_row_pages(root: PathLike) -> list:
    """Digests with a COMPLETE page under ``root`` (meta file present —
    the same completeness test load_state applies)."""
    root = Path(root).absolute()
    if not root.is_dir():
        return []
    out = []
    for p in root.iterdir():
        if (p.is_dir() and p.name.startswith(_ROW_PAGE_PREFIX)
                and (p / _STATE_META).exists()):
            out.append(p.name[len(_ROW_PAGE_PREFIX):])
    return sorted(out)


def load(path: PathLike, target: Optional[Any] = None) -> dict:
    """Restore a checkpoint as a dict of numpy arrays.

    ``target`` (a PyTree of like-shaped arrays, e.g. jax.ShapeDtypeStruct
    or device arrays with shardings) restores directly into that structure/
    placement; without it, arrays come back host-resident.
    """
    ocp = _ocp()
    path = Path(path).absolute()
    ckptr = ocp.StandardCheckpointer()
    if target is not None:
        return ckptr.restore(path, target)
    restored = ckptr.restore(path)

    def to_np(x):
        if isinstance(x, dict):
            return {k: to_np(v) for k, v in x.items()}
        return np.asarray(x)

    return {k: to_np(v) for k, v in restored.items()}
