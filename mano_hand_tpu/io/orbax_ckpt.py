"""Optional Orbax-backed checkpointing for fit results and pose banks.

The flat ``.npz`` format (io/checkpoints.py) is the canonical, dependency-
light path. This module layers the JAX-ecosystem-native alternative on top:
Orbax writes sharded arrays without device->host gathering first, supports
async saves that overlap training steps, and restores directly onto a
``jax.sharding.Mesh`` — the right checkpoint story once fitting runs
multi-chip (SURVEY.md §5 "checkpoint/resume": the reference has only the
asset pickle, /root/reference/dump_model.py:20-21).

Import is deferred and failure-tolerant: everything raises a clear error at
call time when orbax is absent, so the core package never depends on it.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional, Union

import numpy as np

PathLike = Union[str, Path]


def available() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


def _ocp():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except ImportError as e:  # pragma: no cover - orbax is in this image
        raise ImportError(
            "orbax-checkpoint is not installed; use "
            "mano_hand_tpu.io.checkpoints (npz) instead"
        ) from e


def _as_tree(result) -> dict:
    """A fit result (NamedTuple) or plain mapping -> a PyTree of arrays.

    Shares the field-extraction policy with the npz backend
    (io.checkpoints.result_fields) so the two never drift.
    """
    from mano_hand_tpu.io.checkpoints import result_fields

    if isinstance(result, dict):
        return {k: v for k, v in result.items() if v is not None}
    return result_fields(result)


_ASYNC_CKPTR = None  # one long-lived AsyncCheckpointer; created on demand


def _async_ckptr():
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        ocp = _ocp()
        _ASYNC_CKPTR = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _ASYNC_CKPTR


def save(result, path: PathLike, *, async_save: bool = False) -> Path:
    """Persist a fit result / array dict as an Orbax PyTree checkpoint.

    ``async_save=True`` returns after scheduling the write on ONE reused
    background checkpointer; a subsequent ``save`` first joins the
    in-flight write (Orbax serializes saves on the same checkpointer), and
    ``wait()`` joins explicitly — use async to overlap checkpointing with
    the next fitting batch, and call ``wait()`` before process exit.
    """
    ocp = _ocp()
    path = Path(path).absolute()
    if async_save:
        ckptr = _async_ckptr()
        ckptr.save(path, _as_tree(result), force=True)
    else:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, _as_tree(result), force=True)
        ckptr.wait_until_finished()
    return path


def wait() -> None:
    """Join all outstanding async saves."""
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()


def load(path: PathLike, target: Optional[Any] = None) -> dict:
    """Restore a checkpoint as a dict of numpy arrays.

    ``target`` (a PyTree of like-shaped arrays, e.g. jax.ShapeDtypeStruct
    or device arrays with shardings) restores directly into that structure/
    placement; without it, arrays come back host-resident.
    """
    ocp = _ocp()
    path = Path(path).absolute()
    ckptr = ocp.StandardCheckpointer()
    if target is not None:
        return ckptr.restore(path, target)
    restored = ckptr.restore(path)

    def to_np(x):
        if isinstance(x, dict):
            return {k: to_np(v) for k, v in x.items()}
        return np.asarray(x)

    return {k: to_np(v) for k, v in restored.items()}
