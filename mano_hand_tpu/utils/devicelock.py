"""Cooperative single-device lock for benchmark runs.

The axon tunnel exposes ONE TPU chip; two benchmark processes contending
for it (or for the single host CPU core) corrupt each other's timings —
round 3's driver bench probed 8x into a tunnel outage while a leftover
builder retry pipeline was still polling the same device (VERDICT.md
"What's weak" #1). This module makes contention impossible by
construction:

- every bench acquires an exclusive ``flock`` on ``LOCK_PATH`` before
  touching the backend;
- a *driver* bench (the authoritative end-of-round run) additionally
  writes a priority-claim file for its whole lifetime. Builder-side
  retry loops poll that file and STAND DOWN while it is fresh, so the
  driver never queues behind an hours-long builder loop;
- a *builder* bench never waits: if the lock is held it exits
  immediately (its wrapper loop retries later, see
  ``scripts/bench_tpu_wait.sh`` — which is itself deadline-bounded, so
  no retry loop outlives its usefulness).

The lock is advisory: a driver that cannot get it within ``wait_s``
proceeds anyway (logging loudly) — worst case equals today's behavior;
it must never turn a flaky lockfile into a missing BENCH_r{N}.json.

Shell-side counterpart: a claim is "fresh" when the file exists and its
mtime is younger than ``CLAIM_FRESH_S`` (stale claims from crashed
drivers must not wedge builders forever).
"""

from __future__ import annotations

import errno
import fcntl
import json
import os
import time

# MANO_DEVICE_LOCK_DIR redirects both files (tests isolate themselves so
# a CI bench subprocess never queues behind a real builder pipeline).
_LOCK_DIR = os.environ.get("MANO_DEVICE_LOCK_DIR", "/tmp")
LOCK_PATH = os.path.join(_LOCK_DIR, "mano_tpu_device.lock")
CLAIM_PATH = os.path.join(_LOCK_DIR, "mano_tpu_device.priority")
CLAIM_FRESH_S = 2.0 * 3600.0


class DeviceBusy(RuntimeError):
    """A builder-role bench found the device lock held (stand down)."""


def _claim_age_s() -> float | None:
    try:
        return time.time() - os.stat(CLAIM_PATH).st_mtime
    except OSError:
        return None


def priority_claim_active() -> bool:
    """True while a driver bench holds (or recently held) its claim."""
    age = _claim_age_s()
    return age is not None and age < CLAIM_FRESH_S


class DeviceLock:
    """``with DeviceLock(role, ...):`` around any device-touching bench.

    role="driver": writes the priority claim, waits up to ``wait_s`` for
    the EXCLUSIVE flock (refreshing the claim so builders keep standing
    down), then proceeds with or without it.
    role="builder": raises DeviceBusy if a fresh driver claim exists or
    the flock is held — never waits, never blocks a driver.
    role="server" (PR 15 — edge-worker coexistence): takes a SHARED
    flock, so N `mano serve` workers coexist on the device while any
    bench's exclusive lock still excludes them all. Like a builder it
    never waits and stands down for a fresh driver claim or a running
    exclusive bench; unlike a builder it does not conflict with its
    sibling servers. A driver arriving while servers hold shared locks
    rides its existing advisory wait (workers are expected to drain on
    the operator's SIGTERM well inside that window).
    """

    def __init__(self, role: str = "driver", wait_s: float = 1200.0,
                 log=lambda m: None):
        if role not in ("driver", "builder", "server"):
            raise ValueError(f"unknown role {role!r}")
        self.role = role
        self.wait_s = wait_s
        self.log = log
        self._fd = None
        self._locked = False
        self._claimed = False

    def _write_claim(self) -> None:
        tmp = f"{CLAIM_PATH}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"pid": os.getpid(), "t": time.time()}, f)
        os.replace(tmp, CLAIM_PATH)
        self._claimed = True

    def __enter__(self) -> "DeviceLock":
        os.makedirs(_LOCK_DIR, exist_ok=True)
        if self.role in ("builder", "server") and priority_claim_active():
            raise DeviceBusy(
                f"driver priority claim at {CLAIM_PATH} is fresh "
                f"(age {_claim_age_s():.0f}s) — {self.role} stands down")
        if self.role == "driver":
            self._write_claim()
        if self.role == "server":
            # Shared mode: open append (never clobber an exclusive
            # holder's info line) and LOCK_SH so sibling servers
            # coexist; an exclusive bench lock refuses us.
            self._fd = open(LOCK_PATH, "a")
            try:
                fcntl.flock(self._fd, fcntl.LOCK_SH | fcntl.LOCK_NB)
            except OSError as e:
                self._fd.close()
                self._fd = None
                if e.errno not in (errno.EAGAIN, errno.EACCES):
                    raise
                raise DeviceBusy(
                    "device lock held exclusively by a bench — server "
                    "worker stands down") from None
            self._locked = True
            self.log("device lock acquired (server, shared)")
            return self
        self._fd = open(LOCK_PATH, "w")
        # Monotonic deadline arithmetic: an NTP step or suspend/resume
        # during the (up to 20-minute) wait must not make the driver
        # give up instantly or wait forever. Wall-clock time.time()
        # stays ONLY in the cross-process claim timestamps above, which
        # are compared against file mtimes on the same wall clock.
        deadline = time.monotonic() + self.wait_s
        while True:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                self._locked = True
                self._fd.truncate(0)
                self._fd.write(json.dumps(
                    {"pid": os.getpid(), "role": self.role}))
                self._fd.flush()
                self.log(f"device lock acquired ({self.role})")
                return self
            except OSError as e:
                if e.errno not in (errno.EAGAIN, errno.EACCES):
                    raise
            if self.role == "builder":
                self._fd.close()
                self._fd = None
                raise DeviceBusy("device lock held by another bench — "
                                 "builder stands down")
            if time.monotonic() >= deadline:
                self.log(f"WARNING: device lock still held after "
                         f"{self.wait_s:.0f}s wait — proceeding WITHOUT "
                         "it (advisory); expect contention in timings")
                return self
            self._write_claim()  # refresh mtime: builders keep yielding
            time.sleep(10.0)

    @property
    def acquired(self) -> bool:
        """True iff the flock is actually held (a driver past its
        advisory wait proceeds with acquired=False — callers that need
        exclusivity guarantees, e.g. shared-cache enablement, check
        this)."""
        return self._locked

    def __exit__(self, *exc) -> None:
        if self._fd is not None:
            if self._locked:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            self._fd.close()
            self._fd = None
        if self._claimed:
            try:
                # Remove only OUR claim: a second driver (anomalous but
                # possible) must not clear the surviving one's priority
                # on its way out.
                with open(CLAIM_PATH) as f:
                    owner = json.load(f).get("pid")
                if owner == os.getpid():
                    os.remove(CLAIM_PATH)
            except (OSError, ValueError):
                pass
