"""Input pipeline: epoch batching and ahead-of-time device prefetch.

The reference has no data loading at all (its only dataset loop is the
serial Python iteration of /root/reference/data_explore.py:12-15). On
TPU the input pattern that matters is *overlap*: while the chip runs
step N, the host should already be shipping batch N+1, so dispatch
never waits on a host->device copy. These helpers are the standard JAX
recipe for that, shaped for this framework's (pose, shape, target)
arrays and composable with the mesh shardings in ``parallel``:

    from mano_hand_tpu.utils.data import batches, prefetch_to_device

    it = prefetch_to_device(
        batches({"pose": poses, "target": verts}, batch_size=256,
                shuffle=True, seed=0),
        size=2,                                  # batches in flight
        sharding=parallel.batch_sharding(mesh),  # optional: shard as shipped
    )
    for batch in it:
        state, loss = step(state, batch["target"])
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, Mapping, Optional

import jax
import numpy as np


def batches(
    arrays: Mapping[str, np.ndarray],
    batch_size: int,
    shuffle: bool = False,
    seed: int = 0,
    drop_remainder: bool = True,
    epochs: int = 1,
) -> Iterator[dict]:
    """Slice a dict of equal-leading-dim arrays into per-epoch batches.

    ``drop_remainder=True`` keeps every batch the same static shape — on
    TPU a ragged tail batch is a fresh XLA compile, which costs more
    than the dropped samples (pad upstream if every sample matters).
    ``epochs`` repeats with a fresh shuffle order each epoch (seeded:
    identical runs see identical order).
    """
    # Validate HERE, not in the generator body: a generator defers its
    # body to first next(), which would surface call-site mistakes deep
    # inside the consumer (e.g. mid-prefetch) instead of at the call.
    if not arrays:
        raise ValueError("batches() needs at least one array")
    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    n = len(next(iter(arrays.values())))
    for name, a in arrays.items():
        if len(a) != n:
            raise ValueError(
                f"leading dims disagree: {name} has {len(a)}, expected {n}")
    if n < batch_size and drop_remainder:
        raise ValueError(
            f"batch_size {batch_size} exceeds dataset size {n} and "
            "drop_remainder would yield nothing")

    def gen():
        rng = np.random.default_rng(seed)
        for _ in range(epochs):
            order = rng.permutation(n) if shuffle else None
            stop = n - batch_size + 1 if drop_remainder else n
            for lo in range(0, stop, batch_size):
                if order is None:
                    # Plain slices are views — no per-batch host copy on
                    # the sequential path.
                    yield {k: a[lo:lo + batch_size]
                           for k, a in arrays.items()}
                else:
                    idx = order[lo:lo + batch_size]
                    yield {k: a[idx] for k, a in arrays.items()}

    return gen()


def prefetch_to_device(
    iterator: Iterable,
    size: int = 2,
    sharding: Optional[jax.sharding.Sharding] = None,
) -> Iterator:
    """Keep ``size`` batches already ON DEVICE ahead of the consumer.

    ``jax.device_put`` is async (it returns before the copy completes),
    so enqueueing the next batches while the current step runs overlaps
    H2D transfer with compute — the chip never idles on input. With a
    ``sharding`` (e.g. ``parallel.batch_sharding(mesh)``) each batch
    lands already sharded across the mesh, so the consuming ``pjit``
    step starts without a layout change.

    PyTrees pass through ``jax.device_put`` whole, so dict batches from
    :func:`batches` keep their structure.
    """
    # Validate HERE, not in the generator body (the batches() pattern): a
    # generator defers its body to first next(), which would surface a
    # bad size deep inside the consumer instead of at the call.
    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")
    put = (lambda x: jax.device_put(x, sharding)) if sharding is not None \
        else jax.device_put

    def gen():
        queue: collections.deque = collections.deque()
        it = iter(iterator)
        try:
            while True:
                while len(queue) < size:
                    queue.append(put(next(it)))
                yield queue.popleft()
        except StopIteration:
            while queue:
                yield queue.popleft()

    return gen()
