"""Profiling and timing helpers (SURVEY.md §5 "tracing/profiling").

The reference has no instrumentation at all; these wrap the two tools that
matter on TPU: wall-timing with ``block_until_ready`` (async dispatch makes
naive timing meaningless) and the XLA profiler trace for xprof/tensorboard.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Optional

import jax
import numpy as np


class Timer:
    """Accumulating wall-clock timer.

    >>> t = Timer()
    >>> with t:
    ...     work()
    >>> t.total, t.count, t.mean
    """

    def __init__(self) -> None:
        self.total = 0.0
        self.count = 0
        self._t0: Optional[float] = None

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.total += time.perf_counter() - self._t0
        self.count += 1
        self._t0 = None

    @property
    def mean(self) -> float:
        return self.total / max(self.count, 1)


def time_jax_fn(
    fn: Callable, *args, iters: int = 10, warmup: int = 2
) -> dict:
    """Time a JAX callable: block_until_ready per call, median over iters.

    Returns {"median_s", "min_s", "mean_s", "iters"}.

    Caveat: on remote-tunneled devices (e.g. the axon TPU platform)
    ``block_until_ready`` can return at enqueue rather than completion, and
    the first device->host readback adds a fixed per-dispatch sync cost.
    There, use bench.py's ``slope_time`` pattern instead: loop the workload
    inside one jitted program and difference two loop counts so fixed
    overheads cancel. This helper is accurate on directly-attached devices.
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        samples.append(time.perf_counter() - t0)
    return {
        "median_s": float(np.median(samples)),
        "min_s": float(np.min(samples)),
        "mean_s": float(np.mean(samples)),
        "iters": iters,
    }


@contextlib.contextmanager
def xla_trace(log_dir: str):
    """Capture an XLA profiler trace viewable in xprof/tensorboard."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
