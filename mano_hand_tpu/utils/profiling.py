"""Profiling and timing helpers (SURVEY.md §5 "tracing/profiling").

The reference has no instrumentation at all; these wrap the two tools that
matter on TPU: wall-timing with ``block_until_ready`` (async dispatch makes
naive timing meaningless) and the XLA profiler trace for xprof/tensorboard.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, Optional

import jax
import numpy as np


class Timer:
    """Accumulating wall-clock timer.

    >>> t = Timer()
    >>> with t:
    ...     work()
    >>> t.total, t.count, t.mean
    """

    def __init__(self) -> None:
        self.total = 0.0
        self.count = 0
        self._t0: Optional[float] = None

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.total += time.perf_counter() - self._t0
        self.count += 1
        self._t0 = None

    @property
    def mean(self) -> float:
        return self.total / max(self.count, 1)


def time_jax_fn(
    fn: Callable, *args, iters: int = 10, warmup: int = 2
) -> dict:
    """Time a JAX callable: block_until_ready per call, median over iters.

    Returns {"median_s", "min_s", "mean_s", "iters"}.

    Caveat: on remote-tunneled devices (e.g. the axon TPU platform)
    ``block_until_ready`` can return at enqueue rather than completion, and
    the first device->host readback adds a fixed per-dispatch sync cost.
    There, use bench.py's ``slope_time`` pattern instead: loop the workload
    inside one jitted program and difference two loop counts so fixed
    overheads cancel. This helper is accurate on directly-attached devices.
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        samples.append(time.perf_counter() - t0)
    return {
        "median_s": float(np.median(samples)),
        "min_s": float(np.min(samples)),
        "mean_s": float(np.mean(samples)),
        "iters": iters,
    }


@contextlib.contextmanager
def xla_trace(log_dir: str, tracer=None):
    """Capture an XLA profiler trace viewable in xprof/tensorboard.

    ``tracer`` (an ``obs.Tracer``, PR 8) additionally drops the engine
    host-span timeline as ``<log_dir>/engine.trace.json`` when the
    capture closes, so ``scripts/trace_report.py <log_dir>`` reads the
    host and device halves of the SAME window as one merged report —
    the unified-timeline entry point the roofline work drives.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        if tracer is not None:
            # Best-effort inside a finally: a raise here would MASK an
            # in-body exception, and a failed co-export must not cost
            # the XLA capture that already landed.
            try:
                import json
                from pathlib import Path

                path = Path(log_dir) / "engine.trace.json"
                path.write_text(json.dumps(tracer.chrome_trace()))
            except Exception as e:  # noqa: BLE001 — degrade, not crash
                import warnings

                warnings.warn(
                    f"engine-trace co-export into {log_dir} failed "
                    f"({type(e).__name__}: {e}); the XLA capture is "
                    "unaffected")


# Per-bucket latency samples are bounded so a long-lived server cannot
# grow memory with traffic; 8192 samples give stable p99 estimates.
_LATENCY_RESERVOIR = 8192


class ServingCounters:
    """Observability for the bucketed serving paths (serving/engine.py,
    the bucketed fit wrappers, MANOModel.forward_bucketed).

    The load-bearing counter is ``compiles``: it increments ONLY when a
    bucket executable is built by tracing + compiling from scratch, so
    "zero recompiles on steady-state traffic" is a testable number, not
    a hope. ``aot_loads`` counts executables revived from a persistent
    artifact instead (a cold process hitting a warm on-disk bucket).
    Padding waste and queue depth quantify the bucket policy itself;
    per-bucket latency quantiles quantify what a caller actually waits.

    Thread-safe: the engine's dispatcher thread and submitters both
    write here.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.compiles = 0          # fresh trace+compile events (cache misses)
        self.aot_loads = 0         # executables revived from disk artifacts
        # Crash-safe restart telemetry (PR 6): a damaged/mismatched AOT
        # artifact or lattice entry DEGRADES to a counted recompile —
        # this is the count (never a crash, never silently served);
        # ``subjects_restored`` counts SubjectTable rows revived from a
        # checkpoint without re-running the shape-stage bake.
        self.aot_load_failures = 0
        self.subjects_restored = 0
        self.dispatches = 0        # batches sent to the device
        self.rows_live = 0         # real request rows dispatched
        self.rows_padded = 0       # pad rows dispatched alongside them
        self.queue_depth_peak = 0  # max pending requests seen at coalesce
        self.specializations = 0   # shape-stage bakes (subject-cache misses)
        self.shaped_hits = 0       # subject-cache hits (bake reused)
        # Cross-subject coalescing telemetry (PR 4): the per-dispatch
        # request count and subject mix quantify what the gathered
        # dispatch actually merged; overflow/eviction/growth events are
        # the capacity-management audit trail.
        self.requests_dispatched = 0   # requests merged across dispatches
        self.mixed_subject_batches = 0  # dispatches mixing >= 2 subjects
        self.coalesce_overflows = 0    # requests parked: bucket overflow
        self.specializations_evicted = 0  # LRU table-slot evictions
        self.table_growths = 0         # subject-table capacity doublings
        # Fault-tolerance counters (runtime/, PR 3): the recovery
        # drill's done-criteria read these, so resilience is a set of
        # numbers, not a hope — same philosophy as ``compiles``.
        self.retries = 0           # supervised dispatch retry attempts
        self.faults_injected = 0   # chaos-plan faults fired (tests/drills)
        self.failovers = 0         # dispatches served by the CPU fallback
        self.deadline_kills = 0    # supervised calls abandoned at deadline
        # Overload counters (PR 5): bounded admission and per-request
        # deadlines make "survives too much traffic" a set of numbers —
        # sheds and expiries are the work NOT done (by design), the
        # backlog high-water is how close the bound came, and the
        # per-tier ledgers are the goodput criterion's raw material.
        self.shed = 0              # submits refused at admission
        self.expired = 0           # requests expired before/at delivery
        # Caller-initiated cancellation (PR 13): ``future.cancel()``
        # freed the admission slot before the deadline sweep would —
        # work the CALLER withdrew, distinct from shed (refused) and
        # expired (timed out).
        self.cancelled = 0
        self.backlog_peak = 0      # max outstanding requests seen at submit
        # Pipelined dispatch (PR 17): completions counts batches the
        # bounded completion stage resolved (0 = serial depth-1 or
        # lane mode), the peak is the stage's in-flight high-water
        # (launched-but-unresolved batches; bounded by
        # ``inflight_depth``), and presweeps counts batches the stage's
        # deadline re-check expired WHOLE without buying device time.
        self.pipeline_completions = 0
        self.pipeline_inflight_peak = 0
        self.pipeline_presweeps = 0
        # Tiered subject store (PR 16): per-tier resolutions — hot (a
        # batch's digest already table-resident), warm (host-RAM row
        # promoted), cold (disk page promoted), miss (no tier held the
        # row; a full re-bake ran) — plus the movement counters and the
        # damage counter (a cold page that failed verification and
        # DEGRADED to a counted re-bake, the PR-6 contract applied to
        # paging). The promotion-stall reservoir measures what the
        # install path actually waited on a promotion — near-zero when
        # the prefetch hid the transfer inside the coalesce window.
        self.subject_store_hot_hits = 0
        self.subject_store_warm_hits = 0
        self.subject_store_cold_hits = 0
        self.subject_store_misses = 0
        self.subject_store_prefetches = 0
        self.subject_store_promotions = 0
        self.subject_store_demotions_warm = 0
        self.subject_store_demotions_cold = 0
        self.subject_store_cold_damage = 0
        self.subject_store_resize_evictions = 0
        # Closed-loop control (PR 19): the controller's own health as
        # counters — ticks (decision sweeps run), actuations (knobs
        # actually moved; each one is also a traced runtime event with
        # before/after), reverts (crash/stop restorations to the
        # static defaults — nonzero in production means a controller
        # died and the engine degraded to hand-tuned behavior).
        self.control_ticks = 0
        self.control_actuations = 0
        self.control_reverts = 0
        # Shard rebalance on lane loss (PR 20): one ``rebalances`` event
        # per dead shard adopted by the survivors (idempotent — a second
        # trigger for the same shard is a no-op and not counted);
        # ``rows`` counts the hot rows the adopters pulled through the
        # warm tier at adoption time.  Steady recompiles stay 0 by
        # construction ((bucket, cap) keying unchanged), so these two
        # are the whole audit trail.
        self.shard_rebalances = 0
        self.shard_rebalance_rows = 0
        self._promotion_stalls: list = []   # seconds; bounded ring
        self._promotion_writes = 0
        self.tier_submitted: Dict[int, int] = {}   # tier -> offered
        self.tier_served: Dict[int, int] = {}      # tier -> results delivered
        self.tier_shed: Dict[int, int] = {}        # tier -> admission sheds
        self.tier_expired: Dict[int, int] = {}     # tier -> expiries
        self.tier_cancelled: Dict[int, int] = {}   # tier -> cancellations
        self._latencies: Dict[int, list] = {}  # bucket -> [seconds]
        self._latency_writes: Dict[int, int] = {}  # per-bucket write cursor

    # -- writers ----------------------------------------------------------
    def count_compile(self, n: int = 1) -> None:
        with self._lock:
            self.compiles += n

    def count_aot_load(self, n: int = 1) -> None:
        with self._lock:
            self.aot_loads += n

    def count_aot_load_failure(self, n: int = 1) -> None:
        """One AOT artifact / lattice entry that could NOT be served
        (truncated, corrupted, checksum or params_digest mismatch) and
        fell back to a jit compile — the structured-degradation counter
        the cold-start drill's corruption legs assert on."""
        with self._lock:
            self.aot_load_failures += n

    def count_restore(self, n: int = 1) -> None:
        """One subject revived from a SubjectTable checkpoint (row
        written from persisted bytes; no shape-stage bake ran)."""
        with self._lock:
            self.subjects_restored += n

    def count_specialize(self, hit: bool) -> None:
        """One per-subject specialization lookup (serving/engine.py): a
        miss ran the shape-stage bake (a DATA computation — not a
        compile; ``compiles`` stays the zero-recompile criterion's
        counter), a hit reused the cached ShapedHand."""
        with self._lock:
            if hit:
                self.shaped_hits += 1
            else:
                self.specializations += 1

    def count_retry(self, n: int = 1) -> None:
        with self._lock:
            self.retries += n

    def count_fault(self, n: int = 1) -> None:
        with self._lock:
            self.faults_injected += n

    def count_failover(self, n: int = 1) -> None:
        with self._lock:
            self.failovers += n

    def count_deadline_kill(self, n: int = 1) -> None:
        with self._lock:
            self.deadline_kills += n

    def count_tier_submit(self, tier: int = 0) -> None:
        """One submit() OFFERED in this priority tier — counted before
        admission, so shed + expired + served + in-flight sums back to
        it (the goodput denominator)."""
        with self._lock:
            self.tier_submitted[tier] = self.tier_submitted.get(tier, 0) + 1

    def count_served(self, tier: int = 0) -> None:
        """One request resolved with a RESULT (the goodput numerator —
        a request resolved to shed/expired/error is not served)."""
        with self._lock:
            self.tier_served[tier] = self.tier_served.get(tier, 0) + 1

    def count_shed(self, tier: int = 0) -> None:
        """One submit refused at admission (bounded queue / tier quota).
        The decision is O(µs) bookkeeping — no device dispatch, which
        the overload drill's shed probe verifies with ``dispatches``."""
        with self._lock:
            self.shed += 1
            self.tier_shed[tier] = self.tier_shed.get(tier, 0) + 1

    def count_expired(self, tier: int = 0) -> None:
        """One request whose deadline passed before a result could be
        delivered — swept pre-dispatch (no chip time) or expired at
        readback (a stale pose is worthless; see serving/engine.py)."""
        with self._lock:
            self.expired += 1
            self.tier_expired[tier] = self.tier_expired.get(tier, 0) + 1

    def count_cancelled(self, tier: int = 0) -> None:
        """One request whose caller called ``future.cancel()`` before a
        result landed: the admission slot is freed immediately and the
        span closes as terminal kind ``cancelled`` — never dispatched
        when the sweep catches it queued, result discarded when it was
        already in flight (serving/engine.py, PR 13)."""
        with self._lock:
            self.cancelled += 1
            self.tier_cancelled[tier] = self.tier_cancelled.get(tier, 0) + 1

    def observe_backlog(self, outstanding: int) -> None:
        with self._lock:
            if outstanding > self.backlog_peak:
                self.backlog_peak = outstanding

    # -- pipelined dispatch (PR 17) --------------------------------------
    def count_pipeline_completion(self, n: int = 1) -> None:
        """One launched batch resolved by the completion stage (its
        readback/deliver ran on the stage worker, overlapped with the
        dispatcher's next assembly)."""
        with self._lock:
            self.pipeline_completions += n

    def observe_pipeline_inflight(self, inflight: int) -> None:
        """Stage occupancy at a submit (queued + resolving), this batch
        included — the high-water says how much of ``inflight_depth``
        the traffic actually used."""
        with self._lock:
            if inflight > self.pipeline_inflight_peak:
                self.pipeline_inflight_peak = inflight

    def count_pipeline_presweep(self, n: int = 1) -> None:
        """One batch the stage's deadline re-check expired WHOLE before
        its dispatch — stage queue time ate the last deadline, and no
        device time was spent on a result nobody would read."""
        with self._lock:
            self.pipeline_presweeps += n

    def count_dispatch(self, bucket: int, live_rows: int,
                       requests: int = 1, subjects: int = 1) -> None:
        """One batch sent to the device. ``requests`` is how many submit()
        calls the batch coalesced (the coalesce-width numerator);
        ``subjects`` how many DISTINCT specialized subjects rode in it
        (>= 2 marks a mixed-subject gathered dispatch — the PR-4
        first-class case). Single-request callers (the bucketed fit
        wrappers, forward_bucketed) keep the defaults."""
        with self._lock:
            self.dispatches += 1
            self.rows_live += live_rows
            self.rows_padded += bucket - live_rows
            self.requests_dispatched += requests
            if subjects > 1:
                self.mixed_subject_batches += 1

    def count_overflow(self, n: int = 1) -> None:
        """A request parked by _coalesce because admitting it would
        overflow the largest bucket. Genuine capacity overflow ONLY:
        the other park reasons (a path-kind mismatch, or a batch
        already spanning max_subjects distinct subjects) are not
        capacity events and are not counted here."""
        with self._lock:
            self.coalesce_overflows += n

    def count_evict(self, n: int = 1) -> None:
        """One LRU eviction from the subject table (the slot is reused;
        compiled programs are untouched — the table is a runtime arg)."""
        with self._lock:
            self.specializations_evicted += n

    def count_table_growth(self, n: int = 1) -> None:
        with self._lock:
            self.table_growths += n

    # -- tiered subject store (PR 16) ------------------------------------
    def count_store_hot(self, n: int = 1) -> None:
        """N batch digests resolved straight from the device table."""
        with self._lock:
            self.subject_store_hot_hits += n

    def count_store_warm(self, n: int = 1) -> None:
        """One install served from a host-RAM warm row (no re-bake)."""
        with self._lock:
            self.subject_store_warm_hits += n

    def count_store_cold(self, n: int = 1) -> None:
        """One install served from a verified cold page (no re-bake)."""
        with self._lock:
            self.subject_store_cold_hits += n

    def count_store_miss(self, n: int = 1) -> None:
        """One install NO tier could serve: the shape stage re-ran.
        Counted, never errored — a miss is a latency event by design."""
        with self._lock:
            self.subject_store_misses += n

    def count_store_prefetch(self, n: int = 1) -> None:
        """One async host→device promotion started ahead of dispatch
        (coalesce-admit / open_stream)."""
        with self._lock:
            self.subject_store_prefetches += n

    def count_store_promotion(self, n: int = 1) -> None:
        """One row made device-resident from a lower tier."""
        with self._lock:
            self.subject_store_promotions += n

    def count_store_demotion_warm(self, n: int = 1) -> None:
        """One evicted row captured into the warm tier."""
        with self._lock:
            self.subject_store_demotions_warm += n

    def count_store_demotion_cold(self, n: int = 1) -> None:
        """One warm-LRU victim paged to the cold tier."""
        with self._lock:
            self.subject_store_demotions_cold += n

    def count_store_cold_damage(self, n: int = 1) -> None:
        """One cold page that failed verification (missing, unreadable,
        or content/digest mismatch) and degraded to a counted re-bake."""
        with self._lock:
            self.subject_store_cold_damage += n

    def count_store_resize_eviction(self, n: int = 1) -> None:
        """One warm row evicted (LRU-first) by a RUNTIME warm-capacity
        shrink (``SubjectStore.resize_warm``, PR 18) — counted, never
        an error; a paged victim re-enters through the cold tier."""
        with self._lock:
            self.subject_store_resize_evictions += n

    def count_control_tick(self, n: int = 1) -> None:
        """One controller decision sweep (serving/control.py) — ran,
        whether or not anything moved."""
        with self._lock:
            self.control_ticks += n

    def count_control_actuation(self, n: int = 1) -> None:
        """One knob the controller actually moved (quota, coalesce
        base, bucket bias, Retry-After, warm capacity); the traced
        ``control`` runtime event carries the before/after."""
        with self._lock:
            self.control_actuations += n

    def count_control_revert(self, n: int = 1) -> None:
        """One restoration to the static defaults (controller crash or
        reverting stop) — the degrade-to-hand-tuned event."""
        with self._lock:
            self.control_reverts += n

    def count_shard_rebalance(self, rows: int = 0) -> None:
        """One dead shard's subjects adopted by the surviving lanes
        (PR 20): ``rows`` is how many engine-hot rows were proactively
        installed into the adopters at adoption time; everything else
        re-enters lazily through the warm tier on first dispatch."""
        with self._lock:
            self.shard_rebalances += 1
            self.shard_rebalance_rows += int(rows)

    def record_promotion_stall(self, seconds: float) -> None:
        """What one install actually WAITED on a tier promotion (the
        residual after any prefetch overlap) — same bounded-ring policy
        as the request-latency reservoir."""
        with self._lock:
            if len(self._promotion_stalls) >= _LATENCY_RESERVOIR:
                self._promotion_stalls[
                    self._promotion_writes % _LATENCY_RESERVOIR] = seconds
            else:
                self._promotion_stalls.append(seconds)
            self._promotion_writes += 1

    def observe_queue_depth(self, depth: int) -> None:
        with self._lock:
            if depth > self.queue_depth_peak:
                self.queue_depth_peak = depth

    def record_latency(self, bucket: int, seconds: float) -> None:
        with self._lock:
            bucket = int(bucket)
            samples = self._latencies.setdefault(bucket, [])
            if len(samples) >= _LATENCY_RESERVOIR:
                # Ring overwrite on a PER-SAMPLE cursor: keying the slot
                # off the dispatch counter would make every request of a
                # batch land in one slot (only the last survives — a
                # systematic low bias on p99), and adjacent batches
                # would keep re-hitting near-identical slots.
                cursor = self._latency_writes.get(bucket, 0)
                samples[cursor % _LATENCY_RESERVOIR] = seconds
            self._latency_writes[bucket] = \
                self._latency_writes.get(bucket, 0) + 1
            if len(samples) < _LATENCY_RESERVOIR:
                samples.append(seconds)

    # -- readers ----------------------------------------------------------
    # The derived-metric formulas live in these static helpers so the
    # properties (which take the lock themselves) and snapshot() (which
    # computes them INSIDE its single lock hold) can never drift apart.
    @staticmethod
    def _waste_ratio(rows_live: int, rows_padded: int) -> float:
        total = rows_live + rows_padded
        return rows_padded / total if total else 0.0

    @staticmethod
    def _width_mean(requests_dispatched: int, dispatches: int) -> float:
        return requests_dispatched / dispatches if dispatches else 0.0

    @staticmethod
    def _quantiles(items: Dict[int, list]) -> dict:
        out = {}
        for b, s in sorted(items.items()):
            if not s:
                continue
            arr = np.asarray(s)
            out[b] = {
                "p50_ms": float(np.percentile(arr, 50) * 1e3),
                "p99_ms": float(np.percentile(arr, 99) * 1e3),
                "n": int(arr.size),
            }
        return out

    @property
    def padding_waste(self) -> float:
        """Fraction of dispatched rows that were padding, in [0, 1)."""
        with self._lock:
            return self._waste_ratio(self.rows_live, self.rows_padded)

    @property
    def coalesce_width_mean(self) -> float:
        """Mean submit() requests merged per dispatch (1.0 = the
        degenerate single-request batches PR 4 exists to fix)."""
        with self._lock:
            return self._width_mean(self.requests_dispatched,
                                    self.dispatches)

    def latency_quantiles(self) -> dict:
        """{bucket: {"p50_ms", "p99_ms", "n"}} over the recorded samples."""
        with self._lock:
            items = {b: list(s) for b, s in self._latencies.items()}
        return self._quantiles(items)

    def snapshot(self) -> dict:
        """JSON-able state dump (the bench/CLI serving metrics block).

        ONE lock-held copy: every raw counter, the derived ratios, and
        the latency samples are read inside a single acquisition, so a
        snapshot taken mid-overload (concurrent submitters hammering
        the shed/dispatch counters) is internally consistent — its
        ``padding_waste`` is exactly ``rows_padded / (rows_live +
        rows_padded)`` of the SAME dict, never a torn tuple where the
        ratio reflects a later write than the integers beside it (the
        PR-5 drill telemetry depends on this; pinned in tests)."""
        with self._lock:
            base = {
                "compiles": self.compiles,
                "aot_loads": self.aot_loads,
                "aot_load_failures": self.aot_load_failures,
                "subjects_restored": self.subjects_restored,
                "dispatches": self.dispatches,
                "rows_live": self.rows_live,
                "rows_padded": self.rows_padded,
                "queue_depth_peak": self.queue_depth_peak,
                "specializations": self.specializations,
                "shaped_hits": self.shaped_hits,
                "requests_dispatched": self.requests_dispatched,
                "mixed_subject_batches": self.mixed_subject_batches,
                "coalesce_overflows": self.coalesce_overflows,
                "specializations_evicted": self.specializations_evicted,
                "table_growths": self.table_growths,
                "retries": self.retries,
                "faults_injected": self.faults_injected,
                "failovers": self.failovers,
                "deadline_kills": self.deadline_kills,
                "shed": self.shed,
                "expired": self.expired,
                "cancelled": self.cancelled,
                "backlog_peak": self.backlog_peak,
                "pipeline_completions": self.pipeline_completions,
                "pipeline_inflight_peak": self.pipeline_inflight_peak,
                "pipeline_presweeps": self.pipeline_presweeps,
                "subject_store_hot_hits": self.subject_store_hot_hits,
                "subject_store_warm_hits": self.subject_store_warm_hits,
                "subject_store_cold_hits": self.subject_store_cold_hits,
                "subject_store_misses": self.subject_store_misses,
                "subject_store_prefetches": self.subject_store_prefetches,
                "subject_store_promotions": self.subject_store_promotions,
                "subject_store_demotions_warm":
                    self.subject_store_demotions_warm,
                "subject_store_demotions_cold":
                    self.subject_store_demotions_cold,
                "subject_store_cold_damage": self.subject_store_cold_damage,
                "subject_store_resize_evictions":
                    self.subject_store_resize_evictions,
                "control_ticks": self.control_ticks,
                "control_actuations": self.control_actuations,
                "control_reverts": self.control_reverts,
                "shard_rebalances": self.shard_rebalances,
                "shard_rebalance_rows": self.shard_rebalance_rows,
            }
            base["padding_waste"] = round(
                self._waste_ratio(self.rows_live, self.rows_padded), 4)
            base["coalesce_width_mean"] = round(
                self._width_mean(self.requests_dispatched,
                                 self.dispatches), 3)
            tiers = sorted(set(self.tier_submitted) | set(self.tier_served)
                           | set(self.tier_shed) | set(self.tier_expired)
                           | set(self.tier_cancelled))
            base["tiers"] = {
                str(t): {
                    "submitted": self.tier_submitted.get(t, 0),
                    "served": self.tier_served.get(t, 0),
                    "shed": self.tier_shed.get(t, 0),
                    "expired": self.tier_expired.get(t, 0),
                    "cancelled": self.tier_cancelled.get(t, 0),
                }
                for t in tiers
            }
            items = {b: list(s) for b, s in self._latencies.items()}
            stalls = list(self._promotion_stalls)
        # Percentile math alone happens outside the lock (pure reads of
        # the copied sample lists; submitters never wait on numpy).
        base["latency_by_bucket"] = self._quantiles(items)
        base["subject_store_promotion_ms"] = self._quantiles(
            {0: stalls}).get(0, {"p50_ms": 0.0, "p99_ms": 0.0, "n": 0})
        return base
