from mano_hand_tpu.utils.config import ManoConfig
from mano_hand_tpu.utils.profiling import Timer, time_jax_fn, xla_trace

__all__ = ["ManoConfig", "Timer", "time_jax_fn", "xla_trace"]
