from mano_hand_tpu.utils.config import ManoConfig
from mano_hand_tpu.utils.data import batches, prefetch_to_device
from mano_hand_tpu.utils.profiling import Timer, time_jax_fn, xla_trace

__all__ = ["ManoConfig", "Timer", "batches", "prefetch_to_device",
           "time_jax_fn", "xla_trace"]
