"""Runtime configuration.

The reference hardcodes every constant (asset paths at dump_model.py:48-49,
demo params at mano_np.py:209-216, n_joints/n_shape at mano_np.py:35-36);
SURVEY.md §5 calls for a small config object instead. One dataclass, JSON
round-trippable, that can build the model objects it describes.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Optional, Union

PathLike = Union[str, Path]


@dataclasses.dataclass
class ManoConfig:
    asset: str = "synthetic"        # path to .npz/.pkl, or "synthetic"
    side: Optional[str] = None      # left | right | None (infer)
    backend: str = "jax"            # np | jax
    dtype: str = "float32"          # compute dtype for the jax path
    precision: str = "high"         # high | highest | default — bf16 passes
                                    # per f32 matmul (3/6/1); "high" is the
                                    # library default (ops/common.py)
    mesh_data: int = 1              # data-parallel mesh extent
    mesh_model: int = 1             # tensor-parallel mesh extent
    chunk_size: int = 8192          # huge-batch chunking
    seed: int = 0                   # synthetic-asset seed

    # ----------------------------------------------------------- build
    def load_params(self):
        import numpy as np

        from mano_hand_tpu.assets import load_model, synthetic_params

        if self.asset == "synthetic":
            params = synthetic_params(
                seed=self.seed, side=self.side or "right"
            )
        else:
            params = load_model(self.asset, side=self.side)
        if self.backend == "jax":
            return params.astype(np.dtype(self.dtype))
        return params

    def build_model(self):
        from mano_hand_tpu.models.layer import MANOModel

        return MANOModel(self.load_params(), backend=self.backend)

    def build_mesh(self):
        from mano_hand_tpu.parallel import make_mesh

        return make_mesh(data=self.mesh_data, model=self.mesh_model)

    def jax_precision(self):
        import jax

        return {
            "high": jax.lax.Precision.HIGH,
            "highest": jax.lax.Precision.HIGHEST,
            "default": jax.lax.Precision.DEFAULT,
        }[self.precision]

    # ------------------------------------------------------------ json
    def to_json(self, path: Optional[PathLike] = None) -> str:
        text = json.dumps(dataclasses.asdict(self), indent=2)
        if path is not None:
            Path(path).write_text(text)
        return text

    @classmethod
    def from_json(cls, source: Union[str, PathLike]) -> "ManoConfig":
        p = Path(str(source))
        text = p.read_text() if p.exists() else str(source)
        data = json.loads(text)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        return cls(**data)
